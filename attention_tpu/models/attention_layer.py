"""Flax attention modules built on the framework's kernels.

The reference is a bare kernel with no model around it; these modules are
the "model family" surface a framework user needs: a grouped-query
self-attention layer (BASELINE config 5: 32 Q heads / 4 KV heads) whose
inner op is selectable between the differentiable fused flash path and
the auto-SPMD XLA path.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from attention_tpu.ops.decode import flash_decode
from attention_tpu.ops.paged import PagedKV, paged_append, paged_flash_decode
from attention_tpu.ops.ragged_paged import (
    RaggedPagedStep,
    ragged_paged_append,
    ragged_paged_attention,
)
from attention_tpu.ops.flash import flash_attention
from attention_tpu.ops.flash_vjp import flash_attention_diff
from attention_tpu.ops.quant import (
    QuantizedKV,
    flash_decode_quantized,
    quantize_kv,
    sink_read_rotation,
    update_quantized_kv,
)
from attention_tpu.ops.reference import attention_xla
from attention_tpu.ops.rope import apply_rope


class KVCache(NamedTuple):
    """Per-layer decode cache: K/V (B, Hkv, N, dh) + valid length.

    ``length`` is a traced int32 scalar (uniform across the batch —
    prefill is batched on equal-length prompts; `flash_decode` itself
    also accepts per-sequence (B,) lengths for ragged serving).
    """

    k: jax.Array
    v: jax.Array
    length: jax.Array

    @classmethod
    def create(cls, batch: int, num_kv_heads: int, capacity: int,
               head_dim: int, dtype=jnp.bfloat16) -> "KVCache":
        shape = (batch, num_kv_heads, capacity, head_dim)
        return cls(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            length=jnp.zeros((), jnp.int32),
        )

    def quantize(self) -> "QuantKVCache":
        """One-shot int8 conversion (after prefill): 0.63x the HBM for
        the rest of the decode loop; the bf16 arrays can then be freed."""
        return QuantKVCache(kv=quantize_kv(self.k, self.v),
                            length=self.length)


class QuantKVCache(NamedTuple):
    """int8 decode cache: `QuantizedKV` (int8 values + scales) + valid length.

    Decode-only (S == 1 steps, ``impl='flash'``): the serving flow is
    bf16 prefill -> :meth:`KVCache.quantize` -> int8 decode loop.
    """

    kv: QuantizedKV
    length: jax.Array


class RollingKVCache(NamedTuple):
    """Ring-buffer cache for sliding-window models (optionally with
    StreamingLLM attention sinks): memory is bounded by sinks + window,
    NOT the sequence length, however long generation runs.

    Slot layout: pinned sink slots ``[0, sinks)`` hold the first
    ``sinks`` tokens forever; ring slots ``[sinks, sinks + window)``
    hold the last ``window`` tokens in wrapped order (token t sits at
    ``sinks + (t - sinks) % window`` once past the sinks).  Capacity
    rounds ``sinks + window`` up to the decode kernel's 128-row
    granule; tail slots are never written and reads mask by the valid
    count.  Correctness rests on softmax being permutation-invariant
    over KV rows.  ``length`` counts total tokens seen.
    """

    k: jax.Array  # (B, Hkv, C, dh)
    v: jax.Array
    length: jax.Array

    @classmethod
    def create(cls, batch: int, num_kv_heads: int, window: int,
               head_dim: int, dtype=jnp.bfloat16,
               sinks: int = 0) -> "RollingKVCache":
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        cap = cls.capacity_for(window, sinks)
        shape = (batch, num_kv_heads, cap, head_dim)
        return cls(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            length=jnp.zeros((), jnp.int32),
        )

    @property
    def capacity(self) -> int:
        return self.k.shape[2]

    @staticmethod
    def capacity_for(window: int, sinks: int = 0) -> int:
        """sinks pinned slots + window ring slots, rounded up to the
        decode kernel's 128-row granule (tail slots stay unused — reads
        mask by the valid count, which never exceeds sinks + window)."""
        return -(-(window + sinks) // 128) * 128


class RaggedKVCache(NamedTuple):
    """Decode cache with PER-SEQUENCE valid lengths (B,) — the ragged
    serving cache: one batch mixes prompts of different lengths with no
    host-side bucketing.

    Built from a padded-prompt prefill on the scalar `KVCache` (causal
    masking keeps pad keys invisible to valid queries), then decode
    steps write each sequence's new row at its own ``lengths[b]`` and
    attend over its own valid prefix (`flash_decode` takes (B,) lens
    natively).  Pad rows are progressively overwritten by decode.
    """

    k: jax.Array  # (B, Hkv, N, dh)
    v: jax.Array
    lengths: jax.Array  # (B,) int32 valid rows per sequence

    @property
    def length(self):
        """Per-sequence lengths (named like the other caches so shared
        code — RoPE offsets — treats caches uniformly)."""
        return self.lengths

    @classmethod
    def from_prefill(cls, cache: KVCache, lengths) -> "RaggedKVCache":
        return cls(cache.k, cache.v, jnp.asarray(lengths, jnp.int32))


def _xla_mha(q, k, v, *, causal, window=None, softcap=None, sinks=0):
    """Dense attention on (B, H, S, dh) with GQA head repeat; differentiable
    and auto-partitionable by XLA under pjit shardings."""
    if not causal:
        hq, hkv = q.shape[1], k.shape[1]
        if hq != hkv:
            k = jnp.repeat(k, hq // hkv, axis=1)
            v = jnp.repeat(v, hq // hkv, axis=1)
        return attention_xla(q, k, v, softcap=softcap)
    # causal = the start=0, fully-valid instance of the cached mask
    return _xla_cached_attention(q, k, v, start=0, new_len=k.shape[2],
                                 causal=True, window=window,
                                 softcap=softcap, sinks=sinks)


def _flash_mha(q, k, v, *, causal, window=None, softcap=None, sinks=0):
    # max_mode="bound": the library's fastest exact kernel (same output
    # and lse as the online recurrence — tests/test_ops.py pins it;
    # 0.92-0.97 vs 0.78-0.82 MXU util, scripts/max_mode_exp.py)
    return flash_attention_diff(q, k, v, causal=causal, window=window,
                                softcap=softcap, sinks=sinks or None,
                                max_mode="bound")


def _sink_read_keys(kc, new_total, window, sinks, theta):
    """StreamingLLM positional convention for RoPE'd sink keys, applied
    at read time.

    Keys are cached already-rotated at their absolute positions, which
    is exact for window keys (query-to-key distance stays < window) but
    lets the query-to-SINK distance grow without bound once the stream
    passes ``sinks + window`` — outside the rotation range the model was
    trained on.  The paper assigns positions *within the cache* instead.
    Equivalent formulation used here: shift only the ``sinks`` pinned
    keys forward by ``delta = max(new_total - (window + sinks), 0)``
    (RoPE rotations compose additively), which pins every sink at a
    constant relative distance just before the window start, while the
    query and window keys keep their absolute rotations.  Cost per step:
    a rope over ``sinks`` rows; the stored cache stays absolute.
    """
    delta = jnp.maximum(jnp.asarray(new_total, jnp.int32) - (window + sinks),
                        0)
    if delta.ndim:  # ragged: per-sequence (B,) totals -> (B, 1, 1) pos
        delta = delta[:, None, None]
    rot = apply_rope(kc[:, :, :sinks], delta, theta).astype(kc.dtype)
    # in-place-aliasable write of just the sink rows (a concatenate
    # would copy the whole capacity-sized cache every decode step)
    zero = jnp.zeros((), jnp.int32)
    return jax.lax.dynamic_update_slice(kc, rot, (zero, zero, zero, zero))


def _xla_cached_attention(q, kc, vc, *, start, new_len, causal,
                          window=None, softcap=None, sinks=0):
    """Dense cached attention over (B, H, S, dh) vs full-capacity caches
    (B, Hkv, N, dh), masked to the valid prefix.  Pure einsums — XLA
    auto-partitions it under pjit shardings, the serving analog of
    `_xla_mha`."""
    hq, hkv = q.shape[1], kc.shape[1]
    if hq != hkv:
        kc = jnp.repeat(kc, hq // hkv, axis=1)
        vc = jnp.repeat(vc, hq // hkv, axis=1)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhmd,bhnd->bhmn", q, kc,
                   preferred_element_type=jnp.float32) * scale
    if softcap is not None:
        s = softcap * jnp.tanh(s / softcap)
    col = jnp.arange(kc.shape[2])[None, :]
    mask = col < new_len
    if causal:
        row = jnp.arange(q.shape[2])[:, None]
        mask = jnp.logical_and(mask, col <= row + start)
        if window is not None:
            win = col >= row + start - (window - 1)
            if sinks:
                win = jnp.logical_or(win, col < sinks)
            mask = jnp.logical_and(mask, win)
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(vc.dtype)
    return jnp.einsum("bhmn,bhnd->bhmd", p, vc)


ATTN_IMPLS: dict[str, Callable] = {"xla": _xla_mha, "flash": _flash_mha}


class GQASelfAttention(nn.Module):
    """Grouped-query self-attention: (B, S, D) -> (B, S, D).

    ``impl='flash'`` uses the fused Pallas kernel (custom VJP);
    ``impl='xla'`` uses dense einsums that XLA partitions automatically
    under dp/sp/tp shardings (the training default on a mesh).
    """

    num_q_heads: int
    num_kv_heads: int
    head_dim: int
    impl: str = "flash"
    causal: bool = True
    dtype: jnp.dtype = jnp.bfloat16
    window: int | None = None  # sliding-window attention (requires causal)
    attn_sinks: int = 0  # StreamingLLM sinks: first k positions stay attendable
    rope: bool = False  # rotary position embeddings on Q/K
    rope_theta: float = 10000.0
    softcap: float | None = None  # logit soft-capping (Gemma-2 style)
    # Context parallelism: when set (training under a mesh whose
    # ``cp_axis`` shards the sequence), batch attention runs a
    # differentiable CP composition — the Pallas flash custom VJP under
    # shard_map — instead of a single-device kernel call.  Requires
    # ``impl='flash'``; ``mesh`` must be the training mesh.
    # ``cp_impl``: "allgather" (`parallel.cp`, KV gathered per device —
    # the default training layout), "ring" (`parallel.ring.
    # ring_attention_diff`, O(n/R) KV memory in both passes — the
    # long-context composition), "zigzag" (the ring with llama-3
    # chunk interleaving: equal per-device work at every step of BOTH
    # passes for causal models), or "ulysses" (`parallel.ulysses`,
    # head/seq all-to-all — two collectives per pass, zero softmax
    # collectives; needs q heads and seq divisible by the cp mesh
    # size).  Decode/cached paths are unaffected.
    cp_axis: str | None = None
    cp_impl: str = "allgather"
    # ``tp_axis``: tensor-parallel SERVING — every cached-path kernel
    # call (decode on dense/rolling/ragged/int8/paged caches, chunked
    # prefill) runs head-sharded over this mesh axis via the
    # `parallel.serving` wrappers, while the projections around it stay
    # in ordinary jit for XLA's auto-SPMD to partition (the same
    # composition as cp_axis uses for training: auto-SPMD everywhere,
    # explicit shard_map only at the Pallas kernel).  Requires
    # ``impl='flash'`` and ``mesh``; the axis size must divide the KV
    # head count.
    tp_axis: str | None = None
    mesh: "jax.sharding.Mesh | None" = None

    @nn.compact
    def __call__(self, x: jax.Array,
                 cache: "KVCache | QuantKVCache | None" = None):
        if self.num_q_heads % self.num_kv_heads != 0:
            raise ValueError(
                f"q heads {self.num_q_heads} not a multiple of kv heads "
                f"{self.num_kv_heads}"
            )
        if self.cp_axis is not None:
            if self.impl != "flash":
                raise ValueError(
                    "cp_axis (context-parallel attention) runs the fused "
                    f"flash path; impl {self.impl!r} is not supported"
                )
            if self.mesh is None:
                raise ValueError("cp_axis requires mesh=")
        if self.tp_axis is not None:
            if self.impl != "flash":
                raise ValueError(
                    "tp_axis (head-sharded serving) runs the fused flash "
                    f"kernels; impl {self.impl!r} is not supported (the "
                    "'xla' impl already auto-partitions under jit)"
                )
            if self.mesh is None:
                raise ValueError("tp_axis requires mesh=")
            if self.tp_axis not in self.mesh.shape:
                raise ValueError(
                    f"tp_axis {self.tp_axis!r} is not an axis of the "
                    f"mesh {tuple(self.mesh.axis_names)}"
                )
            tp_size = self.mesh.shape[self.tp_axis]
            if self.num_kv_heads % tp_size:
                raise ValueError(
                    f"kv heads {self.num_kv_heads} not divisible by "
                    f"tp_axis {self.tp_axis!r} size {tp_size}"
                )
        dense = lambda name, heads: nn.DenseGeneral(  # noqa: E731
            features=(heads, self.head_dim),
            use_bias=False,
            dtype=self.dtype,
            name=name,
        )
        q = dense("q_proj", self.num_q_heads)(x)  # (B, S, Hq, dh)
        k = dense("k_proj", self.num_kv_heads)(x)
        v = dense("v_proj", self.num_kv_heads)(x)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))  # (B, H, S, dh)
        if self.rope:
            # rotate BEFORE caching: keys are stored already-rotated at
            # their absolute positions (scores depend only on relative
            # position, so cached history never needs re-rotation)
            if isinstance(cache, RaggedPagedStep):
                # packed step: every token carries its own absolute
                # position (mixed decode/prefill share one axis)
                pos = cache.token_pos[None, None, :]
            else:
                off = jnp.asarray(
                    0 if cache is None else cache.length, jnp.int32
                )
                base = jnp.arange(x.shape[1], dtype=jnp.int32)
                if off.ndim:  # ragged: (B,) offsets -> (B, 1, S) positions
                    pos = (off[:, None] + base[None, :])[:, None, :]
                else:
                    pos = off + base
            q = apply_rope(q, pos, self.rope_theta)
            k = apply_rope(k, pos, self.rope_theta)
        if self.window is not None:
            if not self.causal:
                raise ValueError("window requires causal=True")
            if self.window < 1:
                raise ValueError(f"window must be >= 1, got {self.window}")
        if self.attn_sinks and self.window is None:
            raise ValueError("attn_sinks require a windowed model")
        if self.attn_sinks < 0:
            raise ValueError(
                f"attn_sinks must be >= 0, got {self.attn_sinks}"
            )
        if cache is None:
            if self.cp_axis is not None:
                if self.cp_impl in ("ring", "zigzag"):
                    from attention_tpu.parallel.ring import (
                        ring_attention_diff,
                    )

                    out = ring_attention_diff(
                        q, k, v, mesh=self.mesh, axis_name=self.cp_axis,
                        causal=self.causal, window=self.window,
                        sinks=self.attn_sinks or None,
                        softcap=self.softcap,
                        schedule=("zigzag" if self.cp_impl == "zigzag"
                                  else "contiguous"),
                    )
                elif self.cp_impl == "allgather":
                    from attention_tpu.parallel.cp import cp_flash_attention

                    out = cp_flash_attention(
                        q, k, v, mesh=self.mesh, axis_name=self.cp_axis,
                        causal=self.causal, window=self.window,
                        sinks=self.attn_sinks or None,
                        softcap=self.softcap,
                    )
                elif self.cp_impl == "ulysses":
                    from attention_tpu.parallel.ulysses import (
                        ulysses_attention,
                    )

                    out = ulysses_attention(
                        q, k, v, mesh=self.mesh, axis_name=self.cp_axis,
                        causal=self.causal, window=self.window,
                        sinks=self.attn_sinks or None,
                        softcap=self.softcap,
                    )
                else:
                    raise ValueError(
                        f"unknown cp_impl {self.cp_impl!r} (supported: "
                        "['allgather', 'ring', 'zigzag', 'ulysses'])"
                    )
            else:
                out = ATTN_IMPLS[self.impl](q, k, v, causal=self.causal,
                                            window=self.window,
                                            softcap=self.softcap,
                                            sinks=self.attn_sinks)
        elif isinstance(cache, QuantKVCache):
            out, cache = self._quantized_decode(q, k, v, cache)
        elif isinstance(cache, RaggedKVCache):
            out, cache = self._ragged_attention(q, k, v, cache)
        elif isinstance(cache, RaggedPagedStep):
            out, cache = self._ragged_paged_step(q, k, v, cache)
        elif isinstance(cache, PagedKV):
            out, cache = self._paged_attention(q, k, v, cache)
        elif isinstance(cache, RollingKVCache):
            out, cache = self._rolling_attention(q, k, v, cache)
        else:
            out, cache = self._cached_attention(q, k, v, cache)
        out = out.transpose(0, 2, 1, 3).reshape(x.shape[0], x.shape[1], -1)
        proj = nn.DenseGeneral(
            features=x.shape[-1], use_bias=False, dtype=self.dtype, name="o_proj"
        )(out.astype(self.dtype))
        return proj if cache is None else (proj, cache)

    def _decode_call(self, q1, kr, vc, lens, **kw):
        """The fused decode kernel — head-sharded over ``tp_axis`` when
        serving tensor-parallel, local otherwise.  Shared by the dense,
        rolling, and ragged cache paths.  A 4-D ``q1`` (B, H, S, d)
        runs the speculative-verify chunk kernel instead (``lens`` is
        then the post-append length)."""
        if self.tp_axis is not None:
            from attention_tpu.parallel.serving import head_sharded_decode

            return head_sharded_decode(
                q1, kr, vc, lens, mesh=self.mesh,
                axis_name=self.tp_axis, **kw,
            )
        if q1.ndim == 4:
            from attention_tpu.ops.decode import flash_decode_chunk

            return flash_decode_chunk(q1, kr, vc, lens, **kw)
        return flash_decode(q1, kr, vc, lens, **kw)

    def _batch_flash_call(self, q, k, v, **kw):
        """The batch flash kernel for cached prefill / chunked append —
        head-sharded over ``tp_axis`` (`serving.head_sharded_prefill`),
        local otherwise."""
        if self.tp_axis is None:
            return flash_attention(q, k, v, **kw)
        from attention_tpu.parallel.serving import head_sharded_prefill

        return head_sharded_prefill(q, k, v, mesh=self.mesh,
                                    axis_name=self.tp_axis, **kw)

    def _cached_attention(self, q, k, v, cache: KVCache):
        """Append S new KV rows at ``cache.length``, attend over the
        valid prefix.  ``impl='flash'``: S == 1 -> fused flash-decode
        kernel; S > 1 (prefill, or chunked prefill appending to history)
        -> the flash kernel with a dynamic ``q_offset``/``kv_valid``
        window.  ``impl='xla'``: masked dense einsums that XLA
        auto-partitions under mesh shardings (sharded serving)."""
        s_new = q.shape[2]
        capacity = cache.k.shape[2]
        kc = jax.lax.dynamic_update_slice(
            cache.k, k.astype(cache.k.dtype), (0, 0, cache.length, 0)
        )
        vc = jax.lax.dynamic_update_slice(
            cache.v, v.astype(cache.v.dtype), (0, 0, cache.length, 0)
        )
        new_len = cache.length + s_new
        # Cached dispatch is explicit per impl: a registry entry without a
        # cached path must fail loudly, not silently take the flash one.
        if self.impl not in ("xla", "flash"):
            raise KeyError(
                f"impl {self.impl!r} has no cached-attention path "
                f"(supported: ['flash', 'xla'])"
            )
        # Single-token decode on a RoPE'd sink model reads the sink keys
        # re-rotated to their in-cache positions (see _sink_read_keys);
        # chunked appends (s_new > 1) keep absolute rotations — the
        # per-query shift is not uniform there, and chunked decode on a
        # sink model is a prefill-style operation anyway.
        kr = kc
        if (self.rope and self.attn_sinks and self.window is not None
                and s_new == 1):
            kr = _sink_read_keys(kc, new_len, self.window, self.attn_sinks,
                                 self.rope_theta)
        if self.impl == "xla":
            out = _xla_cached_attention(
                q, kr, vc, start=cache.length, new_len=new_len,
                causal=self.causal, window=self.window,
                softcap=self.softcap, sinks=self.attn_sinks,
            )
        elif s_new == 1:
            # windowed decode included: the decode kernel's per-sequence
            # [len-w, len) band + pinned sinks clamps out-of-window block
            # DMAs, so bandwidth scales with the window, not the prefix
            out = self._decode_call(
                q[:, :, 0, :], kr, vc, new_len,
                softcap=self.softcap, window=self.window,
                sinks=self.attn_sinks or None)[:, :, None, :]
        else:
            # chunked prefill / multi-token append: the banded flash
            # kernel applies the window over the cache
            out = self._batch_flash_call(
                q, kr, vc, causal=self.causal,
                q_offset=cache.length, kv_valid=new_len, window=self.window,
                softcap=self.softcap,
                sinks=self.attn_sinks or None,
            )
        # Overflowing the cache would silently clamp the write index
        # (dynamic_update_slice semantics) and corrupt attention; make it
        # loud instead — poison the output with NaN.
        out = jnp.where(new_len <= capacity, out, jnp.nan).astype(out.dtype)
        return out, KVCache(kc, vc, new_len)

    def _rolling_attention(self, q, k, v, cache: RollingKVCache):
        """Bounded-memory sliding-window (+sinks) serving on the ring
        buffer — see `RollingKVCache` for the slot layout.

        S == 1 (decode): write the new row at its slot (pinned for the
        first ``sinks`` tokens, ring otherwise) and attend over the
        valid slots with the fused decode kernel (slot order is
        irrelevant to softmax).  S > 1 (prefill) assumes a FRESH cache:
        the chunk attends only to itself (causal + window + sinks);
        the first ``sinks`` and last ``window`` rows seed the buffer.
        """
        if self.impl != "flash":
            raise ValueError(
                f"impl {self.impl!r} has no rolling-cache path "
                "(supported: ['flash'])"
            )
        if self.window is None:
            raise ValueError("RollingKVCache requires a windowed model")
        sinks = self.attn_sinks
        ring = self.window
        expect_cap = RollingKVCache.capacity_for(ring, sinks)
        if cache.capacity != expect_cap:
            raise ValueError(
                f"rolling capacity {cache.capacity} != expected "
                f"{expect_cap} (window {ring} + sinks {sinks}, rounded "
                "to the 128-slot granule)"
            )
        s_new = q.shape[2]
        zero = jnp.zeros((), jnp.int32)
        if s_new == 1:
            t = cache.length
            # pinned sink slots [0, sinks); ring slots [sinks, sinks+ring)
            slot = jnp.where(
                t < sinks, t, sinks + jnp.mod(t - sinks, ring)
            ) if sinks else jnp.mod(t, ring)
            kc = jax.lax.dynamic_update_slice(
                cache.k, k.astype(cache.k.dtype), (0, 0, slot, 0)
            )
            vc = jax.lax.dynamic_update_slice(
                cache.v, v.astype(cache.v.dtype), (0, 0, slot, 0)
            )
            valid = jnp.minimum(cache.length + 1, sinks + ring)
            kr = kc
            if self.rope and sinks:
                kr = _sink_read_keys(kc, cache.length + 1, ring, sinks,
                                     self.rope_theta)
            out = self._decode_call(q[:, :, 0, :], kr, vc, valid,
                                    softcap=self.softcap)[:, :, None, :]
        else:
            # fresh-cache prefill: the chunk sees only itself.  A
            # non-fresh cache would silently drop in-window history, so
            # poison that case loudly (the convention of this module).
            out = self._batch_flash_call(q, k, v, causal=True,
                                         window=self.window,
                                         softcap=self.softcap,
                                         sinks=sinks or None)
            out = jnp.where(cache.length == 0, out, jnp.nan).astype(out.dtype)
            kc, vc = cache.k, cache.v
            sink_keep = min(s_new, sinks)
            if sink_keep:
                kc = jax.lax.dynamic_update_slice(
                    kc, k[:, :, :sink_keep].astype(kc.dtype),
                    (zero, zero, zero, zero),
                )
                vc = jax.lax.dynamic_update_slice(
                    vc, v[:, :, :sink_keep].astype(vc.dtype),
                    (zero, zero, zero, zero),
                )
            keep = min(max(s_new - sinks, 0), ring)
            if keep:
                # ring rows land rotated so the invariant 'slot(t) =
                # sinks + (t - sinks) % ring' holds; split is static
                # (fresh cache): 1-2 contiguous writes, no scatter
                rows_k = k[:, :, s_new - keep:].astype(kc.dtype)
                rows_v = v[:, :, s_new - keep:].astype(vc.dtype)
                split = (s_new - keep - sinks) % ring
                first = ring - split
                kc = jax.lax.dynamic_update_slice(
                    kc, rows_k[:, :, :first],
                    (zero, zero, jnp.int32(sinks + split), zero),
                )
                vc = jax.lax.dynamic_update_slice(
                    vc, rows_v[:, :, :first],
                    (zero, zero, jnp.int32(sinks + split), zero),
                )
                if split:
                    kc = jax.lax.dynamic_update_slice(
                        kc, rows_k[:, :, first:],
                        (zero, zero, jnp.int32(sinks), zero),
                    )
                    vc = jax.lax.dynamic_update_slice(
                        vc, rows_v[:, :, first:],
                        (zero, zero, jnp.int32(sinks), zero),
                    )
        return out, RollingKVCache(kc, vc, cache.length + s_new)

    def _ragged_attention(self, q, k, v, cache: RaggedKVCache):
        """S == 1: one decode step per sequence at per-sequence
        positions.  S > 1: a speculative-verify chunk append — S rows
        written at each sequence's length, scored causally in one cache
        stream (`ops.decode.flash_decode_chunk`)."""
        if self.impl != "flash":
            raise ValueError(
                f"impl {self.impl!r} has no ragged-cache path "
                "(supported: ['flash'])"
            )
        s_new = q.shape[2]
        write = jax.vmap(
            lambda buf, rows, i: jax.lax.dynamic_update_slice(
                buf, rows, (jnp.int32(0), i, jnp.int32(0))
            )
        )
        kc = write(cache.k, k.astype(cache.k.dtype), cache.lengths)
        vc = write(cache.v, v.astype(cache.v.dtype), cache.lengths)
        new_lengths = cache.lengths + s_new
        # Sliding-window serving on the ragged cache: each query sits at
        # its own len-1, so the decode kernel's per-sequence [len-w, len)
        # band (+ pinned sinks) applies directly; with RoPE the sink
        # re-rotation delta is per-sequence.  Chunk appends keep
        # absolute rotations (the dense path's rule for s_new > 1).
        kr = kc
        if (self.rope and self.attn_sinks and self.window is not None
                and s_new == 1):
            kr = _sink_read_keys(kc, new_lengths, self.window,
                                 self.attn_sinks, self.rope_theta)
        if s_new == 1:
            out = self._decode_call(
                q[:, :, 0, :], kr, vc, new_lengths, softcap=self.softcap,
                window=self.window, sinks=self.attn_sinks or None,
            )[:, :, None, :]
        else:
            out = self._decode_call(
                q, kr, vc, new_lengths, softcap=self.softcap,
                window=self.window, sinks=self.attn_sinks or None,
            )
        # per-sequence overflow poison (same loud-overflow contract)
        over = new_lengths > cache.k.shape[2]
        out = jnp.where(over[:, None, None, None], jnp.nan, out)
        return out.astype(q.dtype), RaggedKVCache(kc, vc, new_lengths)

    def _ragged_paged_step(self, q, k, v, cache: RaggedPagedStep):
        """One packed serving step: every request's tokens for this
        step — one per decode, a chunk per prefill — ride a single
        token axis and lower onto ONE ragged kernel launch (append
        through the per-slot page tables, then
        `ops.ragged_paged.ragged_paged_attention`)."""
        if self.impl != "flash":
            raise ValueError(
                f"impl {self.impl!r} has no ragged paged-step path "
                "(supported: ['flash'])"
            )
        if self.rope and self.attn_sinks and self.window is not None:
            raise ValueError(
                "rope+sinks needs the per-sequence rotated sink read "
                "copy (paged_sink_decode), which the packed step does "
                "not carry; serve such models with "
                "step_mode='two_call'"
            )
        if self.tp_axis is not None:
            # head-sharded single-launch step: append + ragged
            # attention run per KV-head shard inside one shard_map
            # (pools and new rows shard, host-packed indices
            # replicate) — the mesh serving engine's ragged lowering
            from attention_tpu.parallel.serving import (
                head_sharded_ragged_step,
            )

            out, cache = head_sharded_ragged_step(
                q, cache, k, v, mesh=self.mesh, axis_name=self.tp_axis,
                softcap=self.softcap, window=self.window,
                sinks=self.attn_sinks or None,
            )
            return out.astype(q.dtype), cache
        cache = ragged_paged_append(cache, k, v)
        out = ragged_paged_attention(
            q, cache, softcap=self.softcap, window=self.window,
            sinks=self.attn_sinks or None,
        )
        return out.astype(q.dtype), cache

    def _paged_attention(self, q, k, v, cache: PagedKV):
        """S == 1: one decode step per sequence through the page table.
        S > 1: a speculative-verify chunk append (rows written through
        the table row-by-row, scored causally in one pool stream)."""
        if self.impl != "flash":
            raise ValueError(
                f"impl {self.impl!r} has no paged-cache path "
                "(supported: ['flash'])"
            )
        s_new = q.shape[2]
        if s_new > 1:
            from attention_tpu.ops.paged import paged_append_chunk

            cache = paged_append_chunk(cache, k, v)
            if self.tp_axis is not None:
                from attention_tpu.parallel.serving import (
                    head_sharded_decode_paged,
                )

                out = head_sharded_decode_paged(
                    q, cache, mesh=self.mesh, axis_name=self.tp_axis,
                    softcap=self.softcap, window=self.window,
                    sinks=self.attn_sinks or None,
                )
            else:
                # rope+sinks chunk appends keep absolute rotations (the
                # dense path's s_new > 1 rule), so no sink read copy
                out = paged_flash_decode(
                    q, cache, softcap=self.softcap,
                    window=self.window, sinks=self.attn_sinks or None,
                )
            return out.astype(q.dtype), cache
        cache = paged_append(cache, k, v)
        if self.rope and self.attn_sinks and self.window is not None:
            if self.tp_axis is not None:
                raise ValueError(
                    "rope+sinks on the paged cache reads a per-sequence "
                    "rotated sink copy (paged_sink_decode), which has no "
                    "head-sharded form yet; serve rope+sink models "
                    "tensor-parallel on the dense/ragged/int8 caches"
                )
            # in-cache sink re-rotation can't touch pool pages (they may
            # be prefix-shared across sequences with different deltas);
            # paged_sink_decode instead rotates a per-sequence READ COPY
            # of the sink rows and merges it with the window band — the
            # int8 cache's sink_read_rotation pattern applied at page
            # read
            from attention_tpu.ops.paged import paged_sink_decode

            out = paged_sink_decode(
                q[:, :, 0, :], cache, window=self.window,
                sinks=self.attn_sinks, theta=self.rope_theta,
                softcap=self.softcap,
            )[:, :, None, :]
        elif self.tp_axis is not None:
            from attention_tpu.parallel.serving import (
                head_sharded_decode_paged,
            )

            out = head_sharded_decode_paged(
                q[:, :, 0, :], cache, mesh=self.mesh,
                axis_name=self.tp_axis, softcap=self.softcap,
                window=self.window, sinks=self.attn_sinks or None,
            )[:, :, None, :]
        else:
            out = paged_flash_decode(
                q[:, :, 0, :], cache, softcap=self.softcap,
                window=self.window, sinks=self.attn_sinks or None,
            )[:, :, None, :]
        return out.astype(q.dtype), cache

    def _quantized_decode(self, q, k, v, cache: QuantKVCache):
        """One decode step against an int8 cache: quantize the new KV
        row in, run the fused quantized kernel.  Prefill runs on the
        bf16 `KVCache`, then `KVCache.quantize()` converts.  S > 1 is a
        speculative-verify chunk: rows quantize-append, then score
        causally in one int8 stream
        (`ops.quant.flash_decode_quantized_chunk`)."""
        if self.impl != "flash":
            raise ValueError(
                f"impl {self.impl!r} has no quantized-cache path "
                "(supported: ['flash'])"
            )
        s_new = q.shape[2]
        if s_new > 1:
            kv = update_quantized_kv(cache.kv, k, v, cache.length)
            new_len = cache.length + s_new
            if self.tp_axis is not None:
                from attention_tpu.parallel.serving import (
                    head_sharded_decode_quantized,
                )

                out = head_sharded_decode_quantized(
                    q, kv, new_len, mesh=self.mesh,
                    axis_name=self.tp_axis, softcap=self.softcap,
                    window=self.window, sinks=self.attn_sinks or None)
            else:
                from attention_tpu.ops.quant import (
                    flash_decode_quantized_chunk,
                )

                out = flash_decode_quantized_chunk(
                    q, kv, new_len, softcap=self.softcap,
                    window=self.window, sinks=self.attn_sinks or None)
            return out.astype(q.dtype), QuantKVCache(kv, new_len)
        kv = update_quantized_kv(cache.kv, k, v, cache.length)
        new_len = cache.length + 1
        kr = kv
        if self.rope and self.attn_sinks and self.window is not None:
            # int8 counterpart of _sink_read_keys (per-sequence storage,
            # so — unlike paged pool pages — re-rotation is legal)
            kr = sink_read_rotation(kv, new_len, self.window,
                                    self.attn_sinks, self.rope_theta)
        if self.tp_axis is not None:
            from attention_tpu.parallel.serving import (
                head_sharded_decode_quantized,
            )

            out = head_sharded_decode_quantized(
                q[:, :, 0, :], kr, new_len, mesh=self.mesh,
                axis_name=self.tp_axis, softcap=self.softcap,
                window=self.window, sinks=self.attn_sinks or None)
        else:
            out = flash_decode_quantized(q[:, :, 0, :], kr, new_len,
                                         softcap=self.softcap,
                                         window=self.window,
                                         sinks=self.attn_sinks or None)
        # overflow already NaN-poisons via update_quantized_kv's scales
        return out[:, :, None, :].astype(q.dtype), QuantKVCache(kv, new_len)
