"""Sharded training step: dp x sp x tp mesh over the tiny decoder.

The reference has no training path (forward-only kernel); this supplies
the distributed-training surface a framework needs, built the TPU way:
one ``jax.jit`` train step whose parallelism comes entirely from sharding
annotations — XLA inserts the all-reduces (data parallel), all-gathers
(sequence parallel around attention) and reduce-scatters (tensor
parallel) over ICI.  No hand-written collectives, which is exactly the
declarative counterpart of the reference's hand-scheduled
MPI pipeline (`attention-mpi.c:268-399`).

Sharding layout:
  * batch axis of activations                    -> 'dp'
  * sequence axis of activations                 -> 'sp'
  * head axes of attention projection params     -> 'tp'
  * MLP hidden dim                               -> 'tp'
  * embeddings/vocab                             -> 'tp' on the vocab dim
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from attention_tpu.models.transformer import TinyDecoder


def make_mesh_3d(n_devices: int | None = None, devices=None) -> Mesh:
    """Factor n devices into a (dp, sp, tp) mesh, largest axis first."""
    devices = devices if devices is not None else jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    # factor n = dp * sp * tp with dp >= sp >= tp, greedily
    def _factor(n):
        dims = [1, 1, 1]
        i = 0
        f = 2
        rem = n
        factors = []
        while f * f <= rem:
            while rem % f == 0:
                factors.append(f)
                rem //= f
            f += 1
        if rem > 1:
            factors.append(rem)
        for f in sorted(factors, reverse=True):
            dims[i % 3] *= f
            i += 1
        return sorted(dims, reverse=True)

    dp, sp, tp = _factor(n)
    return Mesh(np.asarray(devices).reshape(dp, sp, tp), ("dp", "sp", "tp"))


def _param_spec(path: tuple, value: Any) -> P:
    """Sharding rule by parameter path — the tp layout table."""
    names = [getattr(p, "key", getattr(p, "name", str(p))) for p in path]
    joined = "/".join(str(n) for n in names)
    if value.ndim == 1:  # norms, biases: replicate
        return P()
    if "Embed" in joined:  # (vocab, dim)
        return P("tp", None)
    if any(f"{p}_proj" in joined for p in ("q", "k", "v")):
        # DenseGeneral kernel (dim, heads, head_dim): shard heads
        return P(None, "tp", None)
    if "o_proj" in joined:  # (hq*dh, dim): shard the head-derived dim
        return P("tp", None)
    if "experts_" in joined:  # MoE (E, d, h)/(E, h, d): shard experts
        return P("tp", None, None)
    if "router" in joined:  # (d, E) router: small, replicate
        return P()
    if "Dense_0" in joined:  # MLP up (dim, hidden): shard hidden
        return P(None, "tp")
    if "Dense_1" in joined:  # MLP down (hidden, dim)
        return P("tp", None)
    if value.ndim >= 2:  # lm head and anything else 2D
        return P(None, "tp")
    return P()


def _fsdp_spec(spec: P, shape: tuple, mesh: Mesh) -> P:
    """Additionally shard the largest still-unsharded dim over 'dp'
    (ZeRO-3 style fully-sharded params: each dp replica holds a slice;
    XLA all-gathers at use and reduce-scatters the grads)."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    if "dp" in entries:
        return spec
    cands = [
        (shape[i], i)
        for i, ax in enumerate(entries)
        if ax is None and shape[i] % mesh.shape["dp"] == 0
    ]
    if not cands:
        return spec
    _, i = max(cands)
    entries[i] = "dp"
    return P(*entries)


def _legal_spec(spec: P, shape: tuple, mesh: Mesh) -> P:
    """Drop sharding on any dim whose axis the mesh lacks (e.g. a
    2-axis (dp, sp) multi-host mesh has no tp) or doesn't divide (a
    single shared KV head can't be split over tp) — replicate instead."""
    fixed = []
    for dim, axis in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if axis is not None and (axis not in mesh.shape
                                 or dim % mesh.shape[axis] != 0):
            axis = None
        fixed.append(axis)
    return P(*fixed)


def shard_params(params, mesh: Mesh, *, fsdp: bool = False):
    """Lay params out per the tp table; ``fsdp=True`` additionally
    shards each param's largest free dim over 'dp' (ZeRO-3 style:
    per-replica parameter/optimizer memory drops ~dp-fold; XLA inserts
    the use-site all-gathers and grad reduce-scatters)."""

    def place(path, x):
        spec = _legal_spec(_param_spec(path, x), x.shape, mesh)
        if fsdp:
            # _fsdp_spec only adds 'dp' on dims it verified divisible
            spec = _fsdp_spec(spec, x.shape, mesh)
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


def loss_fn(params, model: TinyDecoder, batch: jax.Array) -> jax.Array:
    """Next-token cross-entropy over (B, S) int tokens, plus any sown
    auxiliary losses (MoE load-balancing)."""
    logits, mods = model.apply(
        {"params": params}, batch[:, :-1], mutable=["losses"]
    )
    targets = batch[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ce = -jnp.mean(
        jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    )
    aux = sum(jax.tree_util.tree_leaves(mods.get("losses", {})), 0.0)
    return ce + aux


def make_train_step(model: TinyDecoder, optimizer, mesh: Mesh,
                    *, accum_steps: int = 1):
    """Build the jitted sharded train step: (params, opt_state, batch) ->
    (params, opt_state, loss).

    ``accum_steps > 1`` splits the batch into that many microbatches
    and accumulates gradients in a `lax.scan` before ONE optimizer
    update — the effective batch no longer has to fit activations in
    HBM at once.  Equal-sized microbatches keep the mean-loss gradient
    exactly equal to the unaccumulated step (up to fp summation order)
    for dense models; MoE aux losses are computed per microbatch (their
    router statistics are nonlinear in the batch), so accumulation
    regularizes per-microbatch balance rather than full-batch balance.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    batch_spec = NamedSharding(mesh, P("dp", "sp"))

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, batch):
        batch = jax.lax.with_sharding_constraint(batch, batch_spec)
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(loss_fn)(params, model, batch)
        else:
            b = batch.shape[0]
            if b % accum_steps:
                raise ValueError(
                    f"batch {b} not divisible by accum_steps {accum_steps}"
                )
            micro = batch.reshape(accum_steps, b // accum_steps,
                                  *batch.shape[1:])

            def acc_one(carry, mb):
                loss_sum, grad_sum = carry
                loss, grads = jax.value_and_grad(loss_fn)(params, model, mb)
                grad_sum = jax.tree_util.tree_map(
                    jnp.add, grad_sum, grads
                )
                return (loss_sum + loss, grad_sum), None

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )
            (loss_sum, grad_sum), _ = jax.lax.scan(
                acc_one, (jnp.float32(0.0), zeros), micro
            )
            loss = loss_sum / accum_steps
            # back to each param leaf's grad dtype, matching what the
            # unaccumulated path hands the optimizer
            grads = jax.tree_util.tree_map(
                lambda g, p_: (g / accum_steps).astype(p_.dtype),
                grad_sum, params,
            )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def init_sharded(
    model: TinyDecoder,
    mesh: Mesh,
    *,
    batch: int = 8,
    seq: int = 128,
    seed: int = 0,
    lr: float = 1e-3,
    fsdp: bool = False,
):
    """Initialize params + optimizer state, both mesh-sharded.
    ``fsdp=True`` fully shards params (and thus the adamw moments)
    over the dp axis as well — see :func:`shard_params`."""
    rng = jax.random.PRNGKey(seed)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    params = model.init(rng, tokens)["params"]
    params = shard_params(params, mesh, fsdp=fsdp)
    optimizer = optax.adamw(lr)
    opt_state = optimizer.init(params)
    # moment buffers (zeros_like(params)) inherit the params shardings;
    # scalar leaves (step counts) need an explicit replicated sharding so
    # checkpoint templates and jit arguments agree across the mesh
    replicated = NamedSharding(mesh, P())
    opt_state = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, replicated)
        if getattr(x, "ndim", None) == 0
        else x,
        opt_state,
    )
    return params, optimizer, opt_state
