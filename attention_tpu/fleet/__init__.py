"""Disaggregated prefill/decode fleets + the closed-loop autoscaler.

The subsystem that ACTS on the observatory.  PRs 12/14/18 built the
byte-deterministic decision inputs — per-tick pressure series, Holt
watermark forecasts, anomaly detectors, the blackbox actuation audit
trail — all pinned ``advisory``.  This package closes the loop:

    topology.py    role-typed replica pools (`FleetTopology`): fresh
                   admissions route to the prefill pool, streams live
                   in the decode pool, one shared standby bench
    handoff.py     prefill→decode handoff that ships the request's
                   committed KV pages (PR 9 section format, per-shard
                   ``pools.<s>`` CRC'd slices) instead of re-prefilling
    autoscaler.py  deterministic per-tick controller: promote on
                   forecast watermark crossings, demote on sustained
                   slack, rebalance the split — asymmetric hysteresis
                   + cooldown (never flaps), anomaly firings veto
                   scale-downs
    ledger.py      the typed actuation ledger chaos invariant 16
                   balances against the blackbox ring

Correctness doctrine, unchanged from every layer below: placement and
scale decisions may move WHERE tokens are computed, never WHICH — the
disaggregated fleet is token-identical to the monolithic one on the
same seeded trace, a corrupt handoff payload is a typed
`HandoffCorruptError` + re-prefill fallback, and every pool resize is
audited (blackbox event with a recorded cause; a scale-down followed
by sheds inside the guard window dumps an ``incident-<tick>/``
bundle).
"""

from attention_tpu.engine.errors import HandoffCorruptError  # noqa: F401
from attention_tpu.fleet.autoscaler import (  # noqa: F401
    Autoscaler,
    AutoscalerPolicy,
    ScaleAction,
)
from attention_tpu.fleet.handoff import (  # noqa: F401
    HANDOFF_MAGIC,
    HandoffRecord,
    decode_handoff,
    encode_handoff,
    export_handoff,
    import_handoff,
    inspect_handoff,
    is_handoff,
)
from attention_tpu.fleet.ledger import (  # noqa: F401
    ACTUATION_CAUSES,
    ActuationRecord,
)
from attention_tpu.fleet.topology import (  # noqa: F401
    POOLS,
    FleetTopology,
    initial_pools,
)
