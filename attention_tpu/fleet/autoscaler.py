"""Closed-loop elastic autoscaler: the controller that finally acts.

PR 14 built the decision inputs (deterministic Holt forecasts over
the frozen per-tick pressure series, watermark-crossing prediction)
and PR 18 built the audit trail (blackbox actuation events, anomaly
guardrails, postmortem bundles) — both explicitly advisory.  This
module closes the loop: a pure per-tick controller that

* **promotes** a warm standby into a pool when observed or FORECAST
  pressure crosses the up watermark for ``scale_up_after``
  consecutive ticks (the forecast horizon is what lands capacity
  before the burst, not after it);
* **demotes** a drained member back to standby after
  ``scale_down_after`` consecutive slack ticks — asymmetric
  hysteresis: scaling up is cheap and urgent, scaling down is neither;
* **rebalances** the prefill:decode split when one pool is pressured,
  no standby is available, and the other pool has slack (a paired
  down+up, one cause, both pools cooldown-stamped);
* **vetoes** its own scale-downs while an anomaly detector implicates
  the pool (`obs/anomaly.py` firings: a gray-failure key names a
  replica, hence its pool; a fleet-wide detector vetoes both pools).

Determinism: the controller's only inputs are the tick counter, the
per-pool mean pressures, pool sizes, the standby count, and the veto
set — all deterministic series — and pools are visited in the fixed
`POOLS` order.  Same seed, same trace → the same actuation sequence,
which is what lets chaos invariant 16 balance the ledger byte-for-
byte and the cooldown guarantee "zero up→down→up inside one cooldown
window" hold as an invariant rather than a tendency.

The controller DECIDES; `ServingFrontend` executes (promoting
standbys, draining + demoting victims, writing the blackbox events
and the `fleet.ledger` records, arming the mis-actuation guard).
"""

from __future__ import annotations

import dataclasses

from attention_tpu.obs.forecast import ForecastPolicy, HoltForecaster

from attention_tpu.fleet.topology import POOLS


@dataclasses.dataclass(frozen=True)
class AutoscalerPolicy:
    """Controller knobs; every time-like field is in ticks."""

    #: pressure at/above which a pool wants capacity (observed or
    #: forecast inside ``horizon``)
    up_pressure: float = 0.75
    #: pressure at/below which a pool is slack
    down_pressure: float = 0.25
    #: consecutive pressured ticks before a scale-up fires
    scale_up_after: int = 2
    #: consecutive slack ticks before a scale-down fires (asymmetric:
    #: give back capacity far more reluctantly than it was taken)
    scale_down_after: int = 6
    #: after any actuation on a pool, no further actuation on it for
    #: this many ticks — the anti-flap guarantee
    cooldown_ticks: int = 12
    #: forecast steps ahead that count as "crossing is coming"
    horizon: int = 4
    #: ticks after a scale-down during which a shed is a mis-actuation
    #: (dumps an ``incident-<tick>/`` bundle, cause ``actuation``)
    guard_window: int = 8
    #: neither pool may shrink below this
    min_pool: int = 1
    #: per-pool pressure forecaster (the PR 14 Holt machinery)
    forecast: ForecastPolicy = dataclasses.field(
        default_factory=ForecastPolicy)

    def validate(self) -> None:
        if not (0.0 < self.down_pressure < self.up_pressure <= 1.0):
            raise ValueError(
                f"need 0 < down_pressure < up_pressure <= 1, got "
                f"down {self.down_pressure} up {self.up_pressure}"
            )
        for name in ("scale_up_after", "scale_down_after",
                     "cooldown_ticks", "horizon", "guard_window",
                     "min_pool"):
            v = getattr(self, name)
            if v < 1:
                raise ValueError(f"{name} must be >= 1, got {v}")
        self.forecast.validate()


@dataclasses.dataclass(frozen=True)
class ScaleAction:
    """One controller decision for the front end to execute."""

    kind: str    # "scale_up" | "scale_down" | "veto"
    pool: str
    cause: str   # fleet.ledger.ACTUATION_CAUSES member


class Autoscaler:
    """Pure per-tick controller (module doc).  Holds only its own
    forecasters, streaks, and cooldown stamps — never a reference to
    the front end."""

    def __init__(self, policy: AutoscalerPolicy):
        policy.validate()
        self.policy = policy
        self._fc = {pool: HoltForecaster(policy.forecast)
                    for pool in POOLS}
        self._up = {pool: 0 for pool in POOLS}
        self._down = {pool: 0 for pool in POOLS}
        self._last_action = {pool: None for pool in POOLS}

    def _cooling(self, pool: str, tick: int) -> bool:
        last = self._last_action[pool]
        return (last is not None
                and tick - last < self.policy.cooldown_ticks)

    def decide(self, tick: int, *, pressures: dict[str, float],
               pool_sizes: dict[str, int], standbys: int,
               vetoed: tuple[str, ...] | frozenset[str] = (),
               forced: int = 0) -> list[ScaleAction]:
        """One controller tick.  ``pressures``/``pool_sizes`` are
        keyed by pool; ``vetoed`` names pools an anomaly detector
        currently implicates; ``forced`` demotions (chaos
        ``demote_storm``) bypass hysteresis and vetoes but still
        respect ``min_pool``.  Returns actions in execution order —
        a rebalance emits its scale-down before its scale-up so the
        freed handle is in the standby pool when the promotion pops
        it."""
        pol = self.policy
        actions: list[ScaleAction] = []
        avail = standbys
        sizes = dict(pool_sizes)
        for pool in POOLS:
            p = float(pressures[pool])
            fc = self._fc[pool]
            fc.observe(p)
            preds = [fc.predict(h) for h in range(1, pol.horizon + 1)]
            crossed = (p >= pol.up_pressure
                       or any(x >= pol.up_pressure for x in preds))
            slack = (p <= pol.down_pressure
                     and all(x <= pol.down_pressure for x in preds))
            if crossed:
                self._up[pool] += 1
                self._down[pool] = 0
            elif slack:
                self._down[pool] += 1
                self._up[pool] = 0
            else:
                self._up[pool] = 0
                self._down[pool] = 0
            if self._cooling(pool, tick):
                continue
            if self._up[pool] >= pol.scale_up_after:
                if avail > 0:
                    avail -= 1
                    sizes[pool] += 1
                    actions.append(
                        ScaleAction("scale_up", pool, "forecast"))
                    self._last_action[pool] = tick
                    self._up[pool] = 0
                    continue
                other = POOLS[1] if pool == POOLS[0] else POOLS[0]
                if (float(pressures[other]) <= pol.down_pressure
                        and sizes[other] > pol.min_pool
                        and not self._cooling(other, tick)):
                    if other in vetoed:
                        actions.append(
                            ScaleAction("veto", other, "rebalance"))
                        self._up[pool] = 0
                        continue
                    sizes[other] -= 1
                    sizes[pool] += 1
                    actions.append(
                        ScaleAction("scale_down", other, "rebalance"))
                    actions.append(
                        ScaleAction("scale_up", pool, "rebalance"))
                    self._last_action[pool] = tick
                    self._last_action[other] = tick
                    self._up[pool] = 0
                continue
            if (self._down[pool] >= pol.scale_down_after
                    and sizes[pool] > pol.min_pool):
                if pool in vetoed:
                    # bounded emission: one veto per armed streak —
                    # the streak re-arms from zero, so a persistent
                    # anomaly produces a veto every scale_down_after
                    # ticks, not every tick
                    actions.append(ScaleAction("veto", pool, "slack"))
                    self._down[pool] = 0
                    continue
                sizes[pool] -= 1
                actions.append(ScaleAction("scale_down", pool, "slack"))
                self._last_action[pool] = tick
                self._down[pool] = 0
        for _ in range(max(0, int(forced))):
            cands = [pl for pl in POOLS if sizes[pl] > pol.min_pool]
            if not cands:
                break
            pool = sorted(cands, key=lambda pl: (-sizes[pl], pl))[0]
            sizes[pool] -= 1
            actions.append(ScaleAction("scale_down", pool, "forced"))
            self._last_action[pool] = tick
            self._down[pool] = 0
        return actions
