"""Role-typed replica pools: the disaggregated fleet's shape.

The monolithic front end serves prefill and decode on the same
replicas, so one 100k-token RAG prefill stalls every co-located
tenant's TPOT.  `FleetTopology` splits `FrontendConfig.num_replicas`
into two role-typed pools:

* the **prefill pool** absorbs fresh admissions (long, bursty,
  compute-bound prompt processing);
* the **decode pool** streams tokens (short, steady, latency-bound
  appends) and receives each request at prompt-commit through the
  KV-shipping handoff (`fleet.handoff`).

Roles are assigned by replica index at construction — the first
``prefill_replicas`` handles form the prefill pool, the rest the
decode pool — and tracked per replica id in
``ServingFrontend.pool_of`` thereafter, because the elastic
autoscaler (`fleet.autoscaler`) moves warm standbys in and drained
members out at runtime.  The shared standby pool is role-less: a
spare joins whichever pool the scale-up decision names.

Placement is a PREFERENCE, never a correctness boundary: routing
restricts eligibility to the role pool when that pool has a healthy
member and falls back to the whole healthy fleet otherwise, and
token values are independent of placement by construction (seeded
sampling + arithmetic RNG reconstruction), so a degraded topology
serves exactly the same tokens as a perfect one.
"""

from __future__ import annotations

import dataclasses

#: the closed pool-role alphabet, in deterministic iteration order
POOLS = ("prefill", "decode")


@dataclasses.dataclass(frozen=True)
class FleetTopology:
    """Static split of the replica fleet into role-typed pools.

    ``prefill_replicas + decode_replicas`` must equal the front end's
    ``num_replicas``; the shared ``FrontendConfig.standbys`` spares
    back both pools."""

    prefill_replicas: int = 1
    decode_replicas: int = 1

    def validate(self, *, num_replicas: int) -> None:
        if self.prefill_replicas < 1:
            raise ValueError(
                f"prefill_replicas must be >= 1, got "
                f"{self.prefill_replicas}"
            )
        if self.decode_replicas < 1:
            raise ValueError(
                f"decode_replicas must be >= 1, got "
                f"{self.decode_replicas}"
            )
        total = self.prefill_replicas + self.decode_replicas
        if total != num_replicas:
            raise ValueError(
                f"fleet topology covers {total} replicas "
                f"(prefill {self.prefill_replicas} + decode "
                f"{self.decode_replicas}) but num_replicas is "
                f"{num_replicas}"
            )


def initial_pools(replica_ids, topology: FleetTopology) -> dict[str, str]:
    """Index-based role assignment at fleet construction: the first
    ``prefill_replicas`` ids go to the prefill pool, the rest decode."""
    ids = list(replica_ids)
    return {
        rid: (POOLS[0] if i < topology.prefill_replicas else POOLS[1])
        for i, rid in enumerate(ids)
    }
