"""The actuation ledger: every pool-size change, typed and audited.

The autoscaler graduated the observatory from ``advisory=False`` into
actuation, and actuation must be auditable: chaos invariant 16
(`chaos.invariants.actuation_ledger_violations`) balances this ledger
against the blackbox flight-recorder ring — every entry here maps to
exactly one ``scale_up``/``scale_down`` ring event carrying the same
recorded cause, and a pool may never flap (up→down→up) inside one
cooldown window.

Causes are a closed alphabet, like event kinds and incident causes:

    forecast   scale-up on a (predicted or observed) watermark crossing
    slack      scale-down after sustained sub-watermark pressure
    rebalance  paired down+up shifting the prefill:decode split
    forced     chaos ``demote_storm`` bypassing hysteresis (exempt
               from the flap check — the storm IS the flap)
"""

from __future__ import annotations

import dataclasses

#: the closed actuation-cause alphabet (invariant 16 rejects others)
ACTUATION_CAUSES = ("forecast", "slack", "rebalance", "forced")


@dataclasses.dataclass(frozen=True)
class ActuationRecord:
    """One executed fleet resize, in actuation order."""

    tick: int
    kind: str           # "scale_up" | "scale_down"
    pool: str           # fleet.topology.POOLS member
    replica_id: str     # the handle promoted or demoted
    cause: str          # ACTUATION_CAUSES member
