"""Prefill→decode KV handoff: ship committed pages, not tokens.

PR 10's migration fabric moves a request by replaying its stream —
the destination re-prefills prompt + fed generation from scratch.
That is the right durability story (a dead replica's pages are gone)
but the wrong disaggregation story: a prefill-pool replica that just
spent its whole budget computing a 100k-token prompt holds exactly
the KV the decode destination needs, and throwing it away doubles
the fleet's prefill bill.

This module extends the per-request snapshot record (the PR 9
section format: one manifest line + CRC'd payload sections) with a
``pages`` payload — the request's committed prefix pages as per-shard
``pools.<s>`` head slices, the `prefixstore/records.py` layout with a
leading page axis.  A handoff blob is therefore self-validating and
self-describing:

    meta       the `_request_to_dict` request record + exporter
               fingerprint/geometry + the page-aligned token chain
    pools.<s>  shard s's contiguous KV-head slice of every committed
               page, K layers then V layers, independently CRC'd

The decode-side import mirrors `prefixstore.adapter.import_chain`:
gate on fleet fingerprint + geometry (mismatch = miss, never
corruption), allocate watermark-aware, write the pools, commit the
chain into the local prefix cache, drop the importer's reference —
so the subsequent `resume_request` admission finds the prefix cached
and skips the re-prefill entirely.

Integrity doctrine, same as snapshots and the prefix store: any
structural damage raises the typed `HandoffCorruptError`
(a `PrefixStoreCorruptError` subclass, so every existing typed-error
gate covers it); the handoff path catches it and re-admits WITHOUT
the pages.  A corrupt payload costs a re-prefill, never a wrong
token.
"""

from __future__ import annotations

import dataclasses
import json
import zlib
from typing import Any

import jax.numpy as jnp
import numpy as np

from attention_tpu.engine.errors import HandoffCorruptError
from attention_tpu.engine.snapshot import _jbytes, _np_dtype
from attention_tpu.ops.paged import OutOfPagesError
from attention_tpu.prefixstore.adapter import (
    engine_geometry,
    fleet_fingerprint,
)

HANDOFF_MAGIC = "atp-handoff"
HANDOFF_VERSION = 1


@dataclasses.dataclass(frozen=True)
class HandoffRecord:
    """One decoded handoff: the request record + its shipped pages."""

    request: dict                 # the PR 9 per-request section dict
    tokens: tuple[int, ...]       # page-aligned committed prefix chain
    fingerprint: dict             # exporter's fleet fingerprint
    geometry: dict                # exporter's page geometry
    arrays: tuple                 # 2*layers np arrays, K then V, each
    #                               (num_pages, num_kv_heads,
    #                                page_size, head_dim)


def _corrupt(why: str) -> HandoffCorruptError:
    return HandoffCorruptError(f"handoff record: {why}")


def encode_handoff(*, request: dict, tokens, arrays, fingerprint: dict,
                   geometry: dict, shards: int = 1) -> bytes:
    """Serialize one request + its committed prefix pages.

    ``arrays``: 2*layers host arrays (K pools then V pools), each
    ``(num_pages, num_kv_heads, page_size, head_dim)`` — the page axis
    leads so an S-shard exporter slices heads exactly like a snapshot
    does."""
    heads = geometry["num_kv_heads"]
    if shards < 1 or heads % shards:
        raise ValueError(
            f"shards {shards} does not divide num_kv_heads {heads}"
        )
    toks = [int(t) for t in tokens]
    hosted = [np.asarray(a) for a in arrays]
    num_pages = int(hosted[0].shape[0]) if hosted else 0
    meta = {
        "request": request,
        "tokens": toks,
        "num_pages": num_pages,
        "fingerprint": fingerprint,
        "geometry": geometry,
    }
    hh = heads // shards
    sections = [("meta", _jbytes(meta))] + [
        (f"pools.{s}",
         b"".join(np.ascontiguousarray(
             a[:, s * hh:(s + 1) * hh]).tobytes() for a in hosted))
        for s in range(shards)
    ]
    manifest = {
        "magic": HANDOFF_MAGIC,
        "version": HANDOFF_VERSION,
        "shards": shards,
        "sections": [
            {"name": name, "nbytes": len(payload),
             "crc32": zlib.crc32(payload)}
            for name, payload in sections
        ],
    }
    return (_jbytes(manifest) + b"\n"
            + b"".join(payload for _, payload in sections))


def _read_sections(blob: bytes) -> tuple[dict, dict[str, bytes]]:
    """Manifest + checksummed sections, or the typed corrupt raise —
    the `prefixstore.records` validation chain under the handoff
    magic."""
    nl = blob.find(b"\n")
    if nl < 0:
        raise _corrupt("no manifest line")
    try:
        manifest = json.loads(blob[:nl])
    except ValueError:
        raise _corrupt("unparseable manifest")
    if not isinstance(manifest, dict) \
            or manifest.get("magic") != HANDOFF_MAGIC:
        raise _corrupt("bad magic (not a handoff record)")
    if manifest.get("version") != HANDOFF_VERSION:
        raise _corrupt(
            f"unsupported handoff version {manifest.get('version')!r} "
            f"(reader speaks {HANDOFF_VERSION})"
        )
    shards = manifest.get("shards", 1)
    if not isinstance(shards, int) or isinstance(shards, bool) \
            or shards < 1:
        raise _corrupt(f"bad shards count {shards!r}")
    try:
        entries = [(s["name"], int(s["nbytes"]), int(s["crc32"]))
                   for s in manifest["sections"]]
    except (KeyError, TypeError, ValueError):
        raise _corrupt("malformed section table")
    sections: dict[str, bytes] = {}
    offset = nl + 1
    for name, nbytes, crc in entries:
        payload = blob[offset:offset + nbytes]
        if len(payload) != nbytes:
            raise _corrupt(
                f"section {name!r} truncated "
                f"({len(payload)}/{nbytes} bytes)"
            )
        if zlib.crc32(payload) != crc:
            raise _corrupt(f"section {name!r} checksum mismatch")
        sections[name] = payload
        offset += nbytes
    if offset != len(blob):
        raise _corrupt(f"{len(blob) - offset} trailing bytes")
    required = ("meta", *(f"pools.{s}" for s in range(shards)))
    for name in required:
        if name not in sections:
            raise _corrupt(f"missing section {name!r}")
    return manifest, sections


def decode_handoff(blob: bytes) -> HandoffRecord:
    """Validate + reassemble one handoff; `HandoffCorruptError` on any
    structural damage.  Shard head slices concatenate back along the
    head dim, so exporter and importer shard counts are independent."""
    manifest, sections = _read_sections(blob)
    shards = manifest.get("shards", 1)
    try:
        meta = json.loads(sections["meta"])
        request = dict(meta["request"])
        tokens = tuple(int(t) for t in meta["tokens"])
        num_pages = int(meta["num_pages"])
        fingerprint = meta["fingerprint"]
        geometry = meta["geometry"]
        heads = int(geometry["num_kv_heads"])
        page_size = int(geometry["page_size"])
        head_dim = int(geometry["head_dim"])
        layers = int(geometry["layers"])
        dtype = _np_dtype(geometry["dtype"])
    except (KeyError, TypeError, ValueError):
        raise _corrupt("undecodable meta section")
    if num_pages < 1:
        raise _corrupt(f"bad page count {num_pages}")
    if len(tokens) != num_pages * page_size:
        raise _corrupt(
            f"token chain length {len(tokens)} != num_pages "
            f"{num_pages} * page_size {page_size}"
        )
    if heads < 1 or heads % shards:
        raise _corrupt(
            f"shards {shards} does not divide num_kv_heads {heads}"
        )
    hh = heads // shards
    slice_bytes = num_pages * hh * page_size * head_dim * dtype.itemsize
    per_shard = []
    for s in range(shards):
        payload = sections[f"pools.{s}"]
        if len(payload) != 2 * layers * slice_bytes:
            raise _corrupt(
                f"section 'pools.{s}' carries {len(payload)} bytes, "
                f"geometry implies {2 * layers * slice_bytes}"
            )
        per_shard.append([
            np.frombuffer(
                payload[i * slice_bytes:(i + 1) * slice_bytes], dtype
            ).reshape(num_pages, hh, page_size, head_dim)
            for i in range(2 * layers)
        ])
    arrays = tuple(
        np.concatenate([per_shard[s][i] for s in range(shards)], axis=1)
        if shards > 1 else per_shard[0][i]
        for i in range(2 * layers)
    )
    return HandoffRecord(request=request, tokens=tokens,
                         fingerprint=fingerprint, geometry=geometry,
                         arrays=arrays)


def inspect_handoff(blob: bytes) -> dict[str, Any]:
    """Tolerant manifest-level view of one handoff blob for
    `cli snapshot inspect`: section names, byte counts, and per-section
    CRC verdicts — never raises (damage lands in ``problems``)."""
    info: dict[str, Any] = {"format": "handoff", "valid": True,
                            "problems": []}
    try:
        manifest, sections = _read_sections(blob)
    except HandoffCorruptError as e:
        info["valid"] = False
        info["problems"].append(str(e))
        # degrade to whatever the manifest line still says
        nl = blob.find(b"\n")
        try:
            manifest = json.loads(blob[:max(nl, 0)])
        except ValueError:
            return info
        if not isinstance(manifest, dict):
            return info
        sections = None
    info["shards"] = manifest.get("shards", 1)
    info["version"] = manifest.get("version")
    rows = []
    for s in manifest.get("sections", []):
        try:
            name, nbytes, crc = (s["name"], int(s["nbytes"]),
                                 int(s["crc32"]))
        except (KeyError, TypeError, ValueError):
            continue
        ok = (sections is not None and name in sections
              and zlib.crc32(sections[name]) == crc)
        rows.append({"name": name, "nbytes": nbytes, "crc_ok": ok})
    info["sections"] = rows
    if sections is not None:
        try:
            meta = json.loads(sections["meta"])
            info["request_id"] = meta["request"].get("request_id")
            info["num_pages"] = int(meta["num_pages"])
            info["tokens"] = len(meta["tokens"])
        except (KeyError, TypeError, ValueError):
            info["problems"].append("undecodable meta section")
            info["valid"] = False
    return info


def is_handoff(blob: bytes) -> bool:
    """True iff ``blob`` leads with a handoff manifest line (cheap
    format sniff for the CLI's inspect dispatch)."""
    nl = blob.find(b"\n")
    if nl < 0:
        return False
    try:
        manifest = json.loads(blob[:nl])
    except ValueError:
        return False
    return (isinstance(manifest, dict)
            and manifest.get("magic") == HANDOFF_MAGIC)


def export_handoff(engine, req, request_record: dict) -> bytes | None:
    """Serialize one committed request + its full prefix pages from
    the PREFILL engine; None when no whole page is committed yet
    (the handoff then degrades to the plain PR 10 replay path).

    ``request_record`` is the caller's `_request_to_dict` dict — the
    cut serializes the request exactly once and ships the same record
    in the blob the chaos checkers later audit."""
    ps = engine.config.page_size
    toks = tuple(int(t) for t in req.prompt)
    full = min(len(toks) // ps, len(req.pages))
    if full == 0:
        return None
    pages = [int(p) for p in list(req.pages)[:full]]
    arrays = tuple(
        np.stack([np.asarray(pool[p]) for p in pages])
        for pool in (*engine._k_pools, *engine._v_pools)
    )
    return encode_handoff(
        request=request_record,
        tokens=toks[: full * ps],
        arrays=arrays,
        fingerprint=fleet_fingerprint(engine),
        geometry=engine_geometry(engine),
        shards=engine.config.mesh_shards or 1,
    )


def import_handoff(engine, blob: bytes, *, now: int) -> int:
    """Write a handoff's shipped pages into the DECODE engine's pools
    and commit the chain into its local prefix cache; returns prompt
    tokens newly covered (the re-prefill the destination skips).

    Raises `HandoffCorruptError` on structural damage (the caller
    falls back to plain replay); returns 0 on fingerprint/geometry
    mismatch (another fleet's pages: a miss), an already-cached chain,
    or allocator pressure (`for_decode=False`: a busy decode replica
    refuses the import before it refuses decode appends)."""
    rec = decode_handoff(blob)
    if (rec.fingerprint != fleet_fingerprint(engine)
            or rec.geometry != engine_geometry(engine)):
        return 0
    ps = int(rec.geometry["page_size"])
    toks = rec.tokens
    n = len(toks) // ps
    local = engine.allocator.peek_prefix(toks)
    if n <= local:
        return 0   # affinity already holds it; nothing to import
    try:
        pages = engine.allocator.allocate(n - local, for_decode=False)
    except OutOfPagesError:
        return 0
    depth = len(engine._k_pools)
    idx = jnp.asarray(pages, jnp.int32)
    dtype = engine._k_pools[0].dtype
    for layer in range(depth):
        k_stack = jnp.asarray(rec.arrays[layer][local:], dtype)
        v_stack = jnp.asarray(rec.arrays[depth + layer][local:], dtype)
        engine._k_pools[layer] = engine._place_pool(
            engine._k_pools[layer].at[idx].set(k_stack))
        engine._v_pools[layer] = engine._place_pool(
            engine._v_pools[layer].at[idx].set(v_stack))
    chain = engine.allocator.cached_chain(toks)
    engine.allocator.commit_prefix(toks, chain + pages, now=now)
    # drop the importer's reference: the prefix cache's own incref is
    # now the sole owner — the exact end-state a locally computed
    # chain leaves, which the chaos quiescence invariant demands
    engine.allocator.free(pages)
    return (n - local) * ps
