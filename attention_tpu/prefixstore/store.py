"""In-process fleet prefix store: content-addressed, budgeted, durable.

The backend of the global prefix tier.  One `PrefixStore` is shared by
every replica of a fleet (the frontend constructs it and hands it to
each `ReplicaHandle`): engines *export* committed prompt pages into it
as CRC'd `records` blobs keyed by token-chain hash, and *import* on a
local prefix-cache miss before paying a cold prefill.  The store never
holds live device memory — records are host bytes, so a store entry
survives its exporting replica's death, which is the whole point.

Budget discipline mirrors the allocator's: a byte budget with LRU
eviction (victim = oldest ``(last_use, key)``), plus TTL expiry on the
fleet tick clock so a hot entry must stay hot.  Both clocks are ticks,
never wall time — same seed, same evictions, byte-identical summaries.

Counters follow the two-tier obs convention: plain-int mirrors in
``counts`` feed deterministic summaries regardless of whether
telemetry is enabled, and the ``prefixstore.*`` instruments publish
the same increments under the zero-overhead contract.

Durability: `save_store`/`load_store` persist the whole store as one
file in the PR 9 snapshot format — a manifest line plus CRC'd
``meta``/``records`` sections — written with the same
mkstemp/fsync/replace/dir-fsync discipline as engine snapshots.  A
fleet warm restart reloads it; any validation failure raises the typed
`PrefixStoreCorruptError` and the frontend starts a fresh store (cold
cache, never a crash, never wrong bytes).
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import zlib

from attention_tpu import obs
from attention_tpu.obs import blackbox as _blackbox
from attention_tpu.engine.errors import PrefixStoreCorruptError
from attention_tpu.engine.snapshot import _fsync_dir, _jbytes
from attention_tpu.prefixstore.lease import LeaseTable
from attention_tpu.prefixstore.records import chain_key, chain_tokens

STORE_MAGIC = "atp-prefixstore"
STORE_VERSION = 1
#: the store's on-disk name inside a fleet snapshot directory — a
#: sibling of the per-replica snapshot subdirs, never matched by the
#: engine's ``snap-*`` scan
STORE_FILENAME = "prefixstore.atpstore"

_EXPORTS = obs.counter("prefixstore.exports",
                       "prefix-page records published to the store")
_IMPORTS = obs.counter("prefixstore.imports",
                       "chain imports spliced into an allocator")
_IMPORT_TOKENS = obs.counter("prefixstore.import_tokens",
                             "prompt tokens covered by imported pages")
_EVICTIONS = obs.counter("prefixstore.evictions",
                         "records dropped by TTL or the byte budget")
_CORRUPT = obs.counter("prefixstore.corrupt",
                       "records that failed validation (typed, "
                       "re-prefilled)")
_COALESCED = obs.counter("prefixstore.singleflight_coalesced",
                         "requests that waited behind a prefill lease "
                         "instead of prefilling")
_BYTES_GAUGE = obs.gauge("prefixstore.bytes",
                         "bytes of record payloads currently held")


@dataclasses.dataclass(frozen=True)
class PrefixStoreConfig:
    """Knobs of one fleet store; validated at frontend construction."""

    #: record-payload byte budget; LRU eviction keeps the store under it
    max_bytes: int = 1 << 22
    #: ticks an untouched record survives; None = no TTL
    ttl_ticks: int | None = 256
    #: single-flight lease window — a dead leader unblocks waiters
    #: this many ticks after its last acquire/refresh
    lease_ticks: int = 16

    def validate(self) -> None:
        if self.max_bytes < 1:
            raise ValueError(
                f"max_bytes must be >= 1, got {self.max_bytes}"
            )
        if self.ttl_ticks is not None and self.ttl_ticks < 1:
            raise ValueError(
                f"ttl_ticks must be >= 1 or None, got {self.ttl_ticks}"
            )
        if self.lease_ticks < 1:
            raise ValueError(
                f"lease_ticks must be >= 1, got {self.lease_ticks}"
            )


@dataclasses.dataclass
class _Entry:
    key: str
    blob: bytes
    nbytes: int
    created: int      # tick of first publication (TTL clock)
    last_use: int     # tick of last get/touch (LRU clock)
    seq: int          # insertion order (serialization order)


class PrefixStore:
    """Content-addressed record store + its single-flight lease table."""

    def __init__(self, config: PrefixStoreConfig | None = None):
        self.config = config or PrefixStoreConfig()
        self.config.validate()
        self._entries: dict[str, _Entry] = {}
        self._seq = 0
        self.total_bytes = 0
        self.leases = LeaseTable(self.config.lease_ticks)
        # plain-int mirrors: deterministic summary inputs whether or
        # not telemetry is on (the obs zero-overhead contract)
        self.counts: dict[str, int] = {
            "exports": 0,
            "imports": 0,
            "import_tokens": 0,
            "evictions": 0,
            "corrupt": 0,
            "singleflight_coalesced": 0,
        }

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    # -- budget ------------------------------------------------------------

    def _expired(self, entry: _Entry, now: int) -> bool:
        ttl = self.config.ttl_ticks
        return ttl is not None and entry.created + ttl <= now

    def _drop(self, key: str, *, count: bool = True,
              now: int = -1) -> None:
        entry = self._entries.pop(key)
        self.total_bytes -= entry.nbytes
        if count:
            self.counts["evictions"] += 1
            _EVICTIONS.inc()
            _blackbox.note("store_evict", tick=now, key=key[:12])
        _BYTES_GAUGE.set(float(self.total_bytes))

    def expire(self, *, now: int) -> int:
        """Drop every TTL-expired record; returns how many."""
        dead = sorted(k for k, e in self._entries.items()
                      if self._expired(e, now))
        for k in dead:
            self._drop(k, now=now)
        return len(dead)

    def evict_lru(self, *, now: int = -1) -> str | None:
        """Evict the least-recently-used record (tie-break by key, the
        allocator's ``(last_use, key)`` discipline); None when empty."""
        if not self._entries:
            return None
        victim = min(self._entries.values(),
                     key=lambda e: (e.last_use, e.key))
        self._drop(victim.key, now=now)
        return victim.key

    def evict_all(self, *, now: int = -1) -> int:
        """Drop everything (the chaos eviction-storm injector); every
        drop counts as an eviction."""
        n = len(self._entries)
        for key in sorted(self._entries):
            self._drop(key, now=now)
        return n

    # -- records -----------------------------------------------------------

    def put(self, key: str, blob: bytes, *, now: int) -> bool:
        """Publish one record under ``key``; True when newly stored.

        An existing key is only touched (the first publisher's copy
        stays canonical — content-addressed, so they agree anyway).
        TTL expiry runs first, then LRU eviction until the blob fits;
        a blob larger than the whole budget is refused."""
        entry = self._entries.get(key)
        if entry is not None and not self._expired(entry, now):
            entry.last_use = now
            return False
        self.expire(now=now)
        if len(blob) > self.config.max_bytes:
            return False
        while self.total_bytes + len(blob) > self.config.max_bytes:
            self.evict_lru(now=now)
        self._entries[key] = _Entry(
            key=key, blob=blob, nbytes=len(blob),
            created=now, last_use=now, seq=self._seq,
        )
        self._seq += 1
        self.total_bytes += len(blob)
        self.counts["exports"] += 1
        _EXPORTS.inc()
        _BYTES_GAUGE.set(float(self.total_bytes))
        return True

    def get(self, key: str, *, now: int) -> bytes | None:
        """The record bytes under ``key`` (LRU touch), or None."""
        entry = self._entries.get(key)
        if entry is None:
            return None
        if self._expired(entry, now):
            self._drop(key, now=now)
            return None
        entry.last_use = now
        return entry.blob

    def peek(self, key: str, *, now: int) -> bool:
        """Is ``key`` live, WITHOUT touching its LRU clock — the
        router-probe discipline (`BlockAllocator.peek_prefix`): losing
        a routing race must not refresh an entry."""
        entry = self._entries.get(key)
        return entry is not None and not self._expired(entry, now)

    def discard(self, key: str) -> None:
        """Drop ``key`` if present (does not count as an eviction —
        used when an importer found the record corrupt)."""
        if key in self._entries:
            self._drop(key, count=False)

    # -- chain probes ------------------------------------------------------

    def peek_chain(self, tokens, page_size: int, *, now: int) -> int:
        """Records present for the longest contiguous page chain of
        ``tokens``'s shareable prefix; side-effect-free (routing and
        the single-flight gate both call this every tick)."""
        toks = chain_tokens(tokens, page_size)
        if toks is None:
            return 0
        n = 0
        for i in range(page_size, len(toks) + 1, page_size):
            if not self.peek(chain_key(toks[:i]), now=now):
                break
            n += 1
        return n

    def has_chain(self, tokens, page_size: int, *, now: int) -> bool:
        """Does the store hold the WHOLE shareable chain of ``tokens``
        (the single-flight waiters' release condition)?"""
        toks = chain_tokens(tokens, page_size)
        if toks is None:
            return True  # nothing shareable: nothing to wait for
        return self.peek_chain(tokens, page_size, now=now) \
            == len(toks) // page_size

    # -- counter hooks (adapter/frontend call sites) -----------------------

    def note_import(self, *, pages: int, tokens: int) -> None:
        self.counts["imports"] += 1
        self.counts["import_tokens"] += tokens
        _IMPORTS.inc()
        _IMPORT_TOKENS.inc(tokens)

    def note_corrupt(self, key: str | None = None) -> None:
        """A record (or, with no ``key``, the persisted store file)
        failed validation: count it and drop the entry so the next
        miss re-prefills and re-publishes clean bytes."""
        self.counts["corrupt"] += 1
        _CORRUPT.inc()
        if key is not None:
            self.discard(key)

    def note_coalesced(self) -> None:
        self.counts["singleflight_coalesced"] += 1
        _COALESCED.inc()


# -- durability ------------------------------------------------------------


def serialize_store(store: PrefixStore) -> bytes:
    """Deterministic store bytes: manifest line + CRC'd ``meta`` and
    ``records`` sections (records concatenated in insertion order)."""
    entries = sorted(store._entries.values(), key=lambda e: e.seq)
    meta = {
        "seq": store._seq,
        "counts": {k: store.counts[k] for k in sorted(store.counts)},
        "entries": [
            {"key": e.key, "nbytes": e.nbytes, "created": e.created,
             "last_use": e.last_use, "seq": e.seq}
            for e in entries
        ],
    }
    sections = [("meta", _jbytes(meta)),
                ("records", b"".join(e.blob for e in entries))]
    manifest = {
        "magic": STORE_MAGIC,
        "version": STORE_VERSION,
        "sections": [
            {"name": name, "nbytes": len(payload),
             "crc32": zlib.crc32(payload)}
            for name, payload in sections
        ],
    }
    return (_jbytes(manifest) + b"\n"
            + b"".join(payload for _, payload in sections))


def save_store(store: PrefixStore, path: str) -> dict:
    """Write the store durably and atomically (the snapshot
    mkstemp/fsync/replace/dir-fsync discipline); ``{path, nbytes}``."""
    blob = serialize_store(store)
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(directory)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return {"path": path, "nbytes": len(blob)}


def _corrupt_file(path: str, why: str) -> PrefixStoreCorruptError:
    return PrefixStoreCorruptError(f"{path}: {why}")


def load_store(path: str,
               config: PrefixStoreConfig | None = None) -> PrefixStore:
    """Reconstruct a store from ``path``; `PrefixStoreCorruptError` on
    any validation failure (the frontend's cue to start cold).

    Record blobs are NOT decoded here — each carries its own CRCs and
    is re-validated at import time, so a single poisoned record costs
    one re-prefill later, not the whole store now."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise _corrupt_file(path, f"unreadable: {e}")
    nl = blob.find(b"\n")
    if nl < 0:
        raise _corrupt_file(path, "no manifest line")
    try:
        manifest = json.loads(blob[:nl])
    except ValueError:
        raise _corrupt_file(path, "unparseable manifest")
    if not isinstance(manifest, dict) \
            or manifest.get("magic") != STORE_MAGIC:
        raise _corrupt_file(path, "bad magic (not a prefix store)")
    if manifest.get("version") != STORE_VERSION:
        raise _corrupt_file(
            path,
            f"unsupported store version {manifest.get('version')!r} "
            f"(reader speaks {STORE_VERSION})",
        )
    sections: dict[str, bytes] = {}
    offset = nl + 1
    try:
        table = [(s["name"], int(s["nbytes"]), int(s["crc32"]))
                 for s in manifest["sections"]]
    except (KeyError, TypeError, ValueError):
        raise _corrupt_file(path, "malformed section table")
    for name, nbytes, crc in table:
        payload = blob[offset:offset + nbytes]
        if len(payload) != nbytes:
            raise _corrupt_file(
                path,
                f"section {name!r} truncated "
                f"({len(payload)}/{nbytes} bytes)",
            )
        if zlib.crc32(payload) != crc:
            raise _corrupt_file(path,
                                f"section {name!r} checksum mismatch")
        sections[name] = payload
        offset += nbytes
    if offset != len(blob):
        raise _corrupt_file(path, f"{len(blob) - offset} trailing bytes")
    for name in ("meta", "records"):
        if name not in sections:
            raise _corrupt_file(path, f"missing section {name!r}")
    try:
        meta = json.loads(sections["meta"])
        seq = int(meta["seq"])
        counts = {str(k): int(v) for k, v in meta["counts"].items()}
        index = [
            (str(e["key"]), int(e["nbytes"]), int(e["created"]),
             int(e["last_use"]), int(e["seq"]))
            for e in meta["entries"]
        ]
    except (KeyError, TypeError, ValueError):
        raise _corrupt_file(path, "undecodable meta section")
    records = sections["records"]
    if sum(n for _, n, _, _, _ in index) != len(records):
        raise _corrupt_file(
            path, "records section does not match the entry index"
        )
    store = PrefixStore(config)
    for key in store.counts:
        store.counts[key] = counts.get(key, 0)
    store._seq = seq
    pos = 0
    for key, nbytes, created, last_use, eseq in index:
        store._entries[key] = _Entry(
            key=key, blob=records[pos:pos + nbytes], nbytes=nbytes,
            created=created, last_use=last_use, seq=eseq,
        )
        store.total_bytes += nbytes
        pos += nbytes
    # a reader with a smaller budget trims silently: a config change,
    # not fleet churn, so the eviction counter stays honest
    while store.total_bytes > store.config.max_bytes:
        victim = min(store._entries.values(),
                     key=lambda e: (e.last_use, e.key))
        store._drop(victim.key, count=False)
    _BYTES_GAUGE.set(float(store.total_bytes))
    return store
