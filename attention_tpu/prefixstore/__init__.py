"""Global prefix tier: content-addressed fleet-wide KV reuse.

The page-granular prefix cache (PR 2) dies with its replica; at fleet
scale that means N replicas each re-prefilling the same hot system
prompts, few-shot preambles, and RAG headers.  This package lifts the
cache one level: committed prefix pages are exported as CRC'd records
keyed by token-chain hash into one shared in-process `PrefixStore`,
imported on miss by any geometry-compatible replica, and guarded by
tick-expiring single-flight leases so a storm of identical prompts
prefills exactly once fleet-wide.

    records.py   one page = one self-validating record (snapshot
                 section format; per-shard ``pools.<s>`` head slices)
    store.py     budgeted TTL+LRU record store, obs counters,
                 snapshot-grade durable save/load
    lease.py     deterministic tick-expiring single-flight leases
    adapter.py   engine seams: export on commit, import before prefill

Integrity doctrine, same as snapshots: corruption is a typed
`PrefixStoreCorruptError` and costs a re-prefill; geometry or
fingerprint mismatch is a miss; wrong tokens are never acceptable.
"""

from attention_tpu.prefixstore.adapter import (  # noqa: F401
    engine_geometry,
    export_chain,
    fleet_fingerprint,
    import_chain,
)
from attention_tpu.prefixstore.lease import LeaseTable  # noqa: F401
from attention_tpu.prefixstore.records import (  # noqa: F401
    PrefixRecord,
    chain_key,
    chain_tokens,
    decode_record,
    encode_record,
    page_geometry,
)
from attention_tpu.prefixstore.store import (  # noqa: F401
    STORE_FILENAME,
    PrefixStore,
    PrefixStoreConfig,
    load_store,
    save_store,
    serialize_store,
)
