"""Single-flight prefill leases: a storm prefills once fleet-wide.

When K identical prompts arrive across replicas in the same window,
only the first should pay the prefill; the rest wait and import the
exported pages.  The coordination primitive is a *lease* on the token
chain's content key: the first request (deterministic arrival order)
acquires it, routes, prefills, and commits — at which point the
exported chain lands in the store and every waiter's "is it there
yet" check flips to yes.  Waiters never block a tick loop; they sit
in the frontend's store-wait queue and re-check each tick.

Failure is handled by TICK-DRIVEN EXPIRY, not by error paths: a lease
holder that dies mid-prefill (replica kill, shed, timeout) simply
stops refreshing, the lease expires ``lease_ticks`` after acquisition,
and the next waiter in arrival order takes over.  Expiry is computed
from the frontend tick clock only, so the same seed storms the same
way every run.  Misuse — releasing someone else's lease, acquiring
over a live foreign lease — raises the typed `PrefixLeaseError`;
those are caller bugs, not fleet weather.
"""

from __future__ import annotations

import dataclasses

from attention_tpu.engine.errors import PrefixLeaseError


@dataclasses.dataclass
class _Lease:
    key: str        # chain_key of the token prefix being prefilled
    owner: str      # frontend request id holding the flight
    acquired: int   # tick the current owner took (or refreshed) it
    expires: int    # first tick at which the lease no longer holds


class LeaseTable:
    """Tick-expiring single-flight registry, keyed by chain hash."""

    def __init__(self, lease_ticks: int):
        if lease_ticks < 1:
            raise ValueError(
                f"lease_ticks must be >= 1, got {lease_ticks}"
            )
        self.lease_ticks = lease_ticks
        self._leases: dict[str, _Lease] = {}
        #: keys dropped by the most recent :meth:`expire` sweep, in
        #: sorted order — the frontend's flight recorder reads this to
        #: note each dead-leader expiry as a typed causal event
        self.last_expired: list[str] = []

    def __len__(self) -> int:
        return len(self._leases)

    def expire(self, *, now: int) -> int:
        """Drop every lease whose window has closed; returns count."""
        dead = sorted(k for k, l in self._leases.items()
                      if l.expires <= now)
        for k in dead:
            del self._leases[k]
        self.last_expired = dead
        return len(dead)

    def holder(self, key: str, *, now: int) -> str | None:
        """Current live owner of ``key``, expiring lazily."""
        lease = self._leases.get(key)
        if lease is None:
            return None
        if lease.expires <= now:
            del self._leases[key]
            return None
        return lease.owner

    def acquire(self, key: str, owner: str, *, now: int) -> None:
        """Take (or refresh) the flight on ``key`` for ``owner``.

        Re-acquiring one's own lease refreshes the expiry — a retried
        leader keeps leading.  Acquiring over a live foreign lease is
        misuse (callers must consult `holder` first and coalesce)."""
        current = self.holder(key, now=now)
        if current is not None and current != owner:
            raise PrefixLeaseError(
                f"lease on {key[:12]}… is held by {current!r}; "
                f"{owner!r} must coalesce behind it, not acquire"
            )
        self._leases[key] = _Lease(
            key=key, owner=owner, acquired=now,
            expires=now + self.lease_ticks,
        )

    def release(self, key: str, owner: str, *, now: int) -> None:
        """Give up ``key``.  Releasing an absent/expired lease is a
        no-op (finalize paths are idempotent); releasing a live lease
        someone ELSE holds is misuse."""
        current = self.holder(key, now=now)
        if current is None:
            return
        if current != owner:
            raise PrefixLeaseError(
                f"lease on {key[:12]}… is held by {current!r}, "
                f"not releaser {owner!r}"
            )
        del self._leases[key]

    def release_owner(self, owner: str) -> int:
        """Drop every lease ``owner`` holds (the frontend's terminal
        funnel calls this, so a finished/shed leader frees its flights
        immediately instead of waiting out the expiry window)."""
        dead = sorted(k for k, l in self._leases.items()
                      if l.owner == owner)
        for k in dead:
            del self._leases[k]
        return len(dead)

    def active(self, *, now: int) -> list[tuple[str, str]]:
        """Live ``(key, owner)`` pairs in sorted key order — the chaos
        lease-holder-kill injector's target list."""
        self.expire(now=now)
        return sorted((l.key, l.owner) for l in self._leases.values())
