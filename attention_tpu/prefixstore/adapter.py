"""Engine ⇄ store glue: export committed pages, import on miss.

The two host-side seams the global prefix tier hooks into the engine:

* `export_chain` — called from `ServingEngine._commit_prefix` right
  after the allocator publishes a prompt's full pages locally.  Each
  page becomes one CRC'd record (already-published chains are just
  touched, keeping them hot); a mesh engine writes its per-shard head
  slices in the ``pools.<s>`` layout.
* `import_chain` — called from request intake, BEFORE admission runs
  its local `lookup_prefix`.  It extends the allocator's cached chain
  with matching store records: validate (corrupt → typed, counted,
  dropped, re-prefill), gate on fingerprint + geometry (mismatch is a
  miss), verify the exact token chain (hash collisions degrade to a
  miss), allocate pages watermark-aware (`for_decode=False`, so a
  busy replica refuses the import before it refuses decode appends),
  write the payloads into the per-layer pools, commit, then drop the
  importer's reference — the drained end-state is pages held by the
  prefix cache at refcount 1, exactly what a locally computed chain
  leaves and what the chaos quiescence invariant demands.

Both paths are no-ops when ``engine.prefix_store`` is None, so a
storeless fleet is byte-identical to the pre-tier code.
"""

from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from attention_tpu.engine.errors import PrefixStoreCorruptError
from attention_tpu.engine.snapshot import _dtype_name, model_fingerprint
from attention_tpu.obs import blackbox as _blackbox
from attention_tpu.ops.paged import OutOfPagesError
from attention_tpu.prefixstore.records import (
    chain_key,
    decode_record,
    encode_record,
    page_geometry,
)


def fleet_fingerprint(engine) -> dict:
    """`model_fingerprint` PLUS a digest of the actual weights.

    Snapshots only ever reload into the fleet that wrote them, so the
    architecture fingerprint suffices there.  Store records cross
    fleet boundaries (a persisted store can outlive any one fleet),
    and two same-architecture models with different params would pass
    the architecture gate while holding each other's KV — wrong
    tokens, the one unacceptable outcome.  Hashed once per engine
    incarnation (leaf order is the params tree order, deterministic
    for a fixed structure) and cached on the engine."""
    cached = getattr(engine, "_prefixstore_fingerprint", None)
    if cached is not None:
        return cached
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(engine.params):
        arr = np.asarray(leaf)
        h.update(str((arr.dtype.str, arr.shape)).encode())
        h.update(arr.tobytes())
    fp = dict(model_fingerprint(engine.model),
              params_sha256=h.hexdigest())
    engine._prefixstore_fingerprint = fp
    return fp


def engine_geometry(engine) -> dict:
    """The page geometry this engine exports under / imports against."""
    pool = engine._k_pools[0]
    return page_geometry(
        num_kv_heads=pool.shape[1],
        page_size=engine.config.page_size,
        head_dim=pool.shape[3],
        layers=len(engine._k_pools),
        dtype=_dtype_name(pool.dtype),
    )


def _page_arrays(engine, page: int) -> list[np.ndarray]:
    """Host copies of one page's K then V arrays across layers."""
    return [np.asarray(pool[page])
            for pool in (*engine._k_pools, *engine._v_pools)]


def export_chain(engine, tokens, pages, *, now: int) -> int:
    """Publish the committed chain ``pages`` (covering the full pages
    of ``tokens``) into the engine's store; returns records newly
    stored.  Safe to call with any committed prefix — existing records
    are touched, not rewritten."""
    store = engine.prefix_store
    if store is None:
        return 0
    ps = engine.config.page_size
    toks = tuple(int(t) for t in tokens)
    full = min(len(toks) // ps, len(pages))
    if full == 0:
        return 0
    fp = fleet_fingerprint(engine)
    geo = engine_geometry(engine)
    shards = engine.config.mesh_shards or 1
    stored = 0
    for i in range(1, full + 1):
        key_toks = toks[: i * ps]
        key = chain_key(key_toks)
        if store.get(key, now=now) is not None:
            continue  # already published; the get kept it hot
        blob = encode_record(
            tokens=key_toks,
            arrays=_page_arrays(engine, pages[i - 1]),
            fingerprint=fp, geometry=geo, shards=shards,
        )
        if store.put(key, blob, now=now):
            stored += 1
    return stored


def import_chain(engine, tokens, *, now: int) -> int:
    """Splice matching store records onto the engine's local prefix
    chain for ``tokens``; returns prompt tokens newly covered (0 on
    miss, mismatch, no store, or page pressure).

    Never raises: corruption is counted + dropped (the caller's later
    cold prefill is the recovery), and an allocator refusal under the
    watermark simply aborts the import."""
    store = engine.prefix_store
    if store is None:
        return 0
    ps = engine.config.page_size
    toks = tuple(int(t) for t in tokens)
    limit = (len(toks) - 1) // ps
    local = engine.allocator.peek_prefix(toks)
    if limit <= local:
        return 0
    fp = fleet_fingerprint(engine)
    geo = engine_geometry(engine)
    recs = []
    for i in range(local + 1, limit + 1):
        key_toks = toks[: i * ps]
        key = chain_key(key_toks)
        blob = store.get(key, now=now)
        if blob is None:
            break
        try:
            rec = decode_record(blob)
        except PrefixStoreCorruptError:
            store.note_corrupt(key)
            _blackbox.note(
                "store_corrupt", tick=now,
                replica=getattr(engine, "trace_replica", None),
                incarnation=getattr(engine, "trace_incarnation", 0),
                step=engine.current_step, key=key[:12])
            break
        if rec.fingerprint != fp or rec.geometry != geo:
            break  # another fleet's pages: a miss, never corruption
        if rec.tokens != key_toks:
            break  # hash collision: degrade to a miss
        recs.append(rec)
    if not recs:
        return 0
    try:
        pages = engine.allocator.allocate(len(recs), for_decode=False)
    except OutOfPagesError:
        return 0
    depth = len(engine._k_pools)
    idx = jnp.asarray(pages, jnp.int32)
    dtype = engine._k_pools[0].dtype
    for layer in range(depth):
        k_stack = jnp.asarray(
            np.stack([r.arrays[layer] for r in recs]), dtype)
        v_stack = jnp.asarray(
            np.stack([r.arrays[depth + layer] for r in recs]), dtype)
        engine._k_pools[layer] = engine._place_pool(
            engine._k_pools[layer].at[idx].set(k_stack))
        engine._v_pools[layer] = engine._place_pool(
            engine._v_pools[layer].at[idx].set(v_stack))
    chain = engine.allocator.cached_chain(toks)
    covered = local + len(recs)
    engine.allocator.commit_prefix(
        toks[: covered * ps], chain + pages, now=now
    )
    # drop the importer's reference: the cache's own incref (taken in
    # commit_prefix) is now the sole owner, matching a locally
    # computed chain after its request drains
    engine.allocator.free(pages)
    store.note_import(pages=len(recs), tokens=len(recs) * ps)
    _blackbox.note(
        "store_import", tick=now,
        replica=getattr(engine, "trace_replica", None),
        incarnation=getattr(engine, "trace_incarnation", 0),
        step=engine.current_step,
        pages=len(recs), tokens=len(recs) * ps)
    return len(recs) * ps
