"""Content-addressed prefix-page records: one page of KV, CRC'd.

The wire unit of the global prefix tier.  A record holds the exported
K/V payload of ONE committed prefix page together with everything a
stranger replica needs to decide whether the page is usable:

* the exact token chain the page's KV encodes (``tokens``) — the
  store is keyed by the chain's sha256, but the importer re-checks the
  full token tuple, so a hash collision degrades to a miss, never to
  another prompt's KV (the allocator's exact-tuple-key discipline,
  lifted fleet-wide);
* the exporter's fleet fingerprint (`adapter.fleet_fingerprint`:
  architecture PLUS a params digest — store records cross fleet
  boundaries, so same-architecture different-weights models must not
  exchange KV) and pool geometry — any mismatch is a MISS (cold-start
  cue), never corruption;
* per-shard head slices in the snapshot ``pools.<s>`` layout: an
  S-shard mesh exporter writes S sections, each the shard's contiguous
  KV-head slice of every per-layer page array, independently CRC'd.
  The importer reassembles along the head dim and re-places on its own
  mesh, so shard-count mismatch between exporter and importer is fine
  by construction — only *geometry* (heads/page_size/head_dim/layers/
  dtype) gates reuse.

On disk/in store: one ASCII JSON manifest line (magic, version,
shards, per-section byte counts and CRC32s) followed by concatenated
section payloads — the PR 9 snapshot format in miniature.  Any
structural damage raises the typed `PrefixStoreCorruptError`; the
import path treats that as "drop the entry, re-prefill", because a
corrupt record may cost compute but never a wrong token.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import zlib

import numpy as np

from attention_tpu.engine.errors import PrefixStoreCorruptError
from attention_tpu.engine.snapshot import _jbytes, _np_dtype

RECORD_MAGIC = "atp-prefixrec"
RECORD_VERSION = 1


def chain_key(tokens) -> str:
    """Content address of one token chain: sha256 over the canonical
    JSON encoding of the token list.  Collisions are defended against
    at import time (records carry the full chain), so the digest is an
    index key, not a correctness boundary."""
    return hashlib.sha256(_jbytes([int(t) for t in tokens])).hexdigest()


def chain_tokens(tokens, page_size: int) -> tuple[int, ...] | None:
    """The shareable page-aligned prefix of ``tokens`` — the longest
    whole-page chain that still leaves >= 1 token for the prefill that
    produces first-token logits (the allocator's ``(n-1)//page_size``
    limit).  None when no full page is shareable."""
    toks = tuple(int(t) for t in tokens)
    limit = (len(toks) - 1) // page_size
    if limit < 1:
        return None
    return toks[: limit * page_size]


@dataclasses.dataclass(frozen=True)
class PrefixRecord:
    """One decoded prefix page: validated metadata + host arrays."""

    tokens: tuple[int, ...]          # exact chain this page completes
    fingerprint: dict                # exporter's model_fingerprint
    geometry: dict                   # page geometry (see page_geometry)
    arrays: tuple                    # 2*layers np arrays, K then V,
    #                                  each (num_kv_heads, page_size,
    #                                  head_dim)


def page_geometry(*, num_kv_heads: int, page_size: int, head_dim: int,
                  layers: int, dtype: str) -> dict:
    """The reuse gate: two engines may exchange pages iff this dict
    (plus the model fingerprint) matches exactly."""
    return {
        "num_kv_heads": int(num_kv_heads),
        "page_size": int(page_size),
        "head_dim": int(head_dim),
        "layers": int(layers),
        "dtype": str(dtype),
    }


def encode_record(*, tokens, arrays, fingerprint: dict, geometry: dict,
                  shards: int = 1) -> bytes:
    """Serialize one page as a self-validating record.

    ``arrays``: the page's 2*layers host arrays (K pools then V
    pools), each ``(num_kv_heads, page_size, head_dim)``.  ``shards``
    writes that many ``pools.<s>`` head-slice sections — the exporting
    mesh engine's native layout."""
    heads = geometry["num_kv_heads"]
    if shards < 1 or heads % shards:
        raise ValueError(
            f"shards {shards} does not divide num_kv_heads {heads}"
        )
    meta = {
        "tokens": [int(t) for t in tokens],
        "fingerprint": fingerprint,
        "geometry": geometry,
    }
    hh = heads // shards
    hosted = [np.asarray(a) for a in arrays]
    sections = [("meta", _jbytes(meta))] + [
        (f"pools.{s}",
         b"".join(a[s * hh:(s + 1) * hh].tobytes() for a in hosted))
        for s in range(shards)
    ]
    manifest = {
        "magic": RECORD_MAGIC,
        "version": RECORD_VERSION,
        "shards": shards,
        "sections": [
            {"name": name, "nbytes": len(payload),
             "crc32": zlib.crc32(payload)}
            for name, payload in sections
        ],
    }
    return (_jbytes(manifest) + b"\n"
            + b"".join(payload for _, payload in sections))


def _corrupt(why: str) -> PrefixStoreCorruptError:
    return PrefixStoreCorruptError(f"prefix record: {why}")


def _read_sections(blob: bytes) -> tuple[dict, dict[str, bytes]]:
    """Manifest + checksummed sections, or the typed corrupt raise."""
    nl = blob.find(b"\n")
    if nl < 0:
        raise _corrupt("no manifest line")
    try:
        manifest = json.loads(blob[:nl])
    except ValueError:
        raise _corrupt("unparseable manifest")
    if not isinstance(manifest, dict) \
            or manifest.get("magic") != RECORD_MAGIC:
        raise _corrupt("bad magic (not a prefix record)")
    if manifest.get("version") != RECORD_VERSION:
        raise _corrupt(
            f"unsupported record version {manifest.get('version')!r} "
            f"(reader speaks {RECORD_VERSION})"
        )
    shards = manifest.get("shards", 1)
    if not isinstance(shards, int) or isinstance(shards, bool) \
            or shards < 1:
        raise _corrupt(f"bad shards count {shards!r}")
    try:
        entries = [(s["name"], int(s["nbytes"]), int(s["crc32"]))
                   for s in manifest["sections"]]
    except (KeyError, TypeError, ValueError):
        raise _corrupt("malformed section table")
    sections: dict[str, bytes] = {}
    offset = nl + 1
    for name, nbytes, crc in entries:
        payload = blob[offset:offset + nbytes]
        if len(payload) != nbytes:
            raise _corrupt(
                f"section {name!r} truncated "
                f"({len(payload)}/{nbytes} bytes)"
            )
        if zlib.crc32(payload) != crc:
            raise _corrupt(f"section {name!r} checksum mismatch")
        sections[name] = payload
        offset += nbytes
    if offset != len(blob):
        raise _corrupt(f"{len(blob) - offset} trailing bytes")
    required = ("meta", *(f"pools.{s}" for s in range(shards)))
    for name in required:
        if name not in sections:
            raise _corrupt(f"missing section {name!r}")
    return manifest, sections


def decode_record(blob: bytes) -> PrefixRecord:
    """Validate + reassemble one record; `PrefixStoreCorruptError` on
    any structural damage.  Shard slices are concatenated back along
    the head dim, so the decoded arrays are shard-count agnostic."""
    manifest, sections = _read_sections(blob)
    shards = manifest.get("shards", 1)
    try:
        meta = json.loads(sections["meta"])
        tokens = tuple(int(t) for t in meta["tokens"])
        fingerprint = meta["fingerprint"]
        geometry = meta["geometry"]
        heads = int(geometry["num_kv_heads"])
        page_size = int(geometry["page_size"])
        head_dim = int(geometry["head_dim"])
        layers = int(geometry["layers"])
        dtype = _np_dtype(geometry["dtype"])
    except (KeyError, TypeError, ValueError):
        raise _corrupt("undecodable meta section")
    if heads < 1 or heads % shards:
        raise _corrupt(
            f"shards {shards} does not divide num_kv_heads {heads}"
        )
    hh = heads // shards
    slice_bytes = hh * page_size * head_dim * dtype.itemsize
    per_shard = []
    for s in range(shards):
        payload = sections[f"pools.{s}"]
        if len(payload) != 2 * layers * slice_bytes:
            raise _corrupt(
                f"section 'pools.{s}' carries {len(payload)} bytes, "
                f"geometry implies {2 * layers * slice_bytes}"
            )
        per_shard.append([
            np.frombuffer(
                payload[i * slice_bytes:(i + 1) * slice_bytes], dtype
            ).reshape(hh, page_size, head_dim)
            for i in range(2 * layers)
        ])
    arrays = tuple(
        np.concatenate([per_shard[s][i] for s in range(shards)], axis=0)
        if shards > 1 else per_shard[0][i]
        for i in range(2 * layers)
    )
    return PrefixRecord(tokens=tokens, fingerprint=fingerprint,
                        geometry=geometry, arrays=arrays)
