"""Benchmark suite: ablations and scaling sweeps (reference methodology).

Reproduces the reference's performance-analysis methodology (report
Q2-Q7, README.md:95-121) with TPU-native treatments:

  * **Ablation table** (Q2): the reference isolates AVX-512, mixed
    precision, and pipeline overlap against an unoptimized MPI baseline.
    The TPU analogs, each against the un-fused fp32 XLA baseline:
      - ``fused``      — Pallas flash kernel, fp32 (the SIMD/fusion axis)
      - ``mixed``      — un-fused XLA, bf16 in / fp32 accum (the
                         d2f/f2d mixed-precision axis)
      - ``overlap``    — distributed kv-sharded path (the comm/compute
                         overlap axis; meaningful on a multi-device mesh)
      - ``full``       — fused + bf16 (+ sharding when a mesh is given)
  * **Strong scaling** (Q4/Q7): fixed problem, growing mesh.
  * **Weak scaling** (Q7): problem grows with the mesh (n per device
    fixed), the reference's M/P families.

All sweeps emit structured :class:`RunRecord` rows (SURVEY §5) rather
than printf lines.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from attention_tpu.ops.flash import BlockSizes, flash_attention
from attention_tpu.ops.reference import attention_xla
from attention_tpu.parallel.kv_sharded import kv_sharded_attention
from attention_tpu.parallel.mesh import default_mesh
from attention_tpu.parallel.ring import ring_attention
from attention_tpu.utils.flops import attention_flops, utilization
from attention_tpu.utils.profiling import RunRecord
from attention_tpu.utils.timing import benchmark_attention


def _record(config, backend, m, n, dk, dv, dtype, timing, *, n_devices=1,
            mesh_axes=None, extra=None) -> RunRecord:
    flops = attention_flops(m, n, dk, dv)
    dev = jax.devices()[0]
    return RunRecord(
        config=config,
        backend=backend,
        m=m, n=n, dk=dk, dv=dv,
        dtype=jnp.dtype(dtype).name,
        best_us=timing.best_us,
        median_us=timing.median_s * 1e6,
        gflops_per_chip=flops / timing.best_s / 1e9 / n_devices,
        utilization=utilization(flops, timing.best_s, dev) / n_devices,
        device_kind=getattr(dev, "device_kind", "unknown"),
        n_devices=n_devices,
        mesh_axes=dict(mesh_axes) if mesh_axes else None,
        extra=extra,
    )


def _qkv(m, n, dk, dv, dtype):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return (
        jax.random.normal(ks[0], (m, dk), dtype),
        jax.random.normal(ks[1], (n, dk), dtype),
        jax.random.normal(ks[2], (n, dv), dtype),
    )


def ablation_table(
    m: int = 4096,
    n: int = 4096,
    dk: int = 128,
    dv: int = 128,
    *,
    repeats: int = 5,
    block_sizes: BlockSizes | None = None,
    mesh=None,
) -> dict[str, RunRecord]:
    """The Q2 ablation: each optimization axis alone, then combined.

    Returns records keyed by variant; ``speedup vs baseline`` =
    baseline.best_us / variant.best_us (the reference's relative-speedup
    definition, README.md:95-102).
    """
    bs = block_sizes or BlockSizes()
    variants: dict[str, RunRecord] = {}

    qf, kf, vf = _qkv(m, n, dk, dv, jnp.float32)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (qf, kf, vf))

    t = benchmark_attention(attention_xla, qf, kf, vf, repeats=repeats)
    variants["baseline"] = _record("ablation", "xla-f32", m, n, dk, dv,
                                   "float32", t)
    t = benchmark_attention(flash_attention, qf, kf, vf, block_sizes=bs, repeats=repeats)
    variants["fused"] = _record("ablation", "flash-f32", m, n, dk, dv,
                                "float32", t)
    t = benchmark_attention(attention_xla, qb, kb, vb, repeats=repeats)
    variants["mixed"] = _record("ablation", "xla-bf16", m, n, dk, dv,
                                "bfloat16", t)
    if mesh is not None:
        t = benchmark_attention(
            kv_sharded_attention, qf, kf, vf, mesh=mesh, block_sizes=bs,
            repeats=repeats,
        )
        variants["overlap"] = _record(
            "ablation", "kv-sharded-f32", m, n, dk, dv, "float32", t,
            n_devices=mesh.devices.size, mesh_axes=mesh.shape,
        )
        t = benchmark_attention(
            kv_sharded_attention, qb, kb, vb, mesh=mesh, block_sizes=bs,
            repeats=repeats,
        )
        variants["full"] = _record(
            "ablation", "kv-sharded-bf16", m, n, dk, dv, "bfloat16", t,
            n_devices=mesh.devices.size, mesh_axes=mesh.shape,
        )
    else:
        t = benchmark_attention(flash_attention, qb, kb, vb, block_sizes=bs,
                      repeats=repeats)
        variants["full"] = _record("ablation", "flash-bf16", m, n, dk, dv,
                                   "bfloat16", t)
    base = variants["baseline"].best_us
    for name, rec in variants.items():
        rec.extra = {**(rec.extra or {}), "speedup_vs_baseline": base / rec.best_us}
    return variants


def strong_scaling(
    m: int = 4096,
    n: int = 8192,
    dk: int = 128,
    dv: int = 128,
    *,
    device_counts=(1, 2, 4, 8),
    backend: str = "kv-sharded",
    repeats: int = 3,
    block_sizes: BlockSizes | None = None,
    dtype=jnp.bfloat16,
) -> list[RunRecord]:
    """Fixed problem, growing mesh (report Q4/Q7)."""
    bs = block_sizes or BlockSizes()
    fn = {"kv-sharded": kv_sharded_attention, "ring": ring_attention}[backend]
    q, k, v = _qkv(m, n, dk, dv, dtype)
    out = []
    for r in sorted(device_counts):
        if r > len(jax.devices()):
            continue
        mesh = default_mesh("kv" if backend == "kv-sharded" else "sp",
                            devices=jax.devices()[:r])
        t = benchmark_attention(fn, q, k, v, mesh=mesh, block_sizes=bs, repeats=repeats)
        out.append(
            _record("strong_scaling", backend, m, n, dk, dv, dtype, t,
                    n_devices=r, mesh_axes=mesh.shape)
        )
    if not out:
        raise ValueError(
            f"no device_counts {device_counts} fit the "
            f"{len(jax.devices())} available devices"
        )
    base = out[0].best_us
    for rec in out:
        rec.extra = {"speedup_vs_smallest": base / rec.best_us}
    return out


def placement_table(
    m: int = 2048,
    n: int = 8192,
    dk: int = 128,
    dv: int = 128,
    *,
    n_devices: int | None = None,
    repeats: int = 3,
    block_sizes: BlockSizes | None = None,
    dtype=jnp.bfloat16,
) -> dict[str, RunRecord]:
    """Device-order study — the reference's process-placement experiment
    (report Q5: 16 procs on 1/2/4 nodes, `images/process_placement.png`)
    rebuilt for a TPU mesh: the same 1D kv mesh laid over the devices in
    identity / reversed / strided order.  Device order decides which
    pmax/psum hops ride adjacent ICI links, the analog of ranks sharing
    a node vs crossing the fabric.  (On the virtual CPU mesh all orders
    cost the same — the point there is methodology, not numbers.)
    """
    bs = block_sizes or BlockSizes()
    devs = jax.devices()[: n_devices or len(jax.devices())]
    r = len(devs)
    orders = {"identity": devs, "reversed": devs[::-1]}
    if r >= 4 and r % 2 == 0:
        orders["strided"] = devs[0::2] + devs[1::2]
    q, k, v = _qkv(m, n, dk, dv, dtype)
    out: dict[str, RunRecord] = {}
    for name, order in orders.items():
        mesh = jax.sharding.Mesh(list(order), ("kv",))
        t = benchmark_attention(kv_sharded_attention, q, k, v, mesh=mesh,
                                block_sizes=bs, repeats=repeats)
        out[name] = _record("placement", "kv-sharded", m, n, dk, dv, dtype,
                            t, n_devices=r, mesh_axes=mesh.shape)
    base = out["identity"].best_us
    for rec in out.values():
        rec.extra = {"relative_time_vs_identity": rec.best_us / base}
    return out


def weak_scaling(
    n_per_device: int = 2048,
    m: int = 2048,
    dk: int = 128,
    dv: int = 128,
    *,
    device_counts=(1, 2, 4, 8),
    backend: str = "kv-sharded",
    repeats: int = 3,
    block_sizes: BlockSizes | None = None,
    dtype=jnp.bfloat16,
) -> list[RunRecord]:
    """KV length grows with the mesh: n = n_per_device * R (report Q7's
    M/P families).  Flat time over R = perfect weak scaling."""
    bs = block_sizes or BlockSizes()
    fn = {"kv-sharded": kv_sharded_attention, "ring": ring_attention}[backend]
    out = []
    for r in sorted(device_counts):
        if r > len(jax.devices()):
            continue
        n = n_per_device * r
        q, k, v = _qkv(m, n, dk, dv, dtype)
        mesh = default_mesh("kv" if backend == "kv-sharded" else "sp",
                            devices=jax.devices()[:r])
        t = benchmark_attention(fn, q, k, v, mesh=mesh, block_sizes=bs, repeats=repeats)
        out.append(
            _record("weak_scaling", backend, m, n, dk, dv, dtype, t,
                    n_devices=r, mesh_axes=mesh.shape,
                    extra={"n_per_device": n_per_device})
        )
    return out
