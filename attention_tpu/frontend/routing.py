"""Session-affine, prefix-cache-aware request routing.

The whole point of routing carefully is that the prefix cache is
per-replica: a shared prompt prefix committed on replica A is worthless
to a request routed to replica B.  The router therefore decides, in
strict deterministic priority order:

1. **Prefix affinity** — probe every alive replica's prefix cache
   (side-effect-free `peek_prefix`) and route to the one holding the
   longest committed page prefix of this prompt.  Cache hits survive
   routing by construction.
2. **Store hit** — with a fleet prefix store attached
   (`attention_tpu.prefixstore`, ISSUE 17) and no replica holding a
   LONGER local chain, a store chain hit means ANY geometry-compatible
   replica can import the pages at admission; route least-loaded
   (spreading the herd is now free) and let the import do the rest.
   A strictly longer local chain still wins — pages already resident
   beat pages that must be copied in.
3. **Session stickiness** — a request carrying a ``session`` tag
   follows its predecessors' replica.  This covers the window where a
   tenant's first request is still PREFILLING: its prefix is not
   committed yet, so a naive prefix-probe scatters the burst across
   replicas and the cache never forms.  Stickiness holds the herd
   together until the prefix lands.
4. **Least-loaded fallback** — smallest ``(queue_len, used_pages,
   replica index)`` among alive replicas; the index tiebreak keeps
   placement deterministic.

``exclude`` lets the retry path requeue AWAY from the replica that
just failed a request (falling back to it only when nothing else is
alive).  ``eligible`` is the supervisor's admission gate: when given,
only the named replicas are considered AT ALL — a SUSPECT/DEGRADED/
DEAD replica must never receive a new admission after its verdict
tick (the supervisor-consistency invariant), so unlike ``exclude``
there is no last-resort fallback through it.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

from attention_tpu import obs
from attention_tpu.frontend.replica import ReplicaHandle

_ROUTE_PREFIX = obs.counter("frontend.route.prefix_affine",
                            "requests routed by longest cached prefix")
_ROUTE_STICKY = obs.counter("frontend.route.sticky_session",
                            "requests routed by session stickiness")
_ROUTE_LOAD = obs.counter("frontend.route.least_loaded",
                          "requests routed by the load fallback")
_ROUTE_STORE = obs.counter("frontend.route.store_hit",
                           "requests routed on a fleet prefix-store hit")


@dataclasses.dataclass(frozen=True)
class RouteDecision:
    replica: ReplicaHandle
    reason: str           # "prefix" | "store" | "sticky" | "least_loaded"
    prefix_pages: int = 0


def store_page_size(replicas: Sequence[ReplicaHandle]) -> int:
    """The fleet's page size for store-chain probes (every replica is
    built from ONE `EngineConfig`, so the handles agree)."""
    return replicas[0].config.page_size if replicas else 1


class Router:
    """Stateless over replicas, stateful over sessions (the sticky
    map).  One router per front end."""

    def __init__(self):
        self._sessions: dict[str, str] = {}   # session -> replica_id

    def forget_replica(self, replica_id: str) -> None:
        """Drop sticky entries pointing at a dead replica so its
        sessions re-route instead of chasing the corpse."""
        self._sessions = {s: r for s, r in self._sessions.items()
                          if r != replica_id}

    def route(self, prompt: Sequence[int],
              replicas: Sequence[ReplicaHandle], *,
              session: str | None = None,
              exclude: str | None = None,
              eligible: frozenset[str] | set[str] | None = None,
              store=None, now: int = 0,
              ) -> RouteDecision | None:
        """Pick a replica for ``prompt`` (None when nothing is alive).

        ``exclude`` names a replica to avoid (the one that just failed
        this request); it is only used as a last resort when it is the
        sole survivor.  ``eligible``, when given, is a hard admission
        gate (no fallback through it): replicas outside the set are
        invisible to this decision."""
        alive = [r for r in replicas
                 if r.alive and (eligible is None
                                 or r.replica_id in eligible)]
        if not alive:
            return None
        preferred = [r for r in alive if r.replica_id != exclude] or alive

        best, best_pages = None, 0
        for r in preferred:
            pages = r.peek_prefix_pages(prompt)
            if pages > best_pages:
                best, best_pages = r, pages
        store_pages = (store.peek_chain(
            prompt, store_page_size(replicas), now=now)
            if store is not None else 0)
        if best is not None and best_pages > store_pages:
            decision = RouteDecision(best, "prefix", best_pages)
            _ROUTE_PREFIX.inc()
        elif store_pages > 0:
            # the chain imports anywhere geometry-compatible, so a
            # store hit makes every alive replica equally cheap: pick
            # by load first (a storm spreads instead of serializing
            # on the local holder), then prefer the replica already
            # holding the chain (resident pages beat a copy), then
            # the deterministic id tiebreak
            chosen = min(
                preferred,
                key=lambda r: (r.queue_len(),
                               r is not best,
                               r.load()["used_pages"],
                               r.replica_id),
            )
            decision = RouteDecision(chosen, "store", store_pages)
            _ROUTE_STORE.inc()
        else:
            sticky_id = self._sessions.get(session) if session else None
            sticky = next((r for r in preferred
                           if r.replica_id == sticky_id), None)
            if sticky is not None:
                decision = RouteDecision(sticky, "sticky")
                _ROUTE_STICKY.inc()
            else:
                chosen = min(
                    preferred,
                    key=lambda r: (r.queue_len(),
                                   r.load()["used_pages"],
                                   r.replica_id),
                )
                decision = RouteDecision(chosen, "least_loaded")
                _ROUTE_LOAD.inc()
        if session:
            self._sessions[session] = decision.replica.replica_id
        return decision
