"""`ServingFrontend`: N engine replicas behind one resilient door.

The layer the ROADMAP's "millions of users" story needs between
clients and `ServingEngine` replicas.  Requests are submitted once to
the front end; everything after that — routing, admission control,
deadline enforcement, retry, shedding, degradation — happens inside
the deterministic ``tick`` loop:

    submit() ─> QUEUED ──admit──> ASSIGNED ──────────> FINISHED
                  │                 │  ▲ retry            │
                  │ (deadline/shed) │  │ (backoff)        │ stream
                  ▼                 ▼  │                  ▼
          TIMED_OUT / SHED       RETRY_WAIT          on_token/on_finish
                                    │
                                    └──(budget dry)──> SHED

One tick = one scheduler round: expire deadlines in the front-end
queues, admit due arrivals (shed/route/assign), re-admit due retries,
step EVERY alive replica exactly once (keeping each engine's step
counter aligned with the global tick, which is what makes per-replica
deadline translation exact), migrate admission-stalled requests, then
feed the degradation ladder.  The headline invariant — every submitted
request terminates in exactly one of FINISHED / CANCELLED / TIMED_OUT
/ SHED, with finished requests token-identical to a fault-free
single-replica run — is pinned by `chaos.invariants` under replica-kill
storms.

Determinism: the only clocks are the tick counter and each engine's
step counter; backoff jitter is seeded (`frontend.backoff`); routing
tiebreaks on replica index.  Same seed, same trace, same fault plan →
byte-identical summary and `RunRecord`.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import os
from typing import Any, Callable, Sequence

from attention_tpu import obs
from attention_tpu.obs import blackbox as _blackbox
from attention_tpu.obs import capacity as _capacity
from attention_tpu.obs import trace as _trace
from attention_tpu.obs.anomaly import AnomalyPolicy, AnomalyTracker
from attention_tpu.obs.forecast import ForecastPolicy, HoltForecaster, _r6
from attention_tpu.obs.postmortem import PostmortemWriter
from attention_tpu.obs.naming import (
    SERIES_TPOT_DIGEST,
    SERIES_TTFT_DIGEST,
)
from attention_tpu.engine.engine import (
    EngineConfig,
    StepLimitExceededError,
)
from attention_tpu.engine.errors import (
    DeadlineExceededError,
    HandoffCorruptError,
    PrefixStoreCorruptError,
    ReplicaDeadError,
    RequestShedError,
    StepInterruptedError,
)
from attention_tpu.engine.request import Request, SamplingParams
from attention_tpu.engine.sim import sampling_of
from attention_tpu.engine.snapshot import _request_to_dict
from attention_tpu.fleet.autoscaler import Autoscaler, AutoscalerPolicy
from attention_tpu.fleet.handoff import export_handoff, import_handoff
from attention_tpu.fleet.ledger import ActuationRecord
from attention_tpu.fleet.topology import (
    POOLS,
    FleetTopology,
    initial_pools,
)
from attention_tpu.frontend.backoff import RetryPolicy
from attention_tpu.frontend.degrade import (
    NUM_PRIORITY_CLASSES,
    DegradationLadder,
    DegradePolicy,
    ShedPolicy,
    pool_pressure,
)
from attention_tpu.frontend.migrate import MigrationRecord, drain_replica
from attention_tpu.frontend.replica import ReplicaHandle
from attention_tpu.frontend.routing import Router
from attention_tpu.frontend.supervisor import (
    ReplicaSupervisor,
    SupervisorPolicy,
    SupervisorState,
)
from attention_tpu.ops.paged import OutOfPagesError
from attention_tpu.prefixstore.records import chain_key, chain_tokens
from attention_tpu.prefixstore.store import (
    STORE_FILENAME,
    PrefixStore,
    PrefixStoreConfig,
    load_store,
    save_store,
)
from attention_tpu.utils.profiling import RunRecord

_SHED = obs.counter("frontend.shed.rejected",
                    "arrivals rejected by admission control")
_DOWNCLASSED = obs.counter("frontend.shed.downclassed",
                           "arrivals demoted one priority class")
_RETRY_SCHED = obs.counter("frontend.retry.scheduled",
                           "requeues placed on the backoff queue")
_RETRY_EXHAUSTED = obs.counter("frontend.retry.exhausted",
                               "requests shed with the budget dry")
_MIGRATED = obs.counter("frontend.retry.migrated",
                        "admission-stalled requests moved off a replica")
_DEADLINE_EXPIRED = obs.counter("frontend.deadline.expired",
                                "front-end-side deadline expiries")
_KILLED = obs.counter("frontend.replica.killed", "replica kills")
_RESTARTED = obs.counter("frontend.replica.restarted",
                         "replica restarts")
_STEP_DOWN = obs.counter("frontend.degrade.step_down",
                         "degradation-ladder level drops")
_RECOVER = obs.counter("frontend.degrade.recover",
                       "degradation-ladder level recoveries")
_LEVEL_G = obs.gauge("frontend.degrade.level",
                     "current degradation-ladder level")
_PRESSURE_G = obs.gauge("frontend.pressure.mean",
                        "mean replica pressure after the tick")
_R_QUEUE_G = obs.gauge("frontend.replica.queue_depth",
                       "per-replica waiting+running requests")
_R_UTIL_G = obs.gauge("frontend.replica.page_util",
                      "per-replica page-pool utilization")
_PROMOTED = obs.counter("frontend.replica.promoted",
                        "warm standbys promoted on a DEAD verdict")
# client-observed latency digests (obs.quantile): per-replica series
# merge bucket-wise into the fleet view, so `cli obs slo` / the SLO
# observatory aggregate replicas without resampling
_TTFT_DIG = obs.digest(SERIES_TTFT_DIGEST,
                       "client TTFT quantile digest (front-end ticks)")
_TPOT_DIG = obs.digest(SERIES_TPOT_DIGEST,
                       "client TPOT quantile digest (ticks per token)")


class FrontendRequestState(enum.Enum):
    QUEUED = "queued"          # submitted, not yet on a replica
    ASSIGNED = "assigned"      # live on a replica
    RETRY_WAIT = "retry_wait"  # backing off before re-assignment
    FINISHED = "finished"
    CANCELLED = "cancelled"
    TIMED_OUT = "timed_out"
    SHED = "shed"


#: the front-end terminal set — the resilience invariant's alphabet
FRONTEND_TERMINAL = frozenset({
    FrontendRequestState.FINISHED, FrontendRequestState.CANCELLED,
    FrontendRequestState.TIMED_OUT, FrontendRequestState.SHED,
})

#: terminal state -> its trace event name (obs.naming TRACE_EVENTS);
#: the `_finalize` funnel records exactly one of these per request
_TERMINAL_EVENT = {
    FrontendRequestState.FINISHED: "finished",
    FrontendRequestState.CANCELLED: "cancelled",
    FrontendRequestState.TIMED_OUT: "timed_out",
    FrontendRequestState.SHED: "shed",
}

# RETRY_WAIT -> RETRY_WAIT is a real edge: a retry that finds no alive
# replica goes straight back on the backoff queue.  ASSIGNED/RETRY_WAIT
# -> SHED is retry-budget exhaustion.
_FE_TRANSITIONS: dict[FrontendRequestState,
                      frozenset[FrontendRequestState]] = {
    FrontendRequestState.QUEUED: frozenset(
        {FrontendRequestState.ASSIGNED, FrontendRequestState.RETRY_WAIT,
         FrontendRequestState.CANCELLED, FrontendRequestState.TIMED_OUT,
         FrontendRequestState.SHED}
    ),
    FrontendRequestState.ASSIGNED: frozenset(
        {FrontendRequestState.RETRY_WAIT, FrontendRequestState.FINISHED,
         FrontendRequestState.CANCELLED, FrontendRequestState.TIMED_OUT,
         FrontendRequestState.SHED}
    ),
    FrontendRequestState.RETRY_WAIT: frozenset(
        {FrontendRequestState.ASSIGNED, FrontendRequestState.RETRY_WAIT,
         FrontendRequestState.CANCELLED, FrontendRequestState.TIMED_OUT,
         FrontendRequestState.SHED}
    ),
    FrontendRequestState.FINISHED: frozenset(),
    FrontendRequestState.CANCELLED: frozenset(),
    FrontendRequestState.TIMED_OUT: frozenset(),
    FrontendRequestState.SHED: frozenset(),
}


@dataclasses.dataclass
class FrontendRequest:
    """One client request as the front end sees it — survives replica
    deaths and re-assignments (the per-replica engine `Request` objects
    are disposable; this record is the durable truth)."""

    request_id: str
    prompt: tuple[int, ...]
    sampling: SamplingParams
    arrival: int                      # front-end tick
    deadline: int | None              # absolute tick (None = no TTL)
    priority: int = 1                 # 0 = highest class
    session: str | None = None
    seq: int = 0

    state: FrontendRequestState = FrontendRequestState.QUEUED
    tokens: list[int] = dataclasses.field(default_factory=list)
    #: which replica emitted each token (parallel to ``tokens``) — the
    #: no-double-serve invariant's evidence trail
    emitters: list[str] = dataclasses.field(default_factory=list)
    replica_id: str | None = None
    last_replica: str | None = None
    routed_by: str | None = None
    attempts: int = 0                 # requeues consumed
    next_retry: int | None = None
    assigned_tick: int = -1
    waiting_since: int | None = None  # stall-detection bookkeeping
    downclassed: bool = False
    prefix_cached_tokens: int = 0
    first_token_tick: int | None = None
    finish_tick: int = -1
    error: BaseException | None = None

    @property
    def is_terminal(self) -> bool:
        return self.state in FRONTEND_TERMINAL

    def transition(self, new: FrontendRequestState) -> None:
        if new not in _FE_TRANSITIONS[self.state]:
            raise ValueError(
                f"request {self.request_id}: illegal front-end "
                f"transition {self.state.name} -> {new.name}"
            )
        self.state = new


@dataclasses.dataclass(frozen=True)
class FrontendConfig:
    """Front-end knobs; every time-like field is in ticks."""

    num_replicas: int = 2
    seed: int = 0
    retry: RetryPolicy = dataclasses.field(default_factory=RetryPolicy)
    shed: ShedPolicy = dataclasses.field(default_factory=ShedPolicy)
    degrade: DegradePolicy = dataclasses.field(
        default_factory=DegradePolicy)
    default_ttl_ticks: int | None = None  # applied when submit has none
    stall_ticks: int = 4   # un-admitted for this long -> migrate
    # durability (engine.snapshot): when BOTH are set each replica
    # snapshots every N of its own steps into
    # <snapshot_dir>/<replica_id>/ and restart_replica recovers warm
    snapshot_dir: str | None = None
    snapshot_every: int | None = None
    # proactive failure handling (frontend.supervisor / .migrate):
    # detection thresholds, plus N spare engine-less handles promoted
    # warm on a DEAD verdict
    supervisor: SupervisorPolicy = dataclasses.field(
        default_factory=SupervisorPolicy)
    standbys: int = 0
    # load forecasting (obs.forecast): None = disabled, and disabled
    # means ZERO work in the tick loop — the same contract telemetry
    # honors.  Even when set it is passive bookkeeping; only the
    # advisory flag inside the policy makes it *log* (never act).
    forecast: ForecastPolicy | None = None
    # global prefix tier (attention_tpu.prefixstore): None = disabled
    # = byte-identical to the storeless front end.  When set, ONE
    # shared `PrefixStore` is built for the fleet, every replica
    # engine exports/imports through it, routing consults store hits,
    # arrivals coalesce behind single-flight prefill leases, and —
    # with snapshot_dir set — store state persists across warm
    # restarts as its own CRC'd-section file
    prefix_store: PrefixStoreConfig | None = None
    # incident layer (obs.anomaly / obs.postmortem): ``anomaly`` arms
    # the online detectors — deterministic bookkeeping fed from the
    # tick loop, advisory-only, None = disabled = zero tick-loop work
    # (the forecast contract).  ``incident_dir`` arms the postmortem
    # writer: detector firings, replica kills, and injected faults
    # each dump one atomic `incident-<tick>/` bundle there.
    anomaly: AnomalyPolicy | None = None
    incident_dir: str | None = None
    # disaggregated serving (attention_tpu.fleet): ``fleet`` splits
    # the replicas into role-typed prefill/decode pools — fresh
    # admissions route to the prefill pool and at prompt-commit hand
    # off (shipping committed KV pages) to the decode pool.  None =
    # monolithic = byte-identical to the pre-fleet front end.
    fleet: FleetTopology | None = None
    # the closed-loop elastic autoscaler (requires ``fleet``; the
    # standby bench is what it promotes from / demotes to)
    autoscaler: AutoscalerPolicy | None = None

    def validate(self) -> None:
        if self.num_replicas < 1:
            raise ValueError(
                f"num_replicas must be >= 1, got {self.num_replicas}"
            )
        if self.standbys < 0:
            raise ValueError(
                f"standbys must be >= 0, got {self.standbys}"
            )
        if self.stall_ticks < 1:
            raise ValueError(
                f"stall_ticks must be >= 1, got {self.stall_ticks}"
            )
        if (self.default_ttl_ticks is not None
                and self.default_ttl_ticks < 1):
            raise ValueError(
                f"default_ttl_ticks must be >= 1, got "
                f"{self.default_ttl_ticks}"
            )
        if (self.snapshot_dir is None) != (self.snapshot_every is None):
            raise ValueError(
                "snapshot_dir and snapshot_every must be set together"
            )
        if self.snapshot_every is not None and self.snapshot_every < 1:
            raise ValueError(
                f"snapshot_every must be >= 1, got {self.snapshot_every}"
            )
        self.retry.validate()
        self.shed.validate()
        self.degrade.validate()
        self.supervisor.validate()
        if self.forecast is not None:
            self.forecast.validate()
        if self.prefix_store is not None:
            self.prefix_store.validate()
        if self.anomaly is not None:
            self.anomaly.validate()
        if self.fleet is not None:
            self.fleet.validate(num_replicas=self.num_replicas)
        if self.autoscaler is not None:
            if self.fleet is None:
                raise ValueError(
                    "autoscaler requires a fleet topology "
                    "(FrontendConfig.fleet)")
            self.autoscaler.validate()


def _cumulative_series(pairs, n: int) -> list[float]:
    """Per-tick running mean of ``(tick, value)`` marks over ticks
    ``0..n-1`` (0.0 before the first mark) — the tick-indexed view of
    the latency digests the forecaster consumes."""
    marks = sorted(pairs)
    out: list[float] = []
    i = 0
    total = 0.0
    count = 0
    for t in range(n):
        while i < len(marks) and marks[i][0] <= t:
            total += marks[i][1]
            count += 1
            i += 1
        out.append(total / count if count else 0.0)
    return out


class ForecastTracker:
    """Per-tick fleet sample recorder + incremental pressure forecaster.

    Exists only when ``FrontendConfig.forecast`` is set; every hook in
    the serving hot path is a single ``tracker is None`` check, the
    zero-overhead contract `frontend.degrade` documents for telemetry
    applied to forecasting.  The tracker never reads the obs registry
    and is never consulted for control flow: ``forecast_pressure`` is
    an advisory surface, and the advisory hooks only *log* what
    forecast-driven admission would have done.
    """

    def __init__(self, policy: ForecastPolicy):
        self.policy = policy
        # per-tick sample series (virtual ticks; index == tick)
        self.pressure: list[float] = []
        self.queue_depth: list[float] = []
        self.admissions: list[float] = []
        self.tokens: list[float] = []
        #: tokens emitted per replica over the whole run (capacity input)
        self.replica_tokens: dict[str, int] = {}
        self._pressure_fc = HoltForecaster(policy)
        self._tokens_total = 0
        self._tokens_seen = 0
        #: events_log prefix already counted for the admissions series
        self.events_seen = 0
        #: one-step-ahead mean-pressure forecast after the last tick
        self.forecast_pressure: float | None = None

    def note_token(self, replica_id: str) -> None:
        self._tokens_total += 1
        self.replica_tokens[replica_id] = (
            self.replica_tokens.get(replica_id, 0) + 1)

    def record_tick(self, pressure: float, queue_depth: int,
                    admissions: int) -> float:
        """Append one sample row; returns the one-step forecast of the
        mean fleet pressure (what next tick is predicted to look like)."""
        self.pressure.append(float(pressure))
        self.queue_depth.append(float(queue_depth))
        self.admissions.append(float(admissions))
        self.tokens.append(float(self._tokens_total - self._tokens_seen))
        self._tokens_seen = self._tokens_total
        self._pressure_fc.observe(pressure)
        self.forecast_pressure = self._pressure_fc.predict(1)
        return self.forecast_pressure

    def report(self, rows: list[dict[str, Any]], *, alive: int,
               shed_pressure: float, downclass_pressure: float,
               horizon: int | None = None) -> dict[str, Any]:
        """The combined observatory document (`obs.capacity`) over the
        recorded samples plus tick-indexed TTFT/TPOT series derived
        from the latency rows.  Pure: calling it twice yields the same
        bytes — the chaos ``forecast_determinism`` invariant."""
        n = len(self.pressure)
        samples = {
            "pressure": self.pressure,
            "queue_depth": self.queue_depth,
            "admissions": self.admissions,
            "tokens": self.tokens,
            "ttft": _cumulative_series(
                ((r["first_token_tick"],
                  float(r["first_token_tick"] - r["submit_tick"]))
                 for r in rows if r["first_token_tick"] is not None), n),
            "tpot": _cumulative_series(
                ((r["finish_tick"],
                  (r["finish_tick"] - r["first_token_tick"])
                  / (r["output_tokens"] - 1))
                 for r in rows if r["first_token_tick"] is not None
                 and r["output_tokens"] >= 2), n),
        }
        inputs = {
            "ticks": n,
            "alive": alive,
            "last_pressure": self.pressure[-1] if self.pressure else 0.0,
            "replica_tokens": dict(sorted(self.replica_tokens.items())),
        }
        return _capacity.observatory_report(
            samples, inputs, policy=self.policy, horizon=horizon,
            shed_pressure=shed_pressure,
            downclass_pressure=downclass_pressure)


class ServingFrontend:
    """Deterministic multi-replica serving front end (module doc)."""

    def __init__(self, model, params, engine_config: EngineConfig,
                 config: FrontendConfig | None = None, *,
                 on_token: Callable[..., None] | None = None,
                 on_finish: Callable[..., None] | None = None):
        config = config or FrontendConfig()
        config.validate()
        self.model = model
        self.params = params
        self.engine_config = engine_config
        self.config = config
        self.on_token = on_token
        self.on_finish = on_finish

        # deterministic mirrors of the obs counters (telemetry is off
        # by default; the summary must not depend on it)
        self.counts = {
            "shed_rejected": 0, "downclassed": 0,
            "retries_scheduled": 0, "retries_exhausted": 0,
            "migrations": 0, "deadline_expired": 0,
            "replica_kills": 0, "replica_restarts": 0,
            "warm_restarts": 0, "warm_adoptions": 0,
            "live_migrations": 0, "migrations_stranded": 0,
            "standby_promotions": 0, "supervisor_suspects": 0,
            "supervisor_degraded": 0, "supervisor_dead": 0,
            "supervisor_recoveries": 0,
            "anomaly_firings": 0, "incidents": 0,
            "handoffs": 0, "handoff_fallbacks": 0,
            "reprefill_avoided_tokens": 0,
            "scale_ups": 0, "scale_downs": 0, "actuation_vetoes": 0,
        }
        self._tick = 0
        #: incident-bundle writer (None = no dumping) — constructed
        #: BEFORE the store load so a corrupt persisted store already
        #: has somewhere to file its incident
        self.postmortem = (PostmortemWriter(config.incident_dir)
                           if config.incident_dir is not None else None)
        #: online anomaly detectors (None = disabled = zero tick work)
        self.anomaly = (AnomalyTracker(config.anomaly)
                        if config.anomaly is not None else None)

        # fleet prefix store: built (or warm-reloaded) BEFORE the
        # replicas so every engine incarnation attaches to the one
        # shared instance.  A corrupt persisted store is the same
        # non-event a corrupt snapshot is: typed, counted, start cold.
        self.prefix_store: PrefixStore | None = None
        if config.prefix_store is not None:
            path = (os.path.join(config.snapshot_dir, STORE_FILENAME)
                    if config.snapshot_dir else None)
            if path is not None and os.path.exists(path):
                try:
                    self.prefix_store = load_store(
                        path, config.prefix_store)
                except PrefixStoreCorruptError:
                    self.prefix_store = PrefixStore(config.prefix_store)
                    self.prefix_store.note_corrupt()
                    self._incident("typed_error", {
                        "error": "PrefixStoreCorruptError",
                        "path": path})
            else:
                self.prefix_store = PrefixStore(config.prefix_store)
        #: requests coalesced behind a single-flight prefill lease,
        #: re-evaluated each tick in seq order
        self._store_wait: list[FrontendRequest] = []
        self._coalesced_ids: set[str] = set()

        self.router = Router()
        self.ladder = DegradationLadder(config.degrade)
        self.supervisor = ReplicaSupervisor(config.supervisor)
        self.replicas = [
            self._make_handle(f"replica-{i}")
            for i in range(config.num_replicas)
        ]
        #: engine-less spares, promoted (in order) on a DEAD verdict
        self.standby_pool = [
            self._make_handle(f"standby-{k}", spare=True)
            for k in range(config.standbys)
        ]
        self._seq = itertools.count()
        self.requests: dict[str, FrontendRequest] = {}
        self._pending: list[FrontendRequest] = []  # (arrival, seq) order
        self._retry: list[FrontendRequest] = []
        #: unified append-ordered event log — ("verdict", tick, replica,
        #: old, new, signals) and ("admit", tick, request, replica) in
        #: the exact order they happened; the supervisor-consistency
        #: checker replays it (append order IS the global order, which
        #: sidesteps within-tick phase ordering entirely)
        self.events_log: list[tuple] = []
        #: every drain decision, in order (`frontend.migrate`)
        self.migrations: list[MigrationRecord] = []
        #: load forecaster (None = disabled = zero tick-loop work)
        self.forecast = (ForecastTracker(config.forecast)
                         if config.forecast is not None else None)
        #: fleet role map, replica id -> pool (empty = monolithic:
        #: every fleet hook is a single truthiness check, the
        #: zero-overhead contract telemetry/forecasting honor)
        self.pool_of: dict[str, str] = (
            initial_pools([h.replica_id for h in self.replicas],
                          config.fleet)
            if config.fleet is not None else {})
        #: closed-loop controller (None = static fleet)
        self.autoscaler = (Autoscaler(config.autoscaler)
                           if config.autoscaler is not None else None)
        #: executed resizes, in order — chaos invariant 16 balances
        #: this ledger against the blackbox ring
        self.actuations: list[ActuationRecord] = []
        # chaos knobs (chaos.faults): corrupt the next N handoff
        # payloads / force N hysteresis-bypassing demotions
        self._poison_handoffs = 0
        self._force_demotions = 0
        #: armed mis-actuation guards: (scale_down tick, pool,
        #: shed_rejected count at actuation time)
        self._guards: list[tuple[int, str, int]] = []

    def _make_handle(self, replica_id: str, *,
                     spare: bool = False) -> ReplicaHandle:
        # the token callback closes over the replica id so every
        # streamed token records WHICH engine emitted it — the
        # no-double-serve invariant's raw evidence
        return ReplicaHandle(
            replica_id, self.model, self.params, self.engine_config,
            snapshot_dir=(os.path.join(self.config.snapshot_dir,
                                       replica_id)
                          if self.config.snapshot_dir else None),
            snapshot_every=self.config.snapshot_every,
            on_token=(lambda req, tok, _rid=replica_id:
                      self._on_engine_token(_rid, req, tok)),
            on_finish=self._on_engine_finish,
            on_timeout=self._on_engine_timeout,
            spare=spare,
            prefix_store=self.prefix_store,
        )

    # -- intake -----------------------------------------------------------

    @property
    def current_tick(self) -> int:
        return self._tick

    def submit(self, prompt, sampling: SamplingParams | None = None, *,
               request_id: str | None = None, arrival: int | None = None,
               ttl_ticks: int | None = None, priority: int = 1,
               session: str | None = None) -> FrontendRequest:
        """Register one request.  ``ttl_ticks`` is relative to arrival
        (falling back to the config default); validation happens here
        so the tick loop never trips over a malformed request."""
        sampling = sampling or SamplingParams()
        sampling.validate(self.model.vocab)
        prompt = tuple(int(t) for t in prompt)
        if not prompt:
            raise ValueError("empty prompt")
        if any(not (0 <= t < self.model.vocab) for t in prompt):
            raise ValueError(
                f"prompt tokens must be in the vocab "
                f"[0, {self.model.vocab})"
            )
        total = len(prompt) + sampling.max_tokens - 1
        if total > self.engine_config.max_seq_len:
            raise ValueError(
                f"prompt + max_tokens - 1 = {total} exceeds "
                f"max_seq_len {self.engine_config.max_seq_len}"
            )
        if not (0 <= priority < NUM_PRIORITY_CLASSES):
            raise ValueError(
                f"priority must be in [0, {NUM_PRIORITY_CLASSES}), "
                f"got {priority}"
            )
        if ttl_ticks is not None and ttl_ticks < 1:
            raise ValueError(f"ttl_ticks must be >= 1, got {ttl_ticks}")
        arrival = self._tick if arrival is None else int(arrival)
        ttl = (ttl_ticks if ttl_ticks is not None
               else self.config.default_ttl_ticks)
        seq = next(self._seq)
        fr = FrontendRequest(
            request_id=request_id or f"req-{seq}",
            prompt=prompt,
            sampling=sampling,
            arrival=arrival,
            deadline=None if ttl is None else arrival + ttl,
            priority=int(priority),
            session=session,
            seq=seq,
        )
        if fr.request_id in self.requests:
            raise ValueError(f"duplicate request id {fr.request_id!r}")
        self.requests[fr.request_id] = fr
        self._pending.append(fr)
        self._pending.sort(key=lambda f: (f.arrival, f.seq))
        self._trace_event(fr, "submitted", tick=fr.arrival,
                          tenant=fr.session, priority=fr.priority)
        return fr

    def cancel(self, request_id: str) -> bool:
        """Client abandons a request wherever it is; False when the
        id is unknown or already terminal."""
        fr = self.requests.get(request_id)
        if fr is None or fr.is_terminal:
            return False
        if fr.state is FrontendRequestState.ASSIGNED:
            handle = self._handle(fr.replica_id)
            if handle is not None and handle.alive:
                handle.engine.cancel(request_id)
        self._finalize(fr, FrontendRequestState.CANCELLED)
        return True

    # -- engine callbacks -------------------------------------------------

    def _on_engine_token(self, replica_id: str, req: Request,
                         token: int) -> None:
        fr = self.requests[req.request_id]
        if not fr.tokens:
            fr.first_token_tick = self._tick
        fr.tokens.append(int(token))
        fr.emitters.append(replica_id)
        fr.waiting_since = None
        if self.forecast is not None:
            self.forecast.note_token(replica_id)
        if self.anomaly is not None:
            self.anomaly.observe_tokens(
                self._tick, replica_id, req.request_id, 1)
        if self.on_token is not None:
            self.on_token(fr, int(token))

    def _on_engine_finish(self, req: Request) -> None:
        fr = self.requests[req.request_id]
        fr.prefix_cached_tokens = req.prefix_cached_tokens
        self._finalize(fr, FrontendRequestState.FINISHED)
        if self.on_finish is not None:
            self.on_finish(fr)

    def _on_engine_timeout(self, req: Request) -> None:
        fr = self.requests[req.request_id]
        self._finalize(
            fr, FrontendRequestState.TIMED_OUT,
            error=DeadlineExceededError(
                f"request {fr.request_id} expired at tick "
                f"{self._tick} (deadline {fr.deadline})"
            ),
        )

    # -- tick loop --------------------------------------------------------

    def tick(self) -> int:
        """One deterministic scheduler round; returns the tick served."""
        t = self._tick
        with obs.span("frontend.tick"):
            self._expire_queued(t)
            self._heartbeat_leases(t)
            self._admit_store_waiters(t)
            self._admit_arrivals(t)
            self._admit_retries(t)
            self._step_replicas(t)
            self._handoff_committed(t)
            self._supervise(t)
            self._migrate_stalled(t)
            self._update_ladder_and_gauges(t)
            self._autoscale(t)
            self._persist_prefix_store(t)
        self._tick += 1
        return t

    def has_work(self) -> bool:
        return any(not fr.is_terminal for fr in self.requests.values())

    def run(self, *, max_ticks: int | None = None) -> dict[str, Any]:
        """Tick until every submitted request is terminal."""
        while self.has_work():
            if max_ticks is not None and self._tick >= max_ticks:
                live = [fr.request_id
                        for fr in self.requests.values()
                        if not fr.is_terminal]
                raise StepLimitExceededError(
                    f"front end exceeded max_ticks={max_ticks} with "
                    f"{len(live)} live request(s): {live[:5]}"
                )
            self.tick()
        return self.summary()

    # -- chaos hooks ------------------------------------------------------

    def kill_replica(self, replica_id: str) -> bool:
        """Fail-stop one replica NOW: its engine (pages, caches,
        in-flight work) is gone; every request assigned to it enters
        the retry-with-backoff path, streamed tokens preserved."""
        handle = self._handle(replica_id)
        if handle is None or not handle.alive:
            return False
        victims = sorted(
            (fr for fr in self.requests.values()
             if fr.state is FrontendRequestState.ASSIGNED
             and fr.replica_id == replica_id),
            key=lambda f: f.seq,
        )
        # note BEFORE the kill so the record carries the dying
        # incarnation's live coordinates
        self._bb_note("replica_kill", replica_id=replica_id,
                      victims=len(victims))
        handle.kill()
        self.router.forget_replica(replica_id)
        self.counts["replica_kills"] += 1
        _KILLED.inc()
        cause = ReplicaDeadError(
            f"replica {replica_id} died at tick {self._tick}"
        )
        self._incident("typed_error", {
            "error": "ReplicaDeadError", "replica": replica_id,
            "victims": len(victims)})
        for fr in victims:
            self._requeue(fr, self._tick, cause)
        return True

    def restart_replica(self, replica_id: str, *,
                        warm: bool | None = None) -> bool:
        """Bring a dead replica back at the current tick.

        ``warm`` defaults to "whenever the replica has a snapshot
        directory": the handle recovers from its newest valid snapshot
        + journal replay and the front end then *reconciles* the
        restored in-flight requests against its own bookkeeping —
        requests whose restored token position matches the streamed
        prefix are adopted in place (no re-prefill, no retry delay);
        anything stale, torn, or already re-homed is cancelled on the
        engine and left to the cold `resume_request` route.  A corrupt
        or missing snapshot degrades to a plain cold restart."""
        handle = self._handle(replica_id)
        if handle is None or handle.alive:
            return False
        want_warm = handle.snapshot_dir is not None \
            if warm is None else warm
        mode = handle.restart(
            tick=self._tick,
            warm_from=handle.snapshot_dir if want_warm else None,
        )
        # fresh engine -> fresh judgement (and the recovery verdict
        # lands in the event log BEFORE any adoption re-admissions)
        verdict = self.supervisor.reset(self._tick, replica_id)
        if verdict is not None:
            self.events_log.append((
                "verdict", self._tick, replica_id,
                verdict.old.value, verdict.new.value,
                list(verdict.signals)))
            self.counts["supervisor_recoveries"] += 1
        if mode == "warm":
            self.counts["warm_restarts"] += 1
            self._reconcile_restored(handle)
        self._apply_ladder_to(handle)
        self.counts["replica_restarts"] += 1
        _RESTARTED.inc()
        self._bb_note("replica_restart", replica_id=replica_id,
                      mode=mode)
        return True

    def _reconcile_restored(self, handle: ReplicaHandle) -> None:
        """Square a warm-restored engine with front-end bookkeeping.

        The snapshot+journal reconstruct the engine's view of its
        in-flight requests; the front end is the source of truth for
        what the CLIENT saw.  A restored request is adopted only when
        it is still wanted (in RETRY_WAIT after the kill-time requeue)
        and its restored output position exactly matches the tokens
        already streamed — a torn journal tail shows up here as a
        position mismatch and falls back to the cold path, preserving
        token parity."""
        eng = handle.engine
        t = self._tick
        for req in (*eng.scheduler.waiting, *eng.scheduler.running):
            fr = self.requests.get(req.request_id)
            if (fr is None
                    or fr.state is not FrontendRequestState.RETRY_WAIT
                    or list(req.output_tokens) != list(fr.tokens)):
                eng.cancel(req.request_id)
                continue
            if fr in self._retry:
                self._retry.remove(fr)
            fr.next_retry = None
            fr.transition(FrontendRequestState.ASSIGNED)
            fr.replica_id = handle.replica_id
            fr.routed_by = "warm-restore"
            fr.assigned_tick = t
            fr.waiting_since = None
            # deadline in the restarted replica's own step space
            req.deadline_step = handle.local_deadline(fr.deadline)
            self.counts["warm_adoptions"] += 1
            self._trace_event(fr, "warm_adopted",
                              tokens_restored=len(fr.tokens))
            self.events_log.append(
                ("admit", t, fr.request_id, handle.replica_id))

    # -- internals --------------------------------------------------------

    def _handle(self, replica_id: str | None) -> ReplicaHandle | None:
        return next((h for h in self.replicas
                     if h.replica_id == replica_id), None)

    def _trace_event(self, fr: FrontendRequest, event: str, *,
                     tick: int | None = None, **extra: Any) -> None:
        """Stamp one front-end trace event with the request's current
        replica coordinates (None/-1 while it sits in a front-end
        queue)."""
        if not _trace.active():
            return
        handle = self._handle(fr.replica_id)
        _trace.record(
            fr.request_id, event,
            tick=self._tick if tick is None else tick,
            replica=fr.replica_id,
            incarnation=handle.deaths if handle is not None else 0,
            step=(handle.engine.current_step
                  if handle is not None and handle.alive else -1),
            **extra,
        )

    def _bb_note(self, kind: str, *, replica_id: str | None = None,
                 tick: int | None = None, **extra: Any) -> None:
        """Stamp one fleet flight-recorder event with the replica's
        current deterministic coordinates (incarnation -1 step while
        it is down), mirroring `_trace_event`'s discipline for
        per-request traces."""
        if not _blackbox.active():
            return
        handle = self._handle(replica_id)
        _blackbox.note(
            kind,
            tick=self._tick if tick is None else tick,
            replica=replica_id,
            incarnation=handle.deaths if handle is not None else 0,
            step=(handle.engine.current_step
                  if handle is not None and handle.alive else -1),
            **extra,
        )

    def _incident(self, cause: str, detail: dict[str, Any]) -> None:
        """File one incident bundle (dedup'd by the writer) for a
        typed error, detector firing, or chaos trigger; a no-op
        without an ``incident_dir``."""
        if self.postmortem is None:
            return
        if self.postmortem.maybe_dump(
                tick=self._tick, cause=cause, detail=detail) is not None:
            self.counts["incidents"] += 1

    def _finalize(self, fr: FrontendRequest,
                  state: FrontendRequestState, *,
                  error: BaseException | None = None) -> None:
        fr.transition(state)
        fr.finish_tick = self._tick
        fr.next_retry = None
        fr.waiting_since = None
        if error is not None:
            fr.error = error
        if fr in self._pending:
            self._pending.remove(fr)
        if fr in self._retry:
            self._retry.remove(fr)
        if fr in self._store_wait:
            self._store_wait.remove(fr)
        if self.prefix_store is not None:
            # a terminal leader frees its single-flight leases NOW
            # (waiters take over next tick) instead of waiting out
            # the tick-expiry window
            self.prefix_store.leases.release_owner(fr.request_id)
        self._trace_event(fr, _TERMINAL_EVENT[state])
        if state is FrontendRequestState.SHED:
            # the flight recorder's watermark-shed / budget-dry event
            # (both shed paths funnel through here)
            self._bb_note("shed", replica_id=fr.last_replica,
                          request=fr.request_id,
                          cause=type(fr.error).__name__
                          if fr.error is not None else None)
        if self.anomaly is not None:
            n = len(fr.tokens)
            ttft = (fr.first_token_tick - fr.arrival
                    if fr.first_token_tick is not None else None)
            tpot = ((fr.finish_tick - fr.first_token_tick) / (n - 1)
                    if fr.first_token_tick is not None and n > 1
                    else None)
            self.anomaly.observe_latency(self._tick, ttft, tpot)
            self.anomaly.forget_request(fr.request_id)
        if obs.enabled() and state is FrontendRequestState.FINISHED:
            labels = {"replica": fr.replica_id or "none"}
            if fr.first_token_tick is not None:
                _TTFT_DIG.observe(
                    max(fr.first_token_tick - fr.arrival, 0), **labels)
                if len(fr.tokens) > 1:
                    _TPOT_DIG.observe(
                        (fr.finish_tick - fr.first_token_tick)
                        / (len(fr.tokens) - 1), **labels)

    def _expire_queued(self, t: int) -> None:
        """Deadline sweep over the FRONT-END queues (pending arrivals
        and the backoff queue); requests live on a replica are swept
        by that engine's own per-step deadline check."""
        for fr in [f for f in (*self._pending, *self._retry,
                               *self._store_wait)
                   if f.deadline is not None and f.deadline <= t]:
            self.counts["deadline_expired"] += 1
            _DEADLINE_EXPIRED.inc()
            self._finalize(
                fr, FrontendRequestState.TIMED_OUT,
                error=DeadlineExceededError(
                    f"request {fr.request_id} expired at tick {t} "
                    f"before reaching a replica (deadline "
                    f"{fr.deadline})"
                ),
            )

    def _shed(self, fr: FrontendRequest, t: int, why: str) -> None:
        self.counts["shed_rejected"] += 1
        _SHED.inc()
        self._finalize(
            fr, FrontendRequestState.SHED,
            error=RequestShedError(
                f"request {fr.request_id} shed at tick {t}: {why}"
            ),
        )

    def _admit_arrivals(self, t: int) -> None:
        while self._pending and self._pending[0].arrival <= t:
            fr = self._pending.pop(0)
            # admission control: judge against the BEST alive replica
            # (pressure recomputed per arrival — each admission grows
            # a queue, so a big burst sheds its own tail)
            best, _ = pool_pressure(
                self.replicas, queue_cap=self.config.shed.queue_cap)
            lowest = fr.priority >= NUM_PRIORITY_CLASSES - 1
            if lowest and (best >= self.config.shed.shed_pressure
                           or self.ladder.level >= 3):
                self._shed(
                    fr, t,
                    f"priority-{fr.priority} arrival under pressure "
                    f"{best:.2f} (ladder level {self.ladder.level})",
                )
                continue
            if (not lowest and fr.priority > 0
                    and best >= self.config.shed.downclass_pressure):
                fr.priority += 1
                fr.downclassed = True
                self.counts["downclassed"] += 1
                _DOWNCLASSED.inc()
            self._assign(fr, t)

    def _admit_retries(self, t: int) -> None:
        due = sorted(
            (fr for fr in self._retry if fr.next_retry <= t),
            key=lambda f: (f.next_retry, f.seq),
        )
        for fr in due:
            self._retry.remove(fr)
            fr.next_retry = None
            self._assign(fr, t, exclude=fr.last_replica)

    def _heartbeat_leases(self, t: int) -> None:
        """A prefill lease belongs to a REQUEST, not a replica: while
        the owning request is live the front end refreshes its leases
        every tick, so a long prefill (many chunked steps) never loses
        its flight to mere elapsed time, and a replica kill just moves
        the same leader through the retry path.  Tick expiry is then
        purely the dead-leader backstop — an owner that vanished
        without its terminal release — which is exactly when waiters
        MUST stop waiting."""
        if self.prefix_store is None:
            return
        leases = self.prefix_store.leases
        if leases.expire(now=t):
            for key in leases.last_expired:
                self._bb_note("lease_expire", tick=t, key=key[:12])
        for key, owner in leases.active(now=t):
            fr = self.requests.get(owner)
            if fr is not None and not fr.is_terminal:
                leases.acquire(key, owner, now=t)

    def _admit_store_waiters(self, t: int) -> None:
        """Re-evaluate every coalesced request (seq order): the leader
        exporting its chain, its terminal release, or plain lease
        expiry all flip the gate, and the waiter then assigns — almost
        always straight into an import hit."""
        if self.prefix_store is None or not self._store_wait:
            return
        waiting = sorted(self._store_wait, key=lambda f: f.seq)
        self._store_wait = []
        for fr in waiting:
            if not fr.is_terminal:
                self._assign(fr, t)

    def _store_gate(self, fr: FrontendRequest, t: int) -> bool:
        """Single-flight de-dup: True = proceed to routing, False =
        coalesced into ``_store_wait`` behind another request's
        prefill lease.  Deterministic: every input is the tick clock,
        the store's contents, and seq order."""
        store = self.prefix_store
        if store is None or fr.tokens:
            return True   # resumes re-prefill their own stream
        ps = self.engine_config.page_size
        key_toks = chain_tokens(fr.prompt, ps)
        if key_toks is None:
            return True   # no full page is shareable
        if store.has_chain(fr.prompt, ps, now=t):
            return True   # import will serve it
        if any(h.alive and h.peek_prefix_pages(fr.prompt) > 0
               for h in self.replicas):
            return True   # a replica holds it locally; affinity routes
        key = chain_key(key_toks)
        owner = store.leases.holder(key, now=t)
        if owner is None or owner == fr.request_id:
            if owner is None:   # fresh grant (not a leader refresh)
                self._bb_note("lease_grant", tick=t,
                              request=fr.request_id, key=key[:12])
            store.leases.acquire(key, fr.request_id, now=t)
            return True   # this request leads the flight
        if fr.request_id not in self._coalesced_ids:
            self._coalesced_ids.add(fr.request_id)
            store.note_coalesced()
        self._store_wait.append(fr)
        return False

    def _persist_prefix_store(self, t: int) -> None:
        """Store durability rides the snapshot cadence: with both a
        store and a snapshot directory configured, the whole store
        lands as its own CRC'd-section file every ``snapshot_every``
        ticks — same atomic write discipline as engine snapshots, so
        a warm fleet restart reloads the prefix tier too."""
        if (self.prefix_store is None
                or self.config.snapshot_dir is None
                or self.config.snapshot_every is None
                or (t + 1) % self.config.snapshot_every != 0):
            return
        save_store(
            self.prefix_store,
            os.path.join(self.config.snapshot_dir, STORE_FILENAME),
        )

    def _assign(self, fr: FrontendRequest, t: int,
                exclude: str | None = None) -> None:
        if not self._store_gate(fr, t):
            return
        eligible = self.supervisor.eligible_ids(self.replicas)
        if self.pool_of:
            # role-typed placement is a PREFERENCE, never a
            # correctness boundary: fresh admissions prefer the
            # prefill pool, resumed streams the decode pool, and an
            # empty intersection falls back to the whole healthy set
            pool = "decode" if fr.tokens else "prefill"
            pooled = {rid for rid in sorted(eligible)
                      if self.pool_of.get(rid) == pool}
            if pooled:
                eligible = pooled
        decision = self.router.route(
            fr.prompt, self.replicas, session=fr.session,
            exclude=exclude,
            eligible=eligible,
            store=self.prefix_store, now=t,
        )
        if decision is None:
            # nothing admissible (dead, or gated by the supervisor):
            # back off and hope for a restart or a recovery verdict
            self._requeue(fr, t, ReplicaDeadError(
                f"no alive HEALTHY replica for {fr.request_id} "
                f"at tick {t}"
            ))
            return
        handle = decision.replica
        deadline_step = handle.local_deadline(fr.deadline)
        try:
            if fr.tokens:
                handle.engine.resume_request(
                    fr.prompt, fr.sampling,
                    request_id=fr.request_id,
                    output_tokens=fr.tokens,
                    deadline_step=deadline_step,
                )
            else:
                handle.engine.add_request(
                    fr.prompt, fr.sampling,
                    request_id=fr.request_id,
                    deadline_step=deadline_step,
                )
        except DeadlineExceededError as e:
            self.counts["deadline_expired"] += 1
            _DEADLINE_EXPIRED.inc()
            self._finalize(fr, FrontendRequestState.TIMED_OUT, error=e)
            return
        fr.transition(FrontendRequestState.ASSIGNED)
        fr.replica_id = handle.replica_id
        fr.routed_by = decision.reason
        fr.assigned_tick = t
        fr.waiting_since = None
        self._trace_event(fr, "routed", reason=decision.reason)
        self._trace_event(fr, "admitted")
        self._bb_note("route_decision", replica_id=handle.replica_id,
                      tick=t, request=fr.request_id,
                      reason=decision.reason)
        self.events_log.append(
            ("admit", t, fr.request_id, handle.replica_id))

    def _requeue(self, fr: FrontendRequest, t: int,
                 cause: BaseException) -> None:
        """Retry-with-backoff, or shed when the budget is dry."""
        fr.attempts += 1
        fr.last_replica = fr.replica_id
        fr.replica_id = None
        fr.waiting_since = None
        if fr.attempts > self.config.retry.max_retries:
            self.counts["retries_exhausted"] += 1
            _RETRY_EXHAUSTED.inc()
            err = RequestShedError(
                f"request {fr.request_id}: retry budget "
                f"({self.config.retry.max_retries}) exhausted; last "
                f"cause: {type(cause).__name__}: {cause}"
            )
            err.__cause__ = cause
            self.counts["shed_rejected"] += 1
            _SHED.inc()
            self._finalize(fr, FrontendRequestState.SHED, error=err)
            return
        delay = self.config.retry.delay_ticks(
            self.config.seed, fr.request_id, fr.attempts)
        fr.next_retry = t + delay
        fr.transition(FrontendRequestState.RETRY_WAIT)
        self._trace_event(fr, "retried", attempt=fr.attempts,
                          delay=delay, from_replica=fr.last_replica,
                          cause=type(cause).__name__)
        if fr not in self._retry:
            self._retry.append(fr)
        self.counts["retries_scheduled"] += 1
        _RETRY_SCHED.inc()

    def _step_replicas(self, t: int) -> None:
        """Step every ALIVE replica exactly once — even idle ones, so
        engine step counters stay aligned with the tick and deadline
        translation stays exact."""
        for handle in self.replicas:
            if not handle.alive:
                continue
            try:
                handle.step()
            except OutOfPagesError as e:
                # capacity failure: relieve AND note it — a replica
                # that can't step is sick until proven otherwise
                handle.note_step_error(e)
                self._relieve_pressure(handle, t, e)
            except StepInterruptedError as e:
                # transient, pre-mutation abort: nothing to clean up,
                # nothing to requeue — just feed the error streak
                handle.note_step_error(e)
            else:
                handle.note_step_ok()

    def _relieve_pressure(self, handle: ReplicaHandle, t: int,
                          cause: OutOfPagesError) -> None:
        """A replica's step failed on capacity: pull its youngest
        request (the same victim preemption would pick) back to the
        front end and retry it elsewhere."""
        eng = handle.engine
        live = [*eng.scheduler.waiting, *eng.scheduler.running]
        if not live:
            return
        victim = max(live, key=lambda r: (r.arrival, r.seq))
        fr = self.requests.get(victim.request_id)
        eng.cancel(victim.request_id)
        if fr is not None and fr.state is FrontendRequestState.ASSIGNED:
            self._requeue(fr, t, cause)

    def _supervise(self, t: int) -> None:
        """Score the fleet, act on the verdicts: drain a replica the
        moment it turns SUSPECT (and again on DEGRADED — destinations
        may have freed up), kill + promote a standby on DEAD.  The
        supervisor judges; this method is the only place that acts."""
        verdicts = self.supervisor.observe(t, self.replicas)
        # log EVERY verdict before acting on ANY: observe() moved all
        # the states atomically, so in append order the tick's state
        # changes precede the actions they trigger (a drain routed to
        # a replica whose recovery verdict sits later in the batch
        # must not read as an admission to a sick replica)
        for v in verdicts:
            self.events_log.append((
                "verdict", t, v.replica_id,
                v.old.value, v.new.value, list(v.signals)))
            if v.is_recovery:
                self.counts["supervisor_recoveries"] += 1
            elif v.new is SupervisorState.SUSPECT:
                self.counts["supervisor_suspects"] += 1
            elif v.new is SupervisorState.DEGRADED:
                self.counts["supervisor_degraded"] += 1
            elif v.new is SupervisorState.DEAD:
                self.counts["supervisor_dead"] += 1
        for v in verdicts:
            if v.is_recovery:
                continue
            handle = self._handle(v.replica_id)
            if v.new is SupervisorState.DEAD:
                if handle is not None and handle.alive:
                    # gray failure crossed the line: treat it as
                    # fail-stop (requeues whatever drain left behind)
                    self.kill_replica(v.replica_id)
                self._promote_standby(t, handle)
            elif handle is not None:
                self.migrations.extend(drain_replica(
                    self, handle, tick=t,
                    eligible=self.supervisor.eligible_ids(
                        self.replicas)))

    def _promote_standby(self, t: int,
                         failed: ReplicaHandle | None) -> bool:
        """Replace a DEAD replica with a warm standby: the spare boots
        from the FAILED replica's snapshot directory (its own manager
        then starts a fresh incarnation in the spare's directory), so
        promotion recovers the dead engine's in-flight state just like
        a warm restart — then reconciliation adopts whatever still
        matches the streamed prefixes."""
        if not self.standby_pool:
            return False
        spare = self.standby_pool.pop(0)
        warm_from = failed.snapshot_dir if failed is not None else None
        mode = spare.restart(tick=t, warm_from=warm_from)
        self.replicas.append(spare)
        if self.pool_of and failed is not None:
            # fleet continuity: the replacement serves the dead
            # replica's pool (the dead handle keeps its entry so a
            # chaos restart rejoins its old role)
            pool = self.pool_of.get(failed.replica_id)
            if pool is not None:
                self.pool_of[spare.replica_id] = pool
        self.supervisor.reset(t, spare.replica_id)
        self.counts["standby_promotions"] += 1
        _PROMOTED.inc()
        self._bb_note("standby_promote", replica_id=spare.replica_id,
                      mode=mode,
                      replaced=(failed.replica_id
                                if failed is not None else None))
        if mode == "warm":
            self.counts["warm_restarts"] += 1
            self._reconcile_restored(spare)
        self._apply_ladder_to(spare)
        return True

    # -- migration hooks (called by frontend.migrate.drain_replica) -------

    def note_migrated(self, fr: FrontendRequest, dest: ReplicaHandle,
                      t: int) -> None:
        """Bookkeeping for one completed cut: the request now lives on
        ``dest`` and nowhere else."""
        fr.last_replica = fr.replica_id
        fr.replica_id = dest.replica_id
        fr.routed_by = "migrated"
        fr.assigned_tick = t
        fr.waiting_since = None
        self.counts["live_migrations"] += 1
        self._trace_event(fr, "migrated", source=fr.last_replica,
                          dest=dest.replica_id,
                          tokens_at_cut=len(fr.tokens))
        self._bb_note("replica_migrate", replica_id=dest.replica_id,
                      tick=t, request=fr.request_id,
                      source=fr.last_replica,
                      tokens_at_cut=len(fr.tokens))
        self.events_log.append(
            ("admit", t, fr.request_id, dest.replica_id))

    def note_migration_stranded(self, fr: FrontendRequest) -> None:
        """No HEALTHY destination: the request stays on the sick
        replica (which keeps serving what it already holds)."""
        self.counts["migrations_stranded"] += 1

    def note_migration_timeout(self, fr: FrontendRequest,
                               e: DeadlineExceededError) -> None:
        """The cut found the request already past its deadline in the
        destination's clock; finalize truthfully."""
        self.counts["deadline_expired"] += 1
        _DEADLINE_EXPIRED.inc()
        self._finalize(fr, FrontendRequestState.TIMED_OUT, error=e)

    # -- disaggregation: prompt-commit handoff + elastic autoscaler -------

    def note_handoff(self, fr: FrontendRequest, dest: ReplicaHandle,
                     t: int, *, avoided: int) -> None:
        """Bookkeeping for one completed prefill->decode cut
        (`note_migrated`'s discipline with the fleet counters):
        ``avoided`` is the re-prefill tokens the shipped KV pages
        saved the destination."""
        fr.last_replica = fr.replica_id
        fr.replica_id = dest.replica_id
        fr.routed_by = "handoff"
        fr.assigned_tick = t
        fr.waiting_since = None
        self.counts["handoffs"] += 1
        self.counts["reprefill_avoided_tokens"] += avoided
        self._trace_event(fr, "migrated", source=fr.last_replica,
                          dest=dest.replica_id,
                          tokens_at_cut=len(fr.tokens))
        self._bb_note("handoff", replica_id=dest.replica_id, tick=t,
                      request=fr.request_id, source=fr.last_replica,
                      avoided_tokens=avoided)
        self.events_log.append(
            ("admit", t, fr.request_id, dest.replica_id))

    def _handoff_committed(self, t: int) -> None:
        """Move every prompt-committed stream (first output token
        sampled, so prefill is done) off the prefill pool and onto a
        decode replica, shipping its committed KV pages so the
        destination resumes without re-prefilling.  No decode
        destination = the stream decodes where it prefilled —
        placement is a preference, never a correctness boundary."""
        if not self.pool_of:
            return
        healthy = self.supervisor.eligible_ids(self.replicas)
        decode_ids = {rid for rid in sorted(healthy)
                      if self.pool_of.get(rid) == "decode"}
        for handle in list(self.replicas):
            if (not handle.alive
                    or self.pool_of.get(handle.replica_id)
                    != "prefill"):
                continue
            dest_ids = decode_ids - {handle.replica_id}
            if not dest_ids:
                continue
            eng = handle.engine
            live = sorted(
                [("waiting", r) for r in eng.scheduler.waiting]
                + [("running", r) for r in eng.scheduler.running],
                key=lambda item: item[1].seq,
            )
            for queue, req in live:
                fr = self.requests.get(req.request_id)
                if (fr is None
                        or fr.state is not FrontendRequestState.ASSIGNED
                        or fr.replica_id != handle.replica_id
                        or not req.output_tokens):
                    continue
                self._handoff_one(handle, queue, req, fr, t, dest_ids)

    def _handoff_one(self, source: ReplicaHandle, queue: str, req,
                     fr: FrontendRequest, t: int,
                     dest_ids: set[str]) -> None:
        """One prefill->decode cut: serialize (PR 9 section format),
        export the committed KV pages, cancel on the source, import +
        resume on the destination.  A corrupt payload is a typed
        `HandoffCorruptError` + re-prefill fallback — the destination
        rebuilds the prefix from the prompt; tokens are never wrong,
        only slower."""
        rec = _request_to_dict(req, queue)
        decision = self.router.route(
            fr.prompt, self.replicas, session=fr.session,
            exclude=source.replica_id, eligible=dest_ids,
        )
        if decision is None:
            return
        dest = decision.replica
        blob = export_handoff(source.engine, req, rec)
        if blob is not None and self._poison_handoffs > 0:
            # chaos `handoff_poison`: flip one payload byte past the
            # manifest so the section CRC — not the JSON parse — is
            # what catches it
            self._poison_handoffs -= 1
            mid = len(blob) // 2
            blob = (blob[:mid] + bytes([blob[mid] ^ 0xFF])
                    + blob[mid + 1:])
        # THE CUT (`frontend.migrate` discipline): source first,
        # destination second — exactly one engine ever holds it
        source.engine.cancel(req.request_id)
        avoided = 0
        if blob is not None:
            try:
                avoided = import_handoff(dest.engine, blob, now=t)
            except HandoffCorruptError:
                self.counts["handoff_fallbacks"] += 1
                self._bb_note("handoff_fallback",
                              replica_id=dest.replica_id, tick=t,
                              request=fr.request_id,
                              source=source.replica_id)
                self._incident("typed_error", {
                    "error": "HandoffCorruptError",
                    "request": fr.request_id,
                    "source": source.replica_id,
                    "dest": dest.replica_id})
        outs = [int(tok) for tok in rec["output_tokens"]]
        sampling = SamplingParams(**rec["sampling"])
        try:
            dest.engine.resume_request(
                rec["prompt"], sampling,
                request_id=fr.request_id, output_tokens=outs,
                deadline_step=dest.local_deadline(fr.deadline),
            )
        except DeadlineExceededError as e:
            self.note_migration_timeout(fr, e)
            self.migrations.append(MigrationRecord(
                tick=t, request_id=fr.request_id,
                source=source.replica_id, dest=None,
                tokens_at_cut=len(fr.tokens), record=rec))
            return
        _trace.adopt(fr.request_id, rec.get("trace", []))
        self.note_handoff(fr, dest, t, avoided=avoided)
        self.migrations.append(MigrationRecord(
            tick=t, request_id=fr.request_id,
            source=source.replica_id, dest=dest.replica_id,
            tokens_at_cut=len(fr.tokens), record=rec))

    def _vetoed_pools(self) -> tuple[str, ...]:
        """Pools the anomaly detectors currently implicate: a
        gray-failure key names a replica, hence its pool; any other
        active firing is fleet-wide and vetoes both."""
        if self.anomaly is None or not self.anomaly.active:
            return ()
        vetoed: set[str] = set()
        for _detector, key in sorted(self.anomaly.active):
            pool = self.pool_of.get(key)
            if pool is not None:
                vetoed.add(pool)
            else:
                vetoed.update(POOLS)
        return tuple(sorted(vetoed))

    def _autoscale(self, t: int) -> None:
        """One controller tick: settle armed mis-actuation guards,
        feed the per-pool pressures, execute the decided actions.
        Runs after `_update_ladder_and_gauges` so the anomaly active
        set feeding the veto is this tick's, not last tick's."""
        if self.autoscaler is None:
            return
        self._check_guards(t)
        pressures: dict[str, float] = {}
        sizes: dict[str, int] = {}
        for pool in POOLS:
            members = [h for h in self.replicas
                       if self.pool_of.get(h.replica_id) == pool]
            sizes[pool] = sum(1 for h in members if h.alive)
            if any(h.alive for h in members):
                _, mean = pool_pressure(
                    members, queue_cap=self.config.shed.queue_cap)
            else:
                mean = 1.0   # an empty/dead pool is saturated
            pressures[pool] = mean
        forced, self._force_demotions = self._force_demotions, 0
        actions = self.autoscaler.decide(
            t, pressures=pressures, pool_sizes=sizes,
            standbys=len(self.standby_pool),
            vetoed=self._vetoed_pools(), forced=forced)
        for act in actions:
            if act.kind == "veto":
                self.counts["actuation_vetoes"] += 1
                self._bb_note("actuation_veto", tick=t,
                              pool=act.pool, cause=act.cause)
            elif act.kind == "scale_up":
                self._scale_up(t, act.pool, act.cause)
            else:
                self._scale_down(t, act.pool, act.cause)

    def _scale_up(self, t: int, pool: str, cause: str) -> None:
        """Promote the next standby (cold boot) into ``pool`` — the
        `_promote_standby` mechanics minus the failed-replica warm
        source, plus the actuation ledger entry."""
        if not self.standby_pool:
            return
        spare = self.standby_pool.pop(0)
        spare.restart(tick=t)
        self.replicas.append(spare)
        self.pool_of[spare.replica_id] = pool
        self.supervisor.reset(t, spare.replica_id)
        self._apply_ladder_to(spare)
        self.counts["scale_ups"] += 1
        self.actuations.append(ActuationRecord(
            tick=t, kind="scale_up", pool=pool,
            replica_id=spare.replica_id, cause=cause))
        self._bb_note("scale_up", replica_id=spare.replica_id,
                      tick=t, pool=pool, cause=cause)

    def _scale_down(self, t: int, pool: str, cause: str) -> None:
        """Drain + demote the youngest alive member of ``pool`` back
        to the standby bench, then arm the mis-actuation guard: a
        shed inside ``guard_window`` ticks indicts this decision
        (incident cause ``actuation``)."""
        members = [h for h in self.replicas
                   if self.pool_of.get(h.replica_id) == pool
                   and h.alive]
        if not members:
            return
        victim = members[-1]
        healthy = self.supervisor.eligible_ids(self.replicas)
        dest_ids = {rid for rid in sorted(healthy)
                    if self.pool_of.get(rid) == pool
                    and rid != victim.replica_id}
        if not dest_ids:
            dest_ids = {rid for rid in sorted(healthy)
                        if rid != victim.replica_id}
        drained = drain_replica(self, victim, tick=t,
                                eligible=dest_ids)
        self.migrations.extend(drained)
        leftovers = sorted(
            (fr for fr in self.requests.values()
             if fr.state is FrontendRequestState.ASSIGNED
             and fr.replica_id == victim.replica_id),
            key=lambda f: f.seq)
        # note BEFORE the kill so the record carries the demoted
        # incarnation's live coordinates (`kill_replica` discipline)
        self._bb_note("scale_down", replica_id=victim.replica_id,
                      tick=t, pool=pool, cause=cause,
                      drained=len(drained))
        victim.kill()
        self.router.forget_replica(victim.replica_id)
        self.replicas.remove(victim)
        del self.pool_of[victim.replica_id]
        self.standby_pool.append(victim)
        err = ReplicaDeadError(
            f"replica {victim.replica_id} demoted to standby at "
            f"tick {t}")
        for fr in leftovers:
            self._requeue(fr, t, err)
        self.counts["scale_downs"] += 1
        self.actuations.append(ActuationRecord(
            tick=t, kind="scale_down", pool=pool,
            replica_id=victim.replica_id, cause=cause))
        self._guards.append((t, pool, self.counts["shed_rejected"]))

    def _check_guards(self, t: int) -> None:
        """Settle armed mis-actuation guards: a scale-down followed
        by ANY shed inside its guard window was capacity the fleet
        still needed — dump one ``actuation`` incident and disarm;
        a guard that ages out clean just expires."""
        if not self._guards:
            return
        gw = self.config.autoscaler.guard_window
        keep: list[tuple[int, str, int]] = []
        for (t0, pool, sheds0) in self._guards:
            if self.counts["shed_rejected"] > sheds0:
                self._incident("actuation", {
                    "pool": pool, "scale_down_tick": t0,
                    "sheds": self.counts["shed_rejected"] - sheds0})
            elif t - t0 < gw:
                keep.append((t0, pool, sheds0))
        self._guards = keep

    def _migrate_stalled(self, t: int) -> None:
        """Admission-stall detection: a request that has sat in a
        replica's waiting queue (injected OOM window, watermark flap,
        pool too full) for ``stall_ticks`` consecutive ticks migrates
        to another replica through the retry path."""
        for handle in self.replicas:
            if not handle.alive:
                continue
            waiting_ids = {r.request_id
                           for r in handle.engine.scheduler.waiting}
            assigned = [fr for fr in self.requests.values()
                        if fr.state is FrontendRequestState.ASSIGNED
                        and fr.replica_id == handle.replica_id]
            for fr in sorted(assigned, key=lambda f: f.seq):
                if fr.request_id not in waiting_ids:
                    fr.waiting_since = None
                    continue
                if fr.waiting_since is None:
                    fr.waiting_since = t
                    continue
                if t - fr.waiting_since + 1 < self.config.stall_ticks:
                    continue
                handle.engine.cancel(fr.request_id)
                self.counts["migrations"] += 1
                _MIGRATED.inc()
                self._requeue(fr, t, OutOfPagesError(
                    f"request {fr.request_id} admission-stalled on "
                    f"{handle.replica_id} for "
                    f"{self.config.stall_ticks} ticks"
                ))

    def _apply_ladder_to(self, handle: ReplicaHandle) -> None:
        if not handle.alive:
            return
        eng = handle.engine
        level = self.ladder.level
        base = self.engine_config.token_budget
        eng.scheduler.token_budget = (
            base if level < 1
            else max(1, int(base * self.config.degrade
                            .token_budget_factor))
        )
        eng.scheduler.prefix_admission = level < 2

    def _update_ladder_and_gauges(self, t: int) -> None:
        _, mean = pool_pressure(
            self.replicas, queue_cap=self.config.shed.queue_cap)
        old = self.ladder.level
        new = self.ladder.observe(mean)
        if new != old:
            (_STEP_DOWN if new > old else _RECOVER).inc()
            for handle in self.replicas:
                self._apply_ladder_to(handle)
        if self.forecast is not None:
            self._observe_forecast(t, mean)
        if self.anomaly is not None:
            self._observe_anomaly(t, mean)
        if obs.enabled():
            _LEVEL_G.set(self.ladder.level)
            _PRESSURE_G.set(mean)
            for handle in self.replicas:
                load = handle.load()
                _R_QUEUE_G.set(load["waiting"] + load["running"],
                               replica=handle.replica_id)
                _R_UTIL_G.set(load["page_utilization"],
                              replica=handle.replica_id)

    def _observe_forecast(self, t: int, mean: float) -> None:
        """Feed the per-tick sample row; then — advisory mode only —
        log what forecast-driven admission WOULD have done.  Nothing
        here feeds back into routing, shedding, or the ladder: the
        forecast stays a measurement until the elastic-scaling PR."""
        tracker = self.forecast
        depth = 0
        for handle in self.replicas:
            if handle.alive:
                load = handle.load()
                depth += load["waiting"] + load["running"]
        admits = sum(1 for ev in self.events_log[tracker.events_seen:]
                     if ev[0] == "admit")
        tracker.events_seen = len(self.events_log)
        pred = tracker.record_tick(mean, depth, admits)
        if not tracker.policy.advisory:
            return
        shed_wm = self.config.shed.shed_pressure
        down_wm = self.config.shed.downclass_pressure
        if pred >= shed_wm and mean < shed_wm:
            self.events_log.append(
                ("forecast", t, "would_shed", _r6(pred), _r6(mean)))
        elif pred >= down_wm and mean < down_wm:
            self.events_log.append(
                ("forecast", t, "would_downclass", _r6(pred), _r6(mean)))

    def _observe_anomaly(self, t: int, mean: float) -> None:
        """Run the online anomaly detectors over this tick's
        frozen-series inputs.  Advisory-only (the forecast contract):
        a firing lands in the event log, the flight recorder, and —
        with an ``incident_dir`` — one postmortem bundle; control
        flow never reads it."""
        tracker = self.anomaly
        tracker.observe_pressure(t, mean)
        new = tracker.step(t)
        for f in new:
            self.counts["anomaly_firings"] += 1
            self.events_log.append((
                "anomaly", t, f["detector"], f["key"],
                f["value"], f["bound"]))
            key = f["key"]
            # a gray-failure key IS a replica id; stamp it so the
            # ring record carries the suspect's coordinates
            rid = key if self._handle(key) is not None else None
            self._bb_note("anomaly_fire", replica_id=rid, tick=t,
                          detector=f["detector"], key=key,
                          value=f["value"], bound=f["bound"])
            self._incident("detector", {
                "detector": f["detector"], "key": key,
                "value": f["value"], "bound": f["bound"]})
        tracker.publish(new)

    @property
    def forecast_pressure(self) -> float | None:
        """One-step-ahead mean-pressure forecast (None while
        forecasting is disabled).  Advisory surface for the supervisor
        / ladder dashboards; control flow never reads it (the
        zero-overhead contract in `frontend.degrade`)."""
        return (None if self.forecast is None
                else self.forecast.forecast_pressure)

    # -- reporting --------------------------------------------------------

    def forecast_report(self, *,
                        horizon: int | None = None) -> dict[str, Any]:
        """The observatory document (`obs.capacity.observatory_report`)
        over this run's recorded samples; ValueError while forecasting
        is disabled."""
        if self.forecast is None:
            raise ValueError(
                "forecasting is disabled (FrontendConfig.forecast is "
                "None); construct the front end with a ForecastPolicy")
        return self.forecast.report(
            self.latency_rows(),
            alive=sum(1 for h in self.replicas if h.alive),
            shed_pressure=self.config.shed.shed_pressure,
            downclass_pressure=self.config.shed.downclass_pressure,
            horizon=horizon)

    def outputs(self) -> dict[str, list[int]]:
        """Streamed tokens per request, submission order."""
        return {fr.request_id: list(fr.tokens)
                for fr in sorted(self.requests.values(),
                                 key=lambda f: f.seq)}

    def latency_rows(self) -> list[dict[str, Any]]:
        """Per-request latency rows in the `obs.slo` schema, submission
        order.  Pure bookkeeping (works with telemetry disabled): the
        SLO observatory is a deterministic function of these rows."""
        rows: list[dict[str, Any]] = []
        for fr in sorted(self.requests.values(), key=lambda f: f.seq):
            rows.append({
                "request_id": fr.request_id,
                "tenant": fr.session or "default",
                "priority": fr.priority,
                "submit_tick": fr.arrival,
                "first_token_tick": fr.first_token_tick,
                "finish_tick": (fr.finish_tick if fr.finish_tick >= 0
                                else self._tick),
                "output_tokens": len(fr.tokens),
                "state": fr.state.value,
            })
        return rows

    def summary(self) -> dict[str, Any]:
        """Deterministic run aggregate: every field is a pure function
        of (seed, trace, fault plan) — no wall-clock anywhere, which is
        what lets the chaos storm pin byte-identical reports."""
        frs = sorted(self.requests.values(), key=lambda f: f.seq)
        by_state = {s.value: 0 for s in FrontendRequestState}
        for fr in frs:
            by_state[fr.state.value] += 1
        finished = [fr for fr in frs
                    if fr.state is FrontendRequestState.FINISHED]
        fin_prompt = sum(len(fr.prompt) for fr in finished)
        fin_cached = sum(fr.prefix_cached_tokens for fr in finished)
        store_block: dict[str, Any] = {}
        if self.prefix_store is not None:
            st = self.prefix_store
            store_block["prefixstore"] = {
                **{k: st.counts[k] for k in sorted(st.counts)},
                "entries": len(st),
                "bytes": st.total_bytes,
                # the fleet-level rate: local affinity hits PLUS
                # store-imported chains, over finished prompt tokens
                "fleet_prefix_hit_rate": round(
                    fin_cached / fin_prompt, 4) if fin_prompt else 0.0,
                "imported_tokens": st.counts["import_tokens"],
            }
        fleet_block: dict[str, Any] = {}
        if self.pool_of:
            fleet_block["fleet"] = {
                "pools": {pool: sum(
                    1 for rid in sorted(self.pool_of)
                    if self.pool_of[rid] == pool) for pool in POOLS},
                "actuations": len(self.actuations),
            }
        return {
            "ticks": self._tick,
            "num_requests": len(frs),
            "states": by_state,
            "streamed_tokens": sum(len(fr.tokens) for fr in frs),
            "finished_output_tokens": sum(len(fr.tokens)
                                          for fr in finished),
            "finished_prompt_tokens": fin_prompt,
            "prefix_cached_tokens": fin_cached,
            "prefix_cache_hit_rate": round(
                fin_cached / fin_prompt, 4) if fin_prompt else 0.0,
            "replica_deaths": sum(h.deaths for h in self.replicas),
            "alive_replicas": sum(1 for h in self.replicas if h.alive),
            "warm_fallbacks": sum(
                h.warm_fallbacks
                for h in (*self.replicas, *self.standby_pool)),
            "standbys_remaining": len(self.standby_pool),
            "supervisor_states": self.supervisor.states(),
            "degrade_level": self.ladder.level,
            "degrade_step_downs": self.ladder.step_downs,
            "degrade_recoveries": self.ladder.recoveries,
            **store_block,
            **fleet_block,
            **self.counts,
        }

    def to_run_record(self, *, config: str = "frontend-serve",
                      extra: dict[str, Any] | None = None) -> RunRecord:
        """The run as the repo's uniform benchmark row.  Deliberately
        deterministic: timing fields (and the record timestamp) are
        zero — the front end's unit of time is the tick — so same
        seed -> byte-identical record."""
        s = self.summary()
        record = RunRecord(
            timestamp=0.0,
            config=config,
            backend="frontend",
            m=s["finished_prompt_tokens"],
            n=s["finished_output_tokens"],
            dk=0,
            dv=0,
            dtype="",
            best_us=0.0,
            median_us=0.0,
            gflops_per_chip=0.0,
            utilization=0.0,
            device_kind="virtual",
            n_devices=self.config.num_replicas,
            extra={**s, **(extra or {})},
        )
        obs.record_run(record)
        return record


def replay_frontend(frontend: ServingFrontend,
                    trace: Sequence[dict[str, Any]], *,
                    max_ticks: int | None = 10000):
    """Feed a trace (the `engine.sim` JSON schema, plus the optional
    resilience fields ``session`` / ``priority`` / ``deadline_ticks``)
    through a front end and run it dry; returns ``(summary, outputs)``
    like `engine.sim.replay` so single-engine baselines and
    multi-replica runs compare directly."""
    for entry in trace:
        frontend.submit(
            entry["prompt"], sampling_of(entry),
            request_id=entry.get("id"),
            arrival=int(entry.get("arrival", 0)),
            ttl_ticks=entry.get("deadline_ticks"),
            priority=int(entry.get("priority", 1)),
            session=entry.get("session"),
        )
    summary = frontend.run(max_ticks=max_ticks)
    return summary, frontend.outputs()
