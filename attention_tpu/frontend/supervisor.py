"""`ReplicaSupervisor`: deterministic gray-failure detection.

PR 6's front end only learns about a sick replica when it is already a
corpse (`ReplicaDeadError` on touch).  Real replicas rarely die that
politely — they get *slow*, they error *intermittently*, they *stall*
silently, their numerics go non-finite — and a front end that waits
for fail-stop serves garbage latency in the meantime.  The supervisor
closes that gap: every tick it scores each replica from four signals
the stack already emits, and drives a per-replica state machine

    HEALTHY ──bad──> SUSPECT ──bad──> DEGRADED ──bad──> DEAD
       ▲               │                 │
       └──recover──────┘ <───recover─────┘

with hysteresis on both edges (``*_after`` consecutive bad ticks to
step down, ``recover_after`` consecutive clean ticks to step back up
ONE level), so a single hiccup never triggers a migration and a
genuinely sick replica cannot flap back to HEALTHY on one good tick.

Signals (all host-side, all deterministic under the seeded virtual
clock — no wall time anywhere):

* **slow step** — per-replica EWMA of the engine's *virtual* step cost
  (`ServingEngine.last_step_virtual_cost`; 1.0 unless a chaos
  slow-step injector inflates it) at least ``slow_factor`` × the fleet
  median.  Real ``StepMetrics.wall_s`` is deliberately NOT used: it
  would make verdicts nondeterministic.
* **error streak** — ``ReplicaHandle.step_error_streak`` (consecutive
  typed step errors noted by the front end) ≥ ``error_streak``.
* **stall** — the engine's step counter unchanged for ``stall_ticks``
  consecutive observations.  The front end steps every alive replica
  every tick, so an idle-but-healthy engine still advances; a frozen
  counter means the step is being swallowed.
* **non-finite logits** — ``ServingEngine.nonfinite_events`` grew
  since the last observation (the engine's finite guard rejected a
  logits row before sampling).

The supervisor only *judges*; the front end *acts* on the verdicts it
returns (migrate on SUSPECT, bar admissions from anything non-HEALTHY,
kill + promote a standby on DEAD).  A fail-stop kill shows up here as
an immediate DEAD verdict (signal ``fail_stop``) so standby promotion
covers both gray and fail-stop deaths.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Sequence

from attention_tpu import obs
from attention_tpu.frontend.replica import ReplicaHandle

_VERDICTS = obs.counter("frontend.supervisor.verdicts",
                        "replica state-machine transitions")
_SIGNALS = obs.counter("frontend.supervisor.signals",
                       "bad-tick signals observed per kind")
_STATE_G = obs.gauge("frontend.supervisor.state",
                     "per-replica supervisor state (0=healthy..3=dead)")
_STREAK_G = obs.gauge("frontend.supervisor.bad_streak",
                      "consecutive bad ticks toward the next step-down")


class SupervisorState(enum.Enum):
    HEALTHY = "healthy"
    SUSPECT = "suspect"
    DEGRADED = "degraded"
    DEAD = "dead"


#: severity order, used for the gauge and the one-level recovery step
_SEVERITY = {
    SupervisorState.HEALTHY: 0,
    SupervisorState.SUSPECT: 1,
    SupervisorState.DEGRADED: 2,
    SupervisorState.DEAD: 3,
}

#: recovery steps UP one level at a time (DEAD only leaves via restart)
_RECOVER_TO = {
    SupervisorState.SUSPECT: SupervisorState.HEALTHY,
    SupervisorState.DEGRADED: SupervisorState.SUSPECT,
}


@dataclasses.dataclass(frozen=True)
class SupervisorPolicy:
    """Detection thresholds; every time-like field is in ticks."""

    suspect_after: int = 2    # consecutive bad ticks HEALTHY -> SUSPECT
    degrade_after: int = 2    # further bad ticks SUSPECT -> DEGRADED
    dead_after: int = 3       # further bad ticks DEGRADED -> DEAD
    recover_after: int = 3    # consecutive clean ticks to step back up
    slow_factor: float = 3.0  # EWMA >= factor * fleet median -> slow
    ewma_alpha: float = 0.5   # virtual-step-cost EWMA weight
    stall_ticks: int = 3      # frozen step counter for this long
    error_streak: int = 2     # consecutive typed step errors

    def validate(self) -> None:
        if min(self.suspect_after, self.degrade_after, self.dead_after,
               self.recover_after, self.stall_ticks,
               self.error_streak) < 1:
            raise ValueError(
                "supervisor thresholds (suspect_after, degrade_after, "
                "dead_after, recover_after, stall_ticks, error_streak) "
                "must all be >= 1"
            )
        if self.slow_factor <= 1.0:
            raise ValueError(
                f"slow_factor must be > 1 (a replica at the fleet "
                f"median is not slow), got {self.slow_factor}"
            )
        if not (0.0 < self.ewma_alpha <= 1.0):
            raise ValueError(
                f"ewma_alpha must be in (0, 1], got {self.ewma_alpha}"
            )


@dataclasses.dataclass(frozen=True)
class Verdict:
    """One state-machine transition, as the front end receives it."""

    tick: int
    replica_id: str
    old: SupervisorState
    new: SupervisorState
    signals: tuple[str, ...]  # the bad signals active at the verdict

    @property
    def is_recovery(self) -> bool:
        return _SEVERITY[self.new] < _SEVERITY[self.old]


class _Track:
    """Per-replica detection state (plain mutable bag)."""

    __slots__ = ("state", "ewma", "last_step", "stall_count",
                 "last_nonfinite", "bad_streak", "ok_streak")

    def __init__(self):
        self.state = SupervisorState.HEALTHY
        self.ewma = 1.0
        self.last_step: int | None = None
        self.stall_count = 0
        self.last_nonfinite = 0
        self.bad_streak = 0
        self.ok_streak = 0


class ReplicaSupervisor:
    """Per-tick failure detector over a fleet of `ReplicaHandle`s.

    Pure judgement: ``observe`` returns the tick's verdicts and the
    caller (the front end) performs migration / admission-gating /
    promotion.  Everything is integer-and-float arithmetic over
    deterministic inputs, so same seed -> same verdict sequence."""

    def __init__(self, policy: SupervisorPolicy | None = None):
        self.policy = policy or SupervisorPolicy()
        self.policy.validate()
        self._tracks: dict[str, _Track] = {}
        #: every transition ever issued, in order (chaos checkers read
        #: the front end's unified event log; this is the local copy)
        self.history: list[Verdict] = []

    # -- state access ------------------------------------------------------

    def state(self, replica_id: str) -> SupervisorState:
        track = self._tracks.get(replica_id)
        return track.state if track is not None else \
            SupervisorState.HEALTHY

    def states(self) -> dict[str, str]:
        return {rid: t.state.value
                for rid, t in sorted(self._tracks.items())}

    def eligible_ids(self, replicas: Sequence[ReplicaHandle]
                     ) -> set[str]:
        """Replicas new admissions may route to: alive AND HEALTHY."""
        return {h.replica_id for h in replicas
                if h.alive
                and self.state(h.replica_id) is SupervisorState.HEALTHY}

    def _track(self, replica_id: str) -> _Track:
        track = self._tracks.get(replica_id)
        if track is None:
            track = self._tracks[replica_id] = _Track()
        return track

    def reset(self, tick: int, replica_id: str) -> Verdict | None:
        """A replica came back (restart or standby promotion): fresh
        engine, fresh judgement.  Returns the recovery verdict when
        the tracked state actually changes."""
        track = self._track(replica_id)
        old = track.state
        self._tracks[replica_id] = _Track()
        if old is SupervisorState.HEALTHY:
            return None
        verdict = Verdict(tick=tick, replica_id=replica_id, old=old,
                          new=SupervisorState.HEALTHY,
                          signals=("restart",))
        self.history.append(verdict)
        _VERDICTS.inc(state="healthy")
        return verdict

    # -- the per-tick judgement --------------------------------------------

    def _signals_for(self, handle: ReplicaHandle, track: _Track,
                     fleet_median: float) -> tuple[str, ...]:
        p = self.policy
        engine = handle.engine
        signals = []
        if (fleet_median > 0.0
                and track.ewma >= p.slow_factor * fleet_median):
            signals.append("slow_step")
        if handle.step_error_streak >= p.error_streak:
            signals.append("error_streak")
        cur = engine.current_step
        if track.last_step is not None and cur == track.last_step:
            track.stall_count += 1
        else:
            track.stall_count = 0
        track.last_step = cur
        if track.stall_count >= p.stall_ticks:
            signals.append("stall")
        if engine.nonfinite_events > track.last_nonfinite:
            signals.append("nonfinite_logits")
        track.last_nonfinite = engine.nonfinite_events
        return tuple(signals)

    def observe(self, tick: int,
                replicas: Sequence[ReplicaHandle]) -> list[Verdict]:
        """Score every replica once; returns this tick's transitions
        in replica order."""
        p = self.policy
        alive = [h for h in replicas if h.alive]
        # fleet view first: EWMA update for everyone, then the median
        # the slow signal compares against (lower median — with two
        # replicas, one slow outlier must not drag the baseline up)
        for handle in alive:
            track = self._track(handle.replica_id)
            cost = float(handle.engine.last_step_virtual_cost)
            track.ewma = (p.ewma_alpha * cost
                          + (1.0 - p.ewma_alpha) * track.ewma)
        ewmas = sorted(self._tracks[h.replica_id].ewma for h in alive)
        fleet_median = ewmas[(len(ewmas) - 1) // 2] if ewmas else 0.0

        verdicts: list[Verdict] = []
        for handle in replicas:
            track = self._track(handle.replica_id)
            if not handle.alive:
                if track.state is not SupervisorState.DEAD:
                    verdicts.append(self._transit(
                        tick, handle.replica_id, track,
                        SupervisorState.DEAD, ("fail_stop",)))
                continue
            if track.state is SupervisorState.DEAD:
                # a DEAD verdict on a live replica means the front end
                # is about to kill it; nothing more to judge until a
                # restart resets the track
                continue
            signals = self._signals_for(handle, track, fleet_median)
            for s in signals:
                _SIGNALS.inc(signal=s)
            if signals:
                track.bad_streak += 1
                track.ok_streak = 0
            else:
                track.ok_streak += 1
                track.bad_streak = 0
            down_after = {
                SupervisorState.HEALTHY: p.suspect_after,
                SupervisorState.SUSPECT: p.degrade_after,
                SupervisorState.DEGRADED: p.dead_after,
            }[track.state]
            down_to = {
                SupervisorState.HEALTHY: SupervisorState.SUSPECT,
                SupervisorState.SUSPECT: SupervisorState.DEGRADED,
                SupervisorState.DEGRADED: SupervisorState.DEAD,
            }[track.state]
            if track.bad_streak >= down_after:
                verdicts.append(self._transit(
                    tick, handle.replica_id, track, down_to, signals))
            elif (track.state in _RECOVER_TO
                    and track.ok_streak >= p.recover_after):
                verdicts.append(self._transit(
                    tick, handle.replica_id, track,
                    _RECOVER_TO[track.state], signals))
        if obs.enabled():
            for handle in replicas:
                _STATE_G.set(
                    _SEVERITY[self.state(handle.replica_id)],
                    replica=handle.replica_id)
                _STREAK_G.set(
                    self._track(handle.replica_id).bad_streak,
                    replica=handle.replica_id)
        return verdicts

    def _transit(self, tick: int, replica_id: str, track: _Track,
                 new: SupervisorState,
                 signals: tuple[str, ...]) -> Verdict:
        verdict = Verdict(tick=tick, replica_id=replica_id,
                          old=track.state, new=new, signals=signals)
        track.state = new
        track.bad_streak = 0
        track.ok_streak = 0
        self.history.append(verdict)
        _VERDICTS.inc(state=new.value)
        return verdict
