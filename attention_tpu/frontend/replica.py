"""One engine replica behind a kill/restart-able handle.

The front end never touches a `ServingEngine` directly: every access
goes through a :class:`ReplicaHandle`, which is the unit of failure —
the chaos harness kills a handle mid-storm and the front end must
recover from its OWN bookkeeping (streamed tokens, retry queue), never
from the dead engine's internals.  ``kill`` therefore drops the engine
reference entirely: any later touch raises the typed
`ReplicaDeadError`, so a resurrection bug reads as a typed error, not
as silently serving from a corpse.

``restart`` brings the replica back one of two ways:

* **warm** (``warm_from=`` a snapshot directory): the newest valid
  snapshot + journal replay reconstruct the dead engine's full state
  (`engine.snapshot.recover_engine`) — pages, prefix cache, in-flight
  requests, RNG positions — so recovery cost is bounded by snapshot
  lag.  Any `SnapshotError` (corrupt, missing, version-skewed) falls
  through to the cold path; durability failures degrade, never crash.
* **cold** (default, and the warm fallback): a fresh engine — empty
  pool, empty prefix cache, step counter 0, exactly what a real
  process restart gives you; in-flight work re-enters via the front
  end's retry machinery (`resume_request`, full re-prefill).

Either way ``restart`` records the tick the replica came back, which
is what keeps deadline translation exact: a replica's engine counts
steps from ITS OWN birth (warm restore keeps the restored step), so
the handle converts front-end ticks to local engine steps via
``start_tick``.

Mesh-sharded replicas need NOTHING here: ``EngineConfig.mesh_shards``
rides inside the config this handle already holds, so every replica
built from it serves through KV-head-sharded kernels, snapshots land
in the per-shard layout, and warm/cold restart logic is unchanged —
`recover_engine` reassembles the per-shard pool sections and the cold
path just builds a fresh mesh engine.  Token streams are identical to
a single-device replica's by the engine's parity contract, so the
front end's retry/dedup bookkeeping composes untouched.
"""

from __future__ import annotations

from typing import Any, Callable

from attention_tpu import obs
from attention_tpu.engine.engine import EngineConfig, ServingEngine
from attention_tpu.engine.errors import (
    ReplicaDeadError,
    ReplicaStateError,
    SnapshotError,
)
from attention_tpu.engine.metrics import StepMetrics
from attention_tpu.engine.request import Request
from attention_tpu.engine.snapshot import SnapshotManager, recover_engine

_WARM_FALLBACK = obs.counter(
    "frontend.replica.warm_fallbacks",
    "warm restarts that degraded to the cold path (typed cause kept "
    "on the handle)")


class ReplicaHandle:
    """One serving replica: engine + liveness + clock translation."""

    def __init__(self, replica_id: str, model, params,
                 config: EngineConfig, *, start_tick: int = 0,
                 snapshot_dir: str | None = None,
                 snapshot_every: int | None = None,
                 on_token: Callable[[Request, int], None] | None = None,
                 on_finish: Callable[[Request], None] | None = None,
                 on_timeout: Callable[[Request], None] | None = None,
                 spare: bool = False,
                 prefix_store: Any = None):
        self.replica_id = replica_id
        self.model = model
        self.params = params
        self.config = config
        self.start_tick = start_tick
        self.deaths = 0
        self.snapshot_dir = snapshot_dir
        self.snapshot_every = snapshot_every
        #: fleet prefix store (attention_tpu/prefixstore) every engine
        #: incarnation of this replica attaches to — the store OUTLIVES
        #: kills by design (host bytes, not device state), which is
        #: exactly how a restarted replica re-imports hot prefixes
        #: instead of re-prefilling them
        self.prefix_store = prefix_store
        #: "warm" | "cold" | None — how the last restart came back
        self.last_restart_mode: str | None = None
        #: why the last warm restart fell back cold (None after a
        #: successful warm restart); ``warm_fallbacks`` counts them
        self.last_warm_fallback: SnapshotError | None = None
        self.warm_fallbacks = 0
        #: consecutive typed step errors — the supervisor's error
        #: signal; the front end calls note_step_error/note_step_ok
        self.step_error_streak = 0
        self.last_step_error: BaseException | None = None
        self._manager: SnapshotManager | None = None
        self._callbacks = (on_token, on_finish, on_timeout)
        # a SPARE (warm standby) is born without an engine — it costs
        # nothing until a DEAD verdict promotes it via restart()
        self._engine: ServingEngine | None = (
            None if spare else self._fresh_engine())

    def _fresh_engine(self) -> ServingEngine:
        on_token, on_finish, on_timeout = self._callbacks
        engine = ServingEngine(self.model, self.params, self.config,
                               on_token=on_token, on_finish=on_finish,
                               on_timeout=on_timeout)
        engine.prefix_store = self.prefix_store
        self._attach_snapshots(engine)
        self._stamp_trace(engine)
        return engine

    def _stamp_trace(self, engine: ServingEngine) -> None:
        """Give the engine its trace coordinates (obs.trace): events it
        records carry THIS replica's id and incarnation, with its step
        counter translated into front-end ticks via ``start_tick``.
        Owner "frontend" hands the request-lifecycle events (submitted/
        admitted/terminals) to the front end — the engine keeps only
        the scheduling events it alone can see."""
        engine.trace_replica = self.replica_id
        engine.trace_incarnation = self.deaths
        engine.trace_start_tick = self.start_tick
        engine.trace_owner = "frontend"

    def _attach_snapshots(self, engine: ServingEngine) -> None:
        if self.snapshot_dir and self.snapshot_every:
            self._manager = SnapshotManager(
                engine, self.snapshot_dir, every=self.snapshot_every,
            )

    # -- liveness ---------------------------------------------------------

    @property
    def alive(self) -> bool:
        return self._engine is not None

    @property
    def engine(self) -> ServingEngine:
        if self._engine is None:
            raise ReplicaDeadError(
                f"replica {self.replica_id} is dead "
                f"(death #{self.deaths})"
            )
        return self._engine

    def kill(self) -> None:
        """Simulated fail-stop: the engine (and every page, cache
        entry, and in-flight request it held) is gone.  Idempotent —
        killing a corpse changes nothing.  Snapshots and journals on
        disk survive by construction: that is the durability contract
        ``restart(warm_from=...)`` recovers from."""
        if self._engine is not None:
            if self._manager is not None:
                # release the journal's append handle before dropping
                # the references — a kill must not leak an open fd
                self._manager.detach()
            self._engine = None
            self._manager = None
            self.deaths += 1
            self.step_error_streak = 0
            self.last_step_error = None

    def restart(self, *, tick: int,
                warm_from: str | None = None) -> str:
        """Bring the replica back at ``tick``; returns ``"warm"`` or
        ``"cold"``.

        With ``warm_from`` set, attempt `recover_engine` on that
        snapshot directory first; a typed `SnapshotError` (corrupt or
        missing snapshot — including every crash-point chaos injects)
        silently degrades to the cold path.  Cold start: empty pool,
        empty prefix cache, step counter 0.

        Either way, re-attaching the `SnapshotManager` starts a new
        incarnation: recovery reads the dead incarnation's files
        first, then the manager clears them and writes a genesis
        snapshot of the engine that actually came back — so a cold
        restart can never be warm-recovered into the PRE-restart
        state, and step-keyed filenames never mix incarnations."""
        if self._engine is not None:
            raise ReplicaStateError(
                f"replica {self.replica_id} is already alive; "
                "kill it before restarting"
            )
        if warm_from is not None:
            on_token, on_finish, on_timeout = self._callbacks
            try:
                engine, _ = recover_engine(
                    self.model, self.params, warm_from,
                    on_token=on_token, on_finish=on_finish,
                    on_timeout=on_timeout,
                )
            except SnapshotError as e:
                # keep the typed cause: "why did this restart cost a
                # full re-prefill" is the first question an operator
                # asks, and the summary surfaces the count
                engine = None
                self.last_warm_fallback = e
                self.warm_fallbacks += 1
                _WARM_FALLBACK.inc()
            if engine is not None:
                # the restored engine keeps its own step counter, so
                # anchor the clock translation at its restored step
                self.start_tick = tick - engine.current_step
                engine.prefix_store = self.prefix_store
                self._engine = engine
                self._attach_snapshots(engine)
                self._stamp_trace(engine)
                self.last_restart_mode = "warm"
                self.last_warm_fallback = None
                return "warm"
        self.start_tick = tick
        self._engine = self._fresh_engine()
        self.last_restart_mode = "cold"
        return "cold"

    # -- serving ----------------------------------------------------------

    def step(self) -> StepMetrics:
        """One engine step (raises `ReplicaDeadError` when dead)."""
        return self.engine.step()

    def note_step_error(self, exc: BaseException) -> None:
        """Record one typed step failure (supervisor error signal)."""
        self.step_error_streak += 1
        self.last_step_error = exc

    def note_step_ok(self) -> None:
        self.step_error_streak = 0

    def has_work(self) -> bool:
        return self._engine is not None \
            and self._engine.scheduler.has_work()

    def local_deadline(self, deadline_tick: int | None) -> int | None:
        """Front-end tick -> this engine's step space.  The handle
        steps its engine exactly once per front-end tick, so local
        step s corresponds to tick ``start_tick + s``."""
        if deadline_tick is None:
            return None
        return deadline_tick - self.start_tick

    # -- load probes ------------------------------------------------------

    def load(self) -> dict[str, Any]:
        """Host-side pressure snapshot (`ServingEngine.health`) plus
        identity; a dead replica reports infinite pressure so routing
        and shedding never pick it."""
        if self._engine is None:
            return {"replica_id": self.replica_id, "alive": False,
                    "waiting": 0, "running": 0, "page_utilization": 1.0,
                    "free_pages": 0, "used_pages": 0}
        h = self._engine.health()
        h["replica_id"] = self.replica_id
        h["alive"] = True
        return h

    def peek_prefix_pages(self, tokens) -> int:
        """Side-effect-free probe of this replica's prefix cache (0
        when dead): the router's affinity signal."""
        if self._engine is None:
            return 0
        return self._engine.allocator.peek_prefix(tokens)

    def queue_len(self) -> int:
        if self._engine is None:
            return 0
        return (len(self._engine.scheduler.waiting)
                + len(self._engine.scheduler.running))
