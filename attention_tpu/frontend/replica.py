"""One engine replica behind a kill/restart-able handle.

The front end never touches a `ServingEngine` directly: every access
goes through a :class:`ReplicaHandle`, which is the unit of failure —
the chaos harness kills a handle mid-storm and the front end must
recover from its OWN bookkeeping (streamed tokens, retry queue), never
from the dead engine's internals.  ``kill`` therefore drops the engine
reference entirely: any later touch raises the typed
`ReplicaDeadError`, so a resurrection bug reads as a typed error, not
as silently serving from a corpse.

``restart`` builds a fresh engine (cold caches — a restarted replica
re-earns its prefix cache) and records the tick it came back, which is
what keeps deadline translation exact: a replica's engine counts steps
from ITS OWN birth, so the handle converts front-end ticks to local
engine steps via ``start_tick``.
"""

from __future__ import annotations

from typing import Any, Callable

from attention_tpu.engine.engine import EngineConfig, ServingEngine
from attention_tpu.engine.errors import ReplicaDeadError
from attention_tpu.engine.metrics import StepMetrics
from attention_tpu.engine.request import Request


class ReplicaHandle:
    """One serving replica: engine + liveness + clock translation."""

    def __init__(self, replica_id: str, model, params,
                 config: EngineConfig, *, start_tick: int = 0,
                 on_token: Callable[[Request, int], None] | None = None,
                 on_finish: Callable[[Request], None] | None = None,
                 on_timeout: Callable[[Request], None] | None = None):
        self.replica_id = replica_id
        self.model = model
        self.params = params
        self.config = config
        self.start_tick = start_tick
        self.deaths = 0
        self._callbacks = (on_token, on_finish, on_timeout)
        self._engine: ServingEngine | None = self._fresh_engine()

    def _fresh_engine(self) -> ServingEngine:
        on_token, on_finish, on_timeout = self._callbacks
        return ServingEngine(self.model, self.params, self.config,
                             on_token=on_token, on_finish=on_finish,
                             on_timeout=on_timeout)

    # -- liveness ---------------------------------------------------------

    @property
    def alive(self) -> bool:
        return self._engine is not None

    @property
    def engine(self) -> ServingEngine:
        if self._engine is None:
            raise ReplicaDeadError(
                f"replica {self.replica_id} is dead "
                f"(death #{self.deaths})"
            )
        return self._engine

    def kill(self) -> None:
        """Simulated fail-stop: the engine (and every page, cache
        entry, and in-flight request it held) is gone.  Idempotent —
        killing a corpse changes nothing."""
        if self._engine is not None:
            self._engine = None
            self.deaths += 1

    def restart(self, *, tick: int) -> None:
        """Bring the replica back with a FRESH engine at ``tick``.
        Cold start: empty pool, empty prefix cache, step counter 0 —
        exactly what a real process restart gives you."""
        if self._engine is not None:
            raise ReplicaDeadError(
                f"replica {self.replica_id} is already alive; "
                "kill it before restarting"
            )
        self.start_tick = tick
        self._engine = self._fresh_engine()

    # -- serving ----------------------------------------------------------

    def step(self) -> StepMetrics:
        """One engine step (raises `ReplicaDeadError` when dead)."""
        return self.engine.step()

    def has_work(self) -> bool:
        return self._engine is not None \
            and self._engine.scheduler.has_work()

    def local_deadline(self, deadline_tick: int | None) -> int | None:
        """Front-end tick -> this engine's step space.  The handle
        steps its engine exactly once per front-end tick, so local
        step s corresponds to tick ``start_tick + s``."""
        if deadline_tick is None:
            return None
        return deadline_tick - self.start_tick

    # -- load probes ------------------------------------------------------

    def load(self) -> dict[str, Any]:
        """Host-side pressure snapshot (`ServingEngine.health`) plus
        identity; a dead replica reports infinite pressure so routing
        and shedding never pick it."""
        if self._engine is None:
            return {"replica_id": self.replica_id, "alive": False,
                    "waiting": 0, "running": 0, "page_utilization": 1.0,
                    "free_pages": 0, "used_pages": 0}
        h = self._engine.health()
        h["replica_id"] = self.replica_id
        h["alive"] = True
        return h

    def peek_prefix_pages(self, tokens) -> int:
        """Side-effect-free probe of this replica's prefix cache (0
        when dead): the router's affinity signal."""
        if self._engine is None:
            return 0
        return self._engine.allocator.peek_prefix(tokens)

    def queue_len(self) -> int:
        if self._engine is None:
            return 0
        return (len(self._engine.scheduler.waiting)
                + len(self._engine.scheduler.running))
