"""Live draining migration: move in-flight requests off a sick replica.

When the `ReplicaSupervisor` turns a replica SUSPECT the front end
does not wait for it to die — it *drains* it: every in-flight request
is serialized in the PR 9 per-request snapshot section format
(`engine.snapshot._request_to_dict` — the exact dict a crash snapshot
would have persisted), cancelled on the source engine, and re-admitted
on a HEALTHY replica through `resume_request`.  Because the resume
path feeds back every streamed token and rebuilds the RNG chain
arithmetically (one split per sampled token), a migrated stream is
token-identical to a fault-free run — migration costs a re-prefill,
never a token.

The cut is strict: the source-side cancel happens BEFORE the
destination admission, so at no point can two engines hold the same
live request (the no-double-serve invariant in `chaos.invariants`
checks the emitted-token attribution against the recorded cuts).
Requests with no HEALTHY destination are left in place — a DEGRADED
replica stops taking new admissions but keeps serving what migration
could not move, which beats shedding it.

Determinism: iteration is in engine-seq order, destination choice goes
through the front end's seeded router, and every record carries the
tick — same seed, same storm, same migration sequence.
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, Any

from attention_tpu import obs
from attention_tpu.obs import trace as _trace
from attention_tpu.engine.errors import DeadlineExceededError
from attention_tpu.engine.request import SamplingParams
from attention_tpu.engine.snapshot import _request_to_dict
from attention_tpu.frontend.replica import ReplicaHandle

if TYPE_CHECKING:  # pragma: no cover - import cycle guard
    from attention_tpu.frontend.frontend import ServingFrontend

_MIGRATED = obs.counter("frontend.migrate.moved",
                        "requests drained off a SUSPECT replica")
_TOKENS = obs.counter("frontend.migrate.tokens_preserved",
                      "already-streamed tokens carried across a cut")
_STRANDED = obs.counter("frontend.migrate.stranded",
                        "drain candidates with no HEALTHY destination")


@dataclasses.dataclass(frozen=True)
class MigrationRecord:
    """One drain decision (kept on the front end for the chaos
    checkers; ``record`` is the serialized PR 9 request section)."""

    tick: int
    request_id: str
    source: str
    dest: str | None          # None = stranded (left on the source)
    tokens_at_cut: int        # streamed tokens at the moment of the cut
    record: dict[str, Any]


def drain_replica(frontend: "ServingFrontend", handle: ReplicaHandle,
                  *, tick: int,
                  eligible: set[str]) -> list[MigrationRecord]:
    """Drain every front-end-owned in-flight request off ``handle``.

    ``eligible`` is the supervisor's HEALTHY set; the source is never
    a destination.  Returns one record per candidate, moved or not.
    """
    records: list[MigrationRecord] = []
    if not handle.alive:
        return records
    eng = handle.engine
    dest_ids = set(eligible) - {handle.replica_id}
    live = sorted(
        [("waiting", r) for r in eng.scheduler.waiting]
        + [("running", r) for r in eng.scheduler.running],
        key=lambda item: item[1].seq,
    )
    from attention_tpu.frontend.frontend import FrontendRequestState

    for queue, req in live:
        fr = frontend.requests.get(req.request_id)
        if (fr is None
                or fr.state is not FrontendRequestState.ASSIGNED
                or fr.replica_id != handle.replica_id):
            continue
        rec = _request_to_dict(req, queue)
        decision = frontend.router.route(
            fr.prompt, frontend.replicas, session=fr.session,
            exclude=handle.replica_id, eligible=dest_ids,
        ) if dest_ids else None
        if decision is None:
            _STRANDED.inc()
            frontend.note_migration_stranded(fr)
            records.append(MigrationRecord(
                tick=tick, request_id=fr.request_id,
                source=handle.replica_id, dest=None,
                tokens_at_cut=len(fr.tokens), record=rec))
            continue
        dest = decision.replica
        # THE CUT: source first, destination second — between the two
        # calls the request lives only in front-end bookkeeping, and
        # after them exactly one engine holds it
        eng.cancel(req.request_id)
        outs = [int(t) for t in rec["output_tokens"]]
        sampling = SamplingParams(**rec["sampling"])
        deadline_step = dest.local_deadline(fr.deadline)
        try:
            if outs:
                dest.engine.resume_request(
                    rec["prompt"], sampling,
                    request_id=fr.request_id, output_tokens=outs,
                    deadline_step=deadline_step,
                )
            else:
                dest.engine.add_request(
                    rec["prompt"], sampling,
                    request_id=fr.request_id,
                    deadline_step=deadline_step,
                )
        except DeadlineExceededError as e:
            # expired relative to the destination clock: the request
            # was already doomed; record the terminal truthfully
            frontend.note_migration_timeout(fr, e)
            records.append(MigrationRecord(
                tick=tick, request_id=fr.request_id,
                source=handle.replica_id, dest=None,
                tokens_at_cut=len(fr.tokens), record=rec))
            continue
        # the drained record carries the request's trace tail (the PR 9
        # snapshot section embeds it); adopting on the destination is
        # what makes a chain survive a cut across processes — in-process
        # it deduplicates to a no-op
        _trace.adopt(fr.request_id, rec.get("trace", []))
        frontend.note_migrated(fr, dest, tick)
        _MIGRATED.inc()
        if outs:
            _TOKENS.inc(len(outs))
        records.append(MigrationRecord(
            tick=tick, request_id=fr.request_id,
            source=handle.replica_id, dest=dest.replica_id,
            tokens_at_cut=len(fr.tokens), record=rec))
    return records
