"""Resilient multi-replica serving front end over `ServingEngine`.

The layer between clients and N engine replicas — the half of the
ROADMAP's "millions of users" item the single-replica engine cannot
provide: surviving a replica dying mid-decode.

    submit() ──> ServingFrontend.tick()
                   │  deadline sweep (TTL at admission + every tick)
                   │  admission control (shed / down-class on pressure)
                   │  Router: prefix-affine -> sticky -> least-loaded
                   │  retry-with-backoff (seeded, virtual-clock)
                   │  DegradationLadder (hysteretic, 4 levels)
                   ▼
            ReplicaHandle x N  (kill/restart-able; the chaos harness's
                   │            fail-stop unit)
                   ▼
            ServingEngine x N  (PR 2: continuous batching, paged KV,
                                prefix cache, preemption-by-recompute)

Modules: `replica` (the fail-stop unit), `routing` (cache-aware
placement), `backoff` (deterministic retry schedule), `degrade`
(shedding thresholds + ladder), `supervisor` (per-tick gray-failure
detection: HEALTHY -> SUSPECT -> DEGRADED -> DEAD with hysteresis),
`migrate` (live draining of in-flight requests off a SUSPECT replica,
token-identical by construction), `frontend` (the tick loop and the
terminal-state invariant).  Typed failures live in the ENGINE taxonomy
(`attention_tpu.engine.errors`) so one import site covers both layers.
"""

from attention_tpu.frontend.backoff import RetryPolicy  # noqa: F401
from attention_tpu.frontend.degrade import (  # noqa: F401
    LEVELS,
    NUM_PRIORITY_CLASSES,
    DegradationLadder,
    DegradePolicy,
    ShedPolicy,
    pool_pressure,
    replica_pressure,
)
from attention_tpu.frontend.frontend import (  # noqa: F401
    FRONTEND_TERMINAL,
    ForecastTracker,
    FrontendConfig,
    FrontendRequest,
    FrontendRequestState,
    ServingFrontend,
    replay_frontend,
)
from attention_tpu.obs.forecast import ForecastPolicy  # noqa: F401
from attention_tpu.frontend.migrate import (  # noqa: F401
    MigrationRecord,
    drain_replica,
)
from attention_tpu.frontend.replica import ReplicaHandle  # noqa: F401
from attention_tpu.frontend.routing import (  # noqa: F401
    RouteDecision,
    Router,
)
from attention_tpu.frontend.supervisor import (  # noqa: F401
    ReplicaSupervisor,
    SupervisorPolicy,
    SupervisorState,
    Verdict,
)
