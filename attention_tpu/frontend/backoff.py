"""Deterministic retry-with-backoff: seeded jitter on a virtual clock.

Production backoff is wall-clock and random; this front end's unit of
time is the *tick* (one scheduler round across every replica), and its
"randomness" is a counter-mode PRNG keyed by ``(seed, request_id,
attempt)`` — so the same seed replays the same retry schedule to the
tick, which is what lets a chaos storm assert byte-identical
`RunRecord` across runs.  Delays grow exponentially with the attempt
number, are capped, and carry multiplicative jitter in
``[1 - jitter, 1 + jitter]`` to de-synchronize retry herds without
sacrificing determinism.
"""

from __future__ import annotations

import dataclasses
import zlib

import numpy as np

from attention_tpu import obs

_DELAY_H = obs.histogram(
    "frontend.retry.delay_ticks",
    "granted backoff delays (exponential + seeded jitter)",
    buckets=(1, 2, 4, 8, 16, 32))


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retry budget + backoff shape for one front end.

    ``max_retries`` counts REQUEUES, not attempts: a request is first
    assigned for free, then may be requeued (replica death, admission
    OutOfPagesError, stalled admission) at most ``max_retries`` times
    before the budget is exhausted and it is shed with the typed
    `RequestShedError`."""

    max_retries: int = 3
    base_delay_ticks: int = 1
    multiplier: float = 2.0
    max_delay_ticks: int = 16
    jitter: float = 0.25      # +/- fraction of the exponential delay

    def validate(self) -> None:
        if self.max_retries < 0:
            raise ValueError(
                f"max_retries must be >= 0, got {self.max_retries}"
            )
        if self.base_delay_ticks < 1 or self.max_delay_ticks < 1:
            raise ValueError("backoff delays must be >= 1 tick")
        if self.multiplier < 1.0:
            raise ValueError(
                f"multiplier must be >= 1, got {self.multiplier}"
            )
        if not (0.0 <= self.jitter < 1.0):
            raise ValueError(
                f"jitter must be in [0, 1), got {self.jitter}"
            )

    def delay_ticks(self, seed: int, request_id: str,
                    attempt: int) -> int:
        """Virtual-clock delay before retry number ``attempt`` (1-based)
        of ``request_id``.  Pure function of its arguments: the jitter
        stream is seeded from (seed, crc32(request_id), attempt), so a
        replayed run backs off identically."""
        if attempt < 1:
            raise ValueError(f"attempt must be >= 1, got {attempt}")
        raw = self.base_delay_ticks * self.multiplier ** (attempt - 1)
        raw = min(float(self.max_delay_ticks), raw)
        if self.jitter:
            rng = np.random.default_rng(
                (seed & 0xFFFFFFFF,
                 zlib.crc32(request_id.encode()) & 0xFFFFFFFF,
                 attempt)
            )
            raw *= 1.0 + self.jitter * float(rng.uniform(-1.0, 1.0))
        delay = max(1, int(round(raw)))
        _DELAY_H.observe(delay)
        return delay
