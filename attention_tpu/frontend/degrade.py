"""Load shedding + the graceful-degradation ladder.

Two pressure responses with different time constants:

* **Shedding** is instantaneous admission control: each arriving
  request is judged against the pressure of the BEST alive replica
  (if even the least-loaded replica is saturated, queueing more work
  only grows tail latency).  Above ``downclass_pressure`` normal-
  priority arrivals are demoted one class; above ``shed_pressure``
  the lowest class is rejected outright with the typed
  `RequestShedError`.
* **The ladder** responds to *sustained* pressure with hysteresis:
  ``step_down_after`` consecutive high-pressure ticks drop one level,
  ``recover_after`` consecutive low-pressure ticks climb one back —
  and the high/low thresholds are separated so the ladder cannot
  flap on a boundary load.  Levels stack:

      0  normal       full token budget, prefix admission on
      1  lean_prefill replica token budgets scaled by
                      ``token_budget_factor`` (chunked prefill
                      throttles first — decode latency is protected)
      2  no_prefix    admission-path prefix-cache lookups off
                      (page churn drops; committed pages stay
                      resident for recovery)
      3  shed_low     lowest-priority arrivals shed regardless of
                      instantaneous pressure

Pressure is computed from the same host-side quantities the
``frontend.replica.*`` gauges export — but read directly off the
replica handles, never through the obs registry: telemetry is OFF by
default and control flow may not depend on it (the zero-overhead
contract).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

from attention_tpu.frontend.replica import ReplicaHandle

#: ladder level names, index == level
LEVELS = ("normal", "lean_prefill", "no_prefix", "shed_low")

#: priority classes: 0 = highest; class 2 is the sheddable tail
NUM_PRIORITY_CLASSES = 3


@dataclasses.dataclass(frozen=True)
class ShedPolicy:
    """Instantaneous admission-control thresholds."""

    queue_cap: int = 8              # queue depth that counts as "full"
    downclass_pressure: float = 0.75
    shed_pressure: float = 0.92

    def validate(self) -> None:
        if self.queue_cap < 1:
            raise ValueError(
                f"queue_cap must be >= 1, got {self.queue_cap}"
            )
        if not (0.0 < self.downclass_pressure
                <= self.shed_pressure <= 1.0):
            raise ValueError(
                "need 0 < downclass_pressure <= shed_pressure <= 1, "
                f"got {self.downclass_pressure}/{self.shed_pressure}"
            )


@dataclasses.dataclass(frozen=True)
class DegradePolicy:
    """Hysteretic ladder thresholds (see module docstring)."""

    pressure_high: float = 0.8      # sustained >= this steps down
    pressure_low: float = 0.4       # sustained <= this recovers
    step_down_after: int = 3        # consecutive high ticks
    recover_after: int = 5          # consecutive low ticks
    token_budget_factor: float = 0.5

    def validate(self) -> None:
        if not (0.0 <= self.pressure_low < self.pressure_high <= 1.0):
            raise ValueError(
                "need 0 <= pressure_low < pressure_high <= 1, got "
                f"{self.pressure_low}/{self.pressure_high}"
            )
        if self.step_down_after < 1 or self.recover_after < 1:
            raise ValueError("hysteresis windows must be >= 1 tick")
        if not (0.0 < self.token_budget_factor <= 1.0):
            raise ValueError(
                f"token_budget_factor must be in (0, 1], got "
                f"{self.token_budget_factor}"
            )


def replica_pressure(handle: ReplicaHandle, *, queue_cap: int) -> float:
    """One replica's pressure in [0, 1]: the max of its page
    occupancy and its normalized queue depth (a dead replica is 1.0)."""
    if not handle.alive:
        return 1.0
    load = handle.load()
    page = float(load["page_utilization"])
    queue = min(1.0, (load["waiting"] + load["running"]) / queue_cap)
    return max(page, queue)


def pool_pressure(replicas: Sequence[ReplicaHandle], *,
                  queue_cap: int) -> tuple[float, float]:
    """(best, mean) pressure over the replica set.  ``best`` (the
    least-loaded replica) drives shedding — new work can be routed
    there; ``mean`` drives the ladder — sustained fleet-wide load."""
    vals = [replica_pressure(r, queue_cap=queue_cap) for r in replicas]
    if not vals:
        return 1.0, 1.0
    return min(vals), sum(vals) / len(vals)


class DegradationLadder:
    """Level state machine with the two hysteresis counters."""

    def __init__(self, policy: DegradePolicy):
        policy.validate()
        self.policy = policy
        self.level = 0
        self._high_ticks = 0
        self._low_ticks = 0
        self.step_downs = 0
        self.recoveries = 0

    @property
    def level_name(self) -> str:
        return LEVELS[self.level]

    def observe(self, pressure: float) -> int:
        """Feed one tick's mean pressure; returns the (possibly
        changed) level.  Mid-band pressure resets both streaks — a
        level change requires CONSECUTIVE ticks beyond a threshold."""
        p = self.policy
        if pressure >= p.pressure_high:
            self._high_ticks += 1
            self._low_ticks = 0
        elif pressure <= p.pressure_low:
            self._low_ticks += 1
            self._high_ticks = 0
        else:
            self._high_ticks = 0
            self._low_ticks = 0
        if (self._high_ticks >= p.step_down_after
                and self.level < len(LEVELS) - 1):
            self.level += 1
            self.step_downs += 1
            self._high_ticks = 0
        elif self._low_ticks >= p.recover_after and self.level > 0:
            self.level -= 1
            self.recoveries += 1
            self._low_ticks = 0
        return self.level
