"""Engine invariant checkers: what must hold no matter what faults fly.

Each checker returns a list of human-readable violation strings (empty
= invariant holds) and ticks the shared ``chaos.invariant.violations``
counter, so a fault campaign's verdict is observable through the obs
registry like every other subsystem.

The four invariants the fault harness pins (ISSUE 4):

1. **Page/refcount conservation** — the `PagePool` free list and
   refcounts stay mutually consistent, and a drained engine holds
   pages ONLY through its prefix cache (each cached page at refcount
   exactly 1: the cache's own reference).
2. **Token parity** — requests a fault plan did not touch produce
   byte-identical token streams to a fault-free run of the same trace
   (faults are isolated: preemption storms and a neighbor's corrupted
   pages must not leak into anyone else's sampling).
3. **Termination** — the engine drains every trace within a step
   bound; no fault plan may wedge the step loop.
4. **Typed errors** — anything that does escape the step loop is one
   of the typed serving errors (`OutOfPagesError`,
   `PageAccountingError`, and the resilience trio
   `DeadlineExceededError` / `ReplicaDeadError` / `RequestShedError`),
   never a bare RuntimeError three layers down.

The multi-replica front end (ISSUE 6) adds two more:

5. **No request lost** — every request submitted to a
   `ServingFrontend` reaches exactly one of the four terminal states
   (FINISHED / CANCELLED / TIMED_OUT / SHED), finished streams are
   complete, and shed/timed-out requests carry their typed cause.
6. **Replica conservation** — page/refcount conservation (and, once
   drained, prefix-cache-only quiescence) holds on every SURVIVING
   replica of a storm; a neighbor's death may not corrupt anyone
   else's pool.

The durability layer (ISSUE 9) adds two more:

7. **Snapshot round trip** — ``restore(save(engine))`` is
   state-identical: the deterministic serialization fingerprint
   (`engine.snapshot.state_fingerprint`) of the restored engine equals
   the original's, so the restored engine's future outputs are
   byte-identical by construction.
8. **Warm-recovery parity** — a replica recovered warm (snapshot +
   journal replay) finishes every stream token-identical to the
   fault-free run; crash points (kill mid-snapshot, bit-flipped
   sections, torn journal tails) may cost warmth, never tokens.

The gray-failure layer (ISSUE 10) adds three more:

9.  **Migration token parity** — every stream live-migrated off a
    SUSPECT replica (and every stream finished on a promoted standby)
    is token-identical to the fault-free run; migration costs a
    re-prefill, never a token.
10. **No double serve** — after a migration cut, the SOURCE replica
    never emits another token for the moved request (unless a later
    legitimate re-admission hands it back).  Checked against the
    per-token emitter attribution the front end records.
11. **Supervisor consistency** — once a replica's verdict is
    SUSPECT/DEGRADED/DEAD, no NEW admission routes to it until a
    recovery or restart verdict.  Checked by replaying the front
    end's unified event log (append order = global order, so
    within-tick phase ordering is handled by construction).

The observability layer (ISSUE 12) adds one more:

12. **Trace completeness** — every submitted request owns exactly one
    well-formed `obs.trace` chain: it starts with ``submitted``, ends
    with exactly one terminal matching the front end's terminal state,
    retry attempts strictly increase, each migration hop lands on its
    recorded destination, and no chain exists for an unknown request.
    Fault campaigns run inside ``obs.trace.capture()`` so the chains
    exist even with telemetry disabled.

The forecasting layer (ISSUE 14) adds one more:

13. **Forecast determinism** — when the front end ran with forecasting
    enabled (campaigns do, see `default_frontend_config`), the
    observatory report is a pure function of the recorded samples:
    computing it twice yields byte-identical canonical JSON, every
    number in it is finite, and rebuilding it from its own embedded
    samples (`obs.capacity.rebuild_report`) reproduces it exactly —
    under kill, gray, and crash storms alike.

The global prefix tier (ISSUE 17) adds one more:

14. **Prefix import parity** — with a fleet prefix store attached
    (`frontend.prefix_store`), every FINISHED stream is
    token-identical to the fault-free no-store run, no matter which
    replica imported its prefix or how the store was poisoned: a
    corrupt record must surface as `PrefixStoreCorruptError` handling
    (count + discard + cold re-prefill), never as wrong tokens.  The
    store's own byte accounting must also balance.  A no-op on a
    storeless front end.

The incident layer (ISSUE 18) adds one more:

15. **Incident completeness** — the postmortem ledger balances: every
    fault a campaign ACTUALLY injected dumped exactly one incident
    bundle naming its kind and tick, every fault-cause bundle traces
    back to a real injection, every detector-cause bundle to a
    recorded anomaly firing, and no bundle carries an unknown cause.
    The campaign runners attach a throwaway ``incident_dir`` to every
    plan, so the audit runs storm after storm with telemetry off.

The disaggregation layer (ISSUE 19) adds one more:

16. **Actuation ledger** — every fleet pool-size change balances
    against the flight recorder: each `fleet.ledger.ActuationRecord`
    the front end executed maps to exactly one ``scale_up`` /
    ``scale_down`` ring event with the same tick, pool, replica, and
    recorded cause (a closed alphabet), and no pool flaps — opposite
    actuations on one pool are separated by at least the policy's
    cooldown window (chaos ``demote_storm`` forced demotions are
    exempt: the storm IS the flap).  A no-op on a front end that
    never actuated.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Iterable, Mapping

from attention_tpu import obs
from attention_tpu.engine.errors import (
    DeadlineExceededError,
    PrefixLeaseError,
    PrefixStoreCorruptError,
    ReplicaDeadError,
    ReplicaStateError,
    RequestShedError,
    SnapshotCorruptError,
    SnapshotError,
    StepInterruptedError,
)
from attention_tpu.ops.paged import OutOfPagesError, PageAccountingError

_VIOLATIONS = obs.counter("chaos.invariant.violations",
                          "invariant-checker violations, by invariant")

#: everything that may legitimately escape a serving step/tick loop
TYPED_ERRORS = (OutOfPagesError, PageAccountingError,
                DeadlineExceededError, ReplicaDeadError,
                RequestShedError, SnapshotError, SnapshotCorruptError,
                ReplicaStateError, StepInterruptedError,
                PrefixStoreCorruptError, PrefixLeaseError)


def _report(invariant: str, problems: list[str]) -> list[str]:
    for _ in problems:
        _VIOLATIONS.inc(invariant=invariant)
    return [f"{invariant}: {p}" for p in problems]


def pool_accounting_violations(pool) -> list[str]:
    """Free-list/refcount consistency of one `PagePool`: every page is
    either free (refcount 0, on the free list exactly once) or held
    (refcount > 0, not on the free list)."""
    problems = []
    free = pool._free
    refs = pool._refs
    if len(set(free)) != len(free):
        problems.append("free list holds duplicate page ids")
    free_set = set(free)
    for page, r in enumerate(refs):
        if r < 0:
            problems.append(f"page {page} refcount {r} < 0")
        if r == 0 and page not in free_set:
            problems.append(f"page {page} refcount 0 but not free")
        if r > 0 and page in free_set:
            problems.append(f"page {page} refcount {r} but on free list")
    if pool.free_pages + sum(1 for r in refs if r > 0) != pool.num_pages:
        problems.append(
            f"free {pool.free_pages} + held "
            f"{sum(1 for r in refs if r > 0)} != {pool.num_pages}"
        )
    return _report("page_conservation", problems)


def engine_quiescence_violations(engine) -> list[str]:
    """A drained engine (run() returned) must hold pages only through
    its prefix cache — one cache reference each, nothing leaked by a
    finished, preempted, or cancelled request."""
    problems = []
    if engine.scheduler.waiting:
        problems.append(
            f"{len(engine.scheduler.waiting)} request(s) still waiting")
    if engine.scheduler.running:
        problems.append(
            f"{len(engine.scheduler.running)} request(s) still running")
    alloc = engine.allocator
    cached = {e.page for e in alloc._prefix.values()}
    if len(cached) != len(alloc._prefix):
        problems.append("prefix cache entries share a physical page")
    for page in range(engine.pool.num_pages):
        r = engine.pool.refcount(page)
        if r == 0:
            continue
        if page not in cached:
            problems.append(f"page {page} held (refcount {r}) but not "
                            "in the prefix cache: leaked")
        elif r != 1:
            problems.append(f"cached page {page} refcount {r} != 1 "
                            "after drain")
    return _report("page_conservation", problems)


def token_parity_violations(
    baseline: Mapping[str, list[int]],
    observed: Mapping[str, list[int]],
    *,
    exclude: Iterable[str] = (),
) -> list[str]:
    """Uninjected requests must match the fault-free run exactly."""
    excluded = set(exclude)
    problems = []
    for rid, want in baseline.items():
        if rid in excluded:
            continue
        got = observed.get(rid)
        if got != want:
            problems.append(
                f"request {rid}: tokens diverged from the fault-free "
                f"run (got {got}, want {want})"
            )
    return _report("token_parity", problems)


def async_parity_violations(
    sync_outputs: Mapping[str, list[int]],
    async_outputs: Mapping[str, list[int]],
    *,
    exclude: Iterable[str] = (),
) -> list[str]:
    """The double-buffered step loop (`EngineConfig.async_steps`) must
    be token-identical to the synchronous loop on the same seed/trace —
    staging is pure pre-rendering, so ANY divergence means the overlap
    leaked into scheduling, sampling, or the page tables.  Checked in
    both directions: a request that exists in one run but not the other
    is a violation too."""
    excluded = set(exclude)
    problems = []
    for rid, want in sync_outputs.items():
        if rid in excluded:
            continue
        got = async_outputs.get(rid)
        if got != want:
            problems.append(
                f"request {rid}: async loop diverged from the sync "
                f"loop (got {got}, want {want})"
            )
    for rid in async_outputs:
        if rid not in sync_outputs and rid not in excluded:
            problems.append(
                f"request {rid}: emitted by the async loop only"
            )
    return _report("async_parity", problems)


def termination_violations(finished: bool, error: BaseException | None,
                           *, max_steps: int) -> list[str]:
    """The run must drain (or fail TYPED) within the step bound."""
    problems = []
    if not finished and error is None:
        problems.append(f"engine did not drain within {max_steps} steps")
    if isinstance(error, RuntimeError) and not isinstance(
            error, TYPED_ERRORS):
        # engine.run's max_steps guard surfaces as RuntimeError: a wedge
        problems.append(f"step loop wedged: {error}")
    return _report("termination", problems)


def typed_error_violations(error: BaseException | None) -> list[str]:
    """Anything surfacing out of the step loop must be a typed
    serving error (capacity/accounting or the resilience trio)."""
    if error is None or isinstance(error, TYPED_ERRORS):
        return []
    return _report(
        "typed_errors",
        [f"untyped {type(error).__name__} escaped the engine: {error}"],
    )


# ------------------------------------------------- front-end invariants


def no_request_lost_violations(frontend) -> list[str]:
    """ISSUE 6 headline: every request submitted to a
    `ServingFrontend` terminates in exactly one of FINISHED /
    CANCELLED / TIMED_OUT / SHED — no storm may drop a request on the
    floor or leave it limping in a non-terminal state after the run
    drains.  Terminal bookkeeping must be consistent: finished streams
    complete (max_tokens or stop token), shed and timed-out requests
    carry their typed cause."""
    from attention_tpu.frontend.frontend import FrontendRequestState

    problems = []
    for fr in sorted(frontend.requests.values(), key=lambda f: f.seq):
        if not fr.is_terminal:
            problems.append(
                f"request {fr.request_id} lost: non-terminal state "
                f"{fr.state.name} after drain"
            )
            continue
        if fr.state is FrontendRequestState.FINISHED:
            stopped = (fr.sampling.stop_token is not None
                       and fr.sampling.stop_token in fr.tokens)
            if len(fr.tokens) != fr.sampling.max_tokens and not stopped:
                problems.append(
                    f"request {fr.request_id} FINISHED with "
                    f"{len(fr.tokens)}/{fr.sampling.max_tokens} tokens "
                    "and no stop token"
                )
        elif fr.state is FrontendRequestState.SHED:
            if not isinstance(fr.error, RequestShedError):
                problems.append(
                    f"request {fr.request_id} SHED without a "
                    f"RequestShedError cause (got "
                    f"{type(fr.error).__name__})"
                )
        elif fr.state is FrontendRequestState.TIMED_OUT:
            if not isinstance(fr.error, DeadlineExceededError):
                problems.append(
                    f"request {fr.request_id} TIMED_OUT without a "
                    f"DeadlineExceededError cause (got "
                    f"{type(fr.error).__name__})"
                )
    for name, queue in (("pending", frontend._pending),
                        ("retry", frontend._retry)):
        if queue:
            problems.append(
                f"{len(queue)} request(s) stranded on the front-end "
                f"{name} queue after drain"
            )
    return _report("request_conservation", problems)


def replica_conservation_violations(frontend, *,
                                    drained: bool) -> list[str]:
    """Page/refcount conservation on every SURVIVING replica; after a
    drained run each must also be quiescent (pages held only by its
    prefix cache).  Dead replicas are exempt — their pools died with
    them; what matters is that a neighbor's death never corrupts a
    survivor's accounting."""
    problems: list[str] = []
    for handle in frontend.replicas:
        if not handle.alive:
            continue
        inner = pool_accounting_violations(handle.engine.pool)
        if drained:
            inner += engine_quiescence_violations(handle.engine)
        problems += [f"{handle.replica_id}: {p}" for p in inner]
    return problems


def migration_parity_violations(
    frontend,
    baseline: Mapping[str, list[int]],
) -> list[str]:
    """Invariant 9: live-migrated streams match the fault-free run.

    Every request the migration machinery actually MOVED (a
    `MigrationRecord` with a destination) that went on to FINISH must
    carry exactly the baseline's tokens — the cut preserved the
    streamed prefix and the RNG chain, so divergence means the resume
    path dropped or resampled something."""
    from attention_tpu.frontend.frontend import FrontendRequestState

    problems = []
    moved = sorted({m.request_id
                    for m in getattr(frontend, "migrations", [])
                    if m.dest is not None})
    for rid in moved:
        fr = frontend.requests.get(rid)
        if fr is None or fr.state is not FrontendRequestState.FINISHED:
            continue
        if list(fr.tokens) != list(baseline.get(rid, [])):
            problems.append(
                f"request {rid}: migrated stream {list(fr.tokens)} != "
                f"fault-free {list(baseline.get(rid, []))}"
            )
    return _report("migration_parity", problems)


def prefix_import_parity_violations(
    frontend,
    baseline: Mapping[str, list[int]],
) -> list[str]:
    """Invariant 14: the fleet prefix store never changes tokens.

    Every FINISHED stream of a store-enabled front end must be
    token-identical to the fault-free NO-STORE run of the same trace —
    whether its prefix was prefilled cold, imported from the store, or
    re-prefilled after a poisoned record was rejected.  Wrong tokens
    are never an acceptable corruption outcome; the only legal
    responses to a bad record are the typed `PrefixStoreCorruptError`
    handling path (count + discard + cold prefill) upstream of here.
    Also pins the store's own byte accounting (``total_bytes`` equals
    the sum of live entry sizes — an eviction storm must not leak
    phantom bytes into the budget).  A no-op when the front end runs
    storeless."""
    from attention_tpu.frontend.frontend import FrontendRequestState

    store = getattr(frontend, "prefix_store", None)
    if store is None:
        return []
    problems = []
    for fr in sorted(frontend.requests.values(), key=lambda f: f.seq):
        if fr.state is not FrontendRequestState.FINISHED:
            continue
        want = baseline.get(fr.request_id)
        if want is None:
            continue
        if list(fr.tokens) != list(want):
            problems.append(
                f"request {fr.request_id}: store-enabled stream "
                f"{list(fr.tokens)} != no-store fault-free "
                f"{list(want)}"
            )
    live_bytes = sum(e.nbytes for e in store._entries.values())
    if live_bytes != store.total_bytes:
        problems.append(
            f"store byte accounting drifted: entries hold "
            f"{live_bytes} bytes, budget ledger says "
            f"{store.total_bytes}"
        )
    for name, value in sorted(store.counts.items()):
        if value < 0:
            problems.append(f"store counter {name} negative: {value}")
    return _report("prefix_import_parity", problems)


def no_double_serve_violations(frontend) -> list[str]:
    """Invariant 10: after a migration cut the source replica never
    emits another token for the moved request.

    Evidence: ``FrontendRequest.emitters`` (which engine emitted each
    token, recorded at stream time) against the front end's
    `MigrationRecord`s and admission history.  A token from the source
    at an index >= the cut position is a double serve — the request
    lived on two engines at once — unless a LATER admit event
    legitimately handed the request back to the source (retry or
    warm-restore)."""
    problems = []
    admits: dict[str, list[tuple[int, str]]] = {}
    for ev in getattr(frontend, "events_log", []):
        if ev[0] == "admit":
            admits.setdefault(ev[2], []).append((ev[1], ev[3]))
    for m in getattr(frontend, "migrations", []):
        if m.dest is None:
            continue
        fr = frontend.requests.get(m.request_id)
        if fr is None:
            continue
        seq = admits.get(m.request_id, [])
        # locate the cut's own admission (at most one drain per
        # request per tick, so (tick, dest) pins it exactly); any
        # admit to the source AFTER it makes source tokens legal again
        cut_idx = next((i for i, (tk, rid) in enumerate(seq)
                        if tk == m.tick and rid == m.dest),
                       len(seq) - 1)
        if any(rid == m.source for _, rid in seq[cut_idx + 1:]):
            continue
        offenders = [i for i, rid in enumerate(fr.emitters)
                     if i >= m.tokens_at_cut and rid == m.source]
        if offenders:
            problems.append(
                f"request {m.request_id}: source {m.source} emitted "
                f"token(s) at index {offenders[:3]} after the cut at "
                f"{m.tokens_at_cut} (tick {m.tick})"
            )
    return _report("no_double_serve", problems)


def supervisor_consistency_violations(frontend) -> list[str]:
    """Invariant 11: no admission to a non-HEALTHY replica.

    Replays the front end's unified event log in append order —
    verdict events move a replica's supervisor state, admit events
    must only ever name a replica currently HEALTHY (the default for
    never-judged replicas).  Because the log is appended in the exact
    order actions happened, within-tick ordering (kills before phases,
    verdicts after admissions) needs no special cases."""
    problems = []
    state: dict[str, str] = {}
    for ev in getattr(frontend, "events_log", []):
        if ev[0] == "verdict":
            _, _, rid, _, new, _ = ev
            state[rid] = new
        elif ev[0] == "admit":
            _, tick, req_id, rid = ev
            if state.get(rid, "healthy") != "healthy":
                problems.append(
                    f"request {req_id} admitted to {rid} at tick "
                    f"{tick} while its verdict was {state[rid]}"
                )
    return _report("supervisor_consistency", problems)


def trace_completeness_violations(frontend) -> list[str]:
    """Invariant 12: one well-formed trace chain per submitted request.

    Reads the live `obs.trace` store (the campaign runner wraps the
    whole plan in ``trace.capture()``); an empty store means tracing
    was off for the run and there is nothing to judge."""
    from attention_tpu.obs import trace as _trace
    from attention_tpu.obs.naming import TRACE_TERMINAL_EVENTS

    chains = _trace.all_traces()
    if not chains:
        return []
    problems = []
    known = set(frontend.requests)
    for rid in sorted(set(chains) - known):
        problems.append(f"orphan chain for unknown request {rid}")
    for rid in sorted(known):
        fr = frontend.requests[rid]
        evs = chains.get(rid, [])
        if not evs:
            problems.append(f"request {rid}: no trace chain recorded")
            continue
        names = [e["event"] for e in evs]
        if names[0] != "submitted":
            problems.append(
                f"request {rid}: chain starts with {names[0]!r}, "
                "not 'submitted'")
        terms = [n for n in names if n in TRACE_TERMINAL_EVENTS]
        if fr.is_terminal:
            if len(terms) != 1:
                problems.append(
                    f"request {rid}: {len(terms)} terminal events "
                    f"{terms} (want exactly one)")
            elif names[-1] != terms[0]:
                problems.append(
                    f"request {rid}: terminal {terms[0]!r} is not the "
                    "last event")
            elif terms[0] != fr.state.value:
                problems.append(
                    f"request {rid}: trace terminal {terms[0]!r} != "
                    f"front-end state {fr.state.value!r}")
        elif terms:
            problems.append(
                f"request {rid}: live request carries terminal "
                f"{terms[0]!r}")
        attempts = [e.get("attempt") for e in evs
                    if e["event"] == "retried"]
        if (any(a is None for a in attempts)
                or any(b <= a for a, b in zip(attempts, attempts[1:]))):
            problems.append(
                f"request {rid}: retry attempts {attempts} not "
                "strictly increasing")
        # hop pairing: a retried hop leaves the replica, so the next
        # placement-class event must be a re-placement (or another
        # backoff round / a terminal) — never an engine-side event on
        # a replica the chain never re-entered; a migrated hop must
        # land exactly on its recorded destination
        placement = {"routed", "warm_adopted", "retried", "migrated"}
        for i, ev in enumerate(evs):
            if ev["event"] == "retried":
                nxt = names[i + 1:i + 2]
                if nxt and nxt[0] not in placement \
                        and nxt[0] not in TRACE_TERMINAL_EVENTS:
                    problems.append(
                        f"request {rid}: {nxt[0]!r} follows a retried "
                        "hop without a re-placement")
            elif ev["event"] == "migrated":
                if ev.get("replica") != ev.get("dest"):
                    problems.append(
                        f"request {rid}: migrated hop stamped on "
                        f"{ev.get('replica')!r}, dest was "
                        f"{ev.get('dest')!r}")
    return _report("trace_completeness", problems)


def forecast_determinism_violations(frontend) -> list[str]:
    """Invariant 13: the observatory report is reproducible.

    Three checks over the same front end: compute-twice byte parity,
    no non-finite numbers, and dump-and-rebuild byte parity (the
    ``cli obs forecast`` contract).  A front end constructed without a
    `ForecastPolicy` has nothing to judge."""
    import json

    if getattr(frontend, "forecast", None) is None:
        return []
    from attention_tpu.obs import capacity as _capacity

    problems: list[str] = []
    a = json.dumps(frontend.forecast_report(), sort_keys=True)
    b = json.dumps(frontend.forecast_report(), sort_keys=True)
    if a != b:
        problems.append(
            "forecast report not reproducible: two computations over "
            "the same samples differ")
    if "NaN" in a or "Infinity" in a:
        problems.append("forecast report contains non-finite numbers")
    rebuilt = _capacity.rebuild_report(json.loads(a))
    if json.dumps(rebuilt, sort_keys=True) != a:
        problems.append(
            "forecast report does not rebuild byte-identically from "
            "its own embedded samples")
    return _report("forecast_determinism", problems)


def incident_completeness_violations(frontend, injector) -> list[str]:
    """Invariant 15: the incident ledger balances.

    Reads the bundles the run dumped under the front end's
    ``incident_dir`` straight from disk (the postmortem contract is
    that the bundle alone suffices) and matches the fault-cause ones
    one-to-one against the injector's ``fired`` ledger; detector-cause
    bundles must each trace to a recorded anomaly firing.  A no-op on
    a front end constructed without a postmortem writer."""
    pm = getattr(frontend, "postmortem", None)
    if pm is None:
        return []
    from attention_tpu.obs import postmortem as _postmortem

    problems: list[str] = []
    fault_bundles: set[tuple[str, int]] = set()
    detector_bundles: list[tuple[str, str, int]] = []
    for bundle_dir in _postmortem.list_incidents(pm.out_dir):
        b = _postmortem.load_incident(bundle_dir)
        meta = b["meta"]
        cause = meta.get("cause")
        detail = meta.get("detail", {})
        if cause not in _postmortem.INCIDENT_CAUSES:
            problems.append(
                f"bundle {b['name']}: unknown cause {cause!r}")
        elif cause == "fault":
            fault_bundles.add(
                (str(detail.get("kind")), int(meta["tick"])))
        elif cause == "detector":
            detector_bundles.append(
                (b["name"], str(detail.get("detector")),
                 int(meta["tick"])))
    fired = {(kind, int(tick))
             for kind, tick in getattr(injector, "fired", [])}
    for kind, tick in sorted(fired - fault_bundles):
        problems.append(
            f"injected fault {kind!r} at tick {tick} left no "
            "incident bundle")
    for kind, tick in sorted(fault_bundles - fired):
        problems.append(
            f"bundle names fault {kind!r} at tick {tick} that was "
            "never injected")
    tracker = getattr(frontend, "anomaly", None)
    firings = ({(f["detector"], int(f["tick"]))
                for f in tracker.firings} if tracker is not None
               else set())
    for name, detector, tick in detector_bundles:
        if (detector, tick) not in firings:
            problems.append(
                f"bundle {name} names detector {detector!r} at tick "
                f"{tick} with no recorded firing")
    if pm.suppressed:
        problems.append(
            f"{pm.suppressed} incident(s) suppressed by the writer's "
            f"bundle limit ({pm.limit})")
    return _report("incident_completeness", problems)


def snapshot_roundtrip_violations(engine) -> list[str]:
    """Invariant 7: ``restore(save(engine))`` is state-identical.

    Saves the live engine to a throwaway file, restores it, and
    compares deterministic state fingerprints — equal fingerprints
    mean the restored engine's serialization (pools, page accounting,
    prefix index, request queues, RNG positions) is byte-identical,
    so its future outputs are too.  Any `SnapshotError` on a
    freshly-written snapshot is itself a violation.

    On a mesh engine (``mesh_shards`` > 1) the snapshot must also
    carry the per-shard layout: the manifest's ``shards`` count equal
    to the engine's, and one ``pools.<s>`` section per shard (each
    with its own CRC) — a single-blob pool section from a sharded
    engine would silently lose per-shard damage detection."""
    from attention_tpu.engine import snapshot as snap

    problems: list[str] = []
    tmpdir = tempfile.mkdtemp(prefix="atp_snap_inv_")
    try:
        path = os.path.join(tmpdir, "snap-00000000.atpsnap")
        snap.save(engine, path)
        info = snap.inspect(path)
        want_shards = getattr(engine.config, "mesh_shards", 0) or 1
        if info.get("shards") != want_shards:
            problems.append(
                f"manifest shards {info.get('shards')} != engine "
                f"mesh_shards {want_shards}"
            )
        pool_names = sorted(
            s["name"] for s in info.get("sections", [])
            if s["name"] == "pools" or s["name"].startswith("pools.")
        )
        want_names = sorted(snap._pool_section_names(want_shards))
        if pool_names != want_names:
            problems.append(
                f"pool sections {pool_names} != expected {want_names}"
            )
        clone = snap.restore(path, engine.model, engine.params)
        a = snap.state_fingerprint(engine)
        b = snap.state_fingerprint(clone)
        if a != b:
            problems.append(
                f"restore(save(engine)) fingerprint mismatch: "
                f"{a[:16]}... != {b[:16]}..."
            )
    except SnapshotError as e:
        problems.append(f"fresh snapshot failed validation: {e}")
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    return _report("snapshot_roundtrip", problems)


def warm_recovery_parity_violations(
    baseline: Mapping[str, list[int]],
    observed: Mapping[str, list[int]],
    finished: Iterable[str],
) -> list[str]:
    """Invariant 8: warm-recovered streams match the fault-free run.

    ``finished`` names the requests that reached FINISHED through the
    storm (kills, warm restarts, crash points included); each must
    carry exactly the fault-free baseline's token stream — warm
    recovery may change WHERE tokens are computed, never WHICH."""
    problems = []
    for rid in sorted(finished):
        if list(observed.get(rid, [])) != list(baseline.get(rid, [])):
            problems.append(
                f"request {rid}: recovered stream "
                f"{list(observed.get(rid, []))} != fault-free "
                f"{list(baseline.get(rid, []))}"
            )
    return _report("warm_recovery_parity", problems)


def actuation_ledger_violations(frontend) -> list[str]:
    """Invariant 16: the actuation ledger balances.

    Matches the front end's executed-resize ledger
    (`ServingFrontend.actuations`) one-to-one, in order, against the
    ``scale_up``/``scale_down`` records in the flight-recorder ring
    (same tick, pool, replica, cause), requires every cause to come
    from the closed `fleet.ledger.ACTUATION_CAUSES` alphabet, and
    checks the anti-flap guarantee: opposite actuations on one pool
    at least ``cooldown_ticks`` apart, chaos ``forced`` demotions
    exempt.  A no-op on a front end that never actuated (and on runs
    where the ring was not captured)."""
    from attention_tpu.obs import blackbox as _blackbox
    from attention_tpu.fleet.ledger import ACTUATION_CAUSES

    ledger = list(getattr(frontend, "actuations", None) or [])
    ring = [ev for ev in _blackbox.events()
            if ev["kind"] in ("scale_up", "scale_down")]
    if not ledger and not ring:
        return []
    problems: list[str] = []
    if len(ledger) != len(ring):
        problems.append(
            f"{len(ledger)} ledger actuation(s) vs {len(ring)} ring "
            f"scale event(s)")
    for rec, ev in zip(ledger, ring):
        got = (ev["kind"], ev["tick"], ev.get("pool"),
               ev.get("replica"), ev.get("cause"))
        want = (rec.kind, rec.tick, rec.pool, rec.replica_id,
                rec.cause)
        if got != want:
            problems.append(
                f"ledger {want} != ring {got}")
    for rec in ledger:
        if rec.cause not in ACTUATION_CAUSES:
            problems.append(
                f"actuation at tick {rec.tick} carries unknown cause "
                f"{rec.cause!r}")
        if rec.kind not in ("scale_up", "scale_down"):
            problems.append(
                f"actuation at tick {rec.tick} carries unknown kind "
                f"{rec.kind!r}")
    policy = getattr(frontend.config, "autoscaler", None)
    cooldown = policy.cooldown_ticks if policy is not None else 0
    last: dict[str, tuple[int, str]] = {}
    for rec in ledger:
        if rec.cause == "forced":
            continue
        prev = last.get(rec.pool)
        if (prev is not None and prev[1] != rec.kind
                and rec.tick - prev[0] < cooldown):
            problems.append(
                f"pool {rec.pool!r} flapped: {prev[1]} at tick "
                f"{prev[0]} then {rec.kind} at tick {rec.tick} "
                f"inside the {cooldown}-tick cooldown")
        last[rec.pool] = (rec.tick, rec.kind)
    return _report("actuation_ledger", problems)
