"""Engine invariant checkers: what must hold no matter what faults fly.

Each checker returns a list of human-readable violation strings (empty
= invariant holds) and ticks the shared ``chaos.invariant.violations``
counter, so a fault campaign's verdict is observable through the obs
registry like every other subsystem.

The four invariants the fault harness pins (ISSUE 4):

1. **Page/refcount conservation** — the `PagePool` free list and
   refcounts stay mutually consistent, and a drained engine holds
   pages ONLY through its prefix cache (each cached page at refcount
   exactly 1: the cache's own reference).
2. **Token parity** — requests a fault plan did not touch produce
   byte-identical token streams to a fault-free run of the same trace
   (faults are isolated: preemption storms and a neighbor's corrupted
   pages must not leak into anyone else's sampling).
3. **Termination** — the engine drains every trace within a step
   bound; no fault plan may wedge the step loop.
4. **Typed errors** — anything that does escape the step loop is one
   of the typed capacity/accounting errors (`OutOfPagesError`,
   `PageAccountingError`), never a bare RuntimeError three layers down.
"""

from __future__ import annotations

from typing import Iterable, Mapping

from attention_tpu import obs
from attention_tpu.ops.paged import OutOfPagesError, PageAccountingError

_VIOLATIONS = obs.counter("chaos.invariant.violations",
                          "invariant-checker violations, by invariant")


def _report(invariant: str, problems: list[str]) -> list[str]:
    for _ in problems:
        _VIOLATIONS.inc(invariant=invariant)
    return [f"{invariant}: {p}" for p in problems]


def pool_accounting_violations(pool) -> list[str]:
    """Free-list/refcount consistency of one `PagePool`: every page is
    either free (refcount 0, on the free list exactly once) or held
    (refcount > 0, not on the free list)."""
    problems = []
    free = pool._free
    refs = pool._refs
    if len(set(free)) != len(free):
        problems.append("free list holds duplicate page ids")
    free_set = set(free)
    for page, r in enumerate(refs):
        if r < 0:
            problems.append(f"page {page} refcount {r} < 0")
        if r == 0 and page not in free_set:
            problems.append(f"page {page} refcount 0 but not free")
        if r > 0 and page in free_set:
            problems.append(f"page {page} refcount {r} but on free list")
    if pool.free_pages + sum(1 for r in refs if r > 0) != pool.num_pages:
        problems.append(
            f"free {pool.free_pages} + held "
            f"{sum(1 for r in refs if r > 0)} != {pool.num_pages}"
        )
    return _report("page_conservation", problems)


def engine_quiescence_violations(engine) -> list[str]:
    """A drained engine (run() returned) must hold pages only through
    its prefix cache — one cache reference each, nothing leaked by a
    finished, preempted, or cancelled request."""
    problems = []
    if engine.scheduler.waiting:
        problems.append(
            f"{len(engine.scheduler.waiting)} request(s) still waiting")
    if engine.scheduler.running:
        problems.append(
            f"{len(engine.scheduler.running)} request(s) still running")
    alloc = engine.allocator
    cached = {e.page for e in alloc._prefix.values()}
    if len(cached) != len(alloc._prefix):
        problems.append("prefix cache entries share a physical page")
    for page in range(engine.pool.num_pages):
        r = engine.pool.refcount(page)
        if r == 0:
            continue
        if page not in cached:
            problems.append(f"page {page} held (refcount {r}) but not "
                            "in the prefix cache: leaked")
        elif r != 1:
            problems.append(f"cached page {page} refcount {r} != 1 "
                            "after drain")
    return _report("page_conservation", problems)


def token_parity_violations(
    baseline: Mapping[str, list[int]],
    observed: Mapping[str, list[int]],
    *,
    exclude: Iterable[str] = (),
) -> list[str]:
    """Uninjected requests must match the fault-free run exactly."""
    excluded = set(exclude)
    problems = []
    for rid, want in baseline.items():
        if rid in excluded:
            continue
        got = observed.get(rid)
        if got != want:
            problems.append(
                f"request {rid}: tokens diverged from the fault-free "
                f"run (got {got}, want {want})"
            )
    return _report("token_parity", problems)


def termination_violations(finished: bool, error: BaseException | None,
                           *, max_steps: int) -> list[str]:
    """The run must drain (or fail TYPED) within the step bound."""
    problems = []
    if not finished and error is None:
        problems.append(f"engine did not drain within {max_steps} steps")
    if isinstance(error, RuntimeError) and not isinstance(
            error, OutOfPagesError):
        # engine.run's max_steps guard surfaces as RuntimeError: a wedge
        problems.append(f"step loop wedged: {error}")
    return _report("termination", problems)


def typed_error_violations(error: BaseException | None) -> list[str]:
    """Anything surfacing out of the step loop must be a typed
    capacity/accounting error."""
    if error is None or isinstance(error, (OutOfPagesError,
                                           PageAccountingError)):
        return []
    return _report(
        "typed_errors",
        [f"untyped {type(error).__name__} escaped the engine: {error}"],
    )
