"""Differential config fuzzer: sampled kernel configs vs the fp64 oracle.

The reference's correctness story is one frozen verifier over
hand-picked testcases (`attention.c:123-162`).  This module makes that
verifier a STANDING machine: every sampled :class:`FuzzConfig` builds
seeded inputs, runs the real kernel path (flash forward, dense/paged
decode, int8/int4 quantized decode — window, sinks, softcap, GQA and
ragged lengths included), computes the exact fp64 answer with the same
masking, and checks the full-scan error statistics against the
tolerance ledger (`chaos.budgets`).

Everything is deterministic from the campaign seed: same seed → same
configs → same inputs → same ledger rows.  A failing case carries its
config (the repro) for `chaos.shrink` to minimize.

The ``defect`` hook perturbs the kernel output before comparison; it
exists so the whole fuzz→shrink→replay pipeline can be exercised (and
tested) against a known synthetic failure without waiting for a real
kernel bug.  The same perturbation is registered as the ``chaos-broken``
backend in `attention_tpu.api`, so a shrunk ``.bin`` repro replays to
the same Wrong! verdict through the frozen ``cli run`` harness.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import numpy as np

from attention_tpu import obs
from attention_tpu.chaos.budgets import tolerance_for
from attention_tpu.chaos.configs import (
    FAMILIES,
    PAGE_SIZE,
    FuzzConfig,
    sample_campaign,
)
from attention_tpu.core.testcase import verify_scan

_CASES = obs.counter("chaos.fuzz.cases",
                     "fuzz cases executed, by family/result")

#: synthetic-defect amplitude: above every ledger budget (max 0.35)
DEFECT_AMPLITUDE = 0.5


def synthetic_defect(out: np.ndarray) -> np.ndarray:
    """The injected failure: one element pushed past every budget.
    Deterministic and shape-independent, so it survives shrinking all
    the way down to the plain single-head ``.bin`` subset."""
    out = np.array(out, dtype=np.float64, copy=True)
    out.flat[0] += DEFECT_AMPLITUDE
    return out


# --------------------------------------------------------------- oracle


def _round_to(x: np.ndarray, dtype: str) -> np.ndarray:
    """Input rounding is part of the INPUT, not kernel error: the
    oracle must see the same bf16-rounded values the kernel reads."""
    if dtype == "bfloat16":
        import jax.numpy as jnp

        # bf16 -> f32 is exact; the f64 hop happens in NumPy (x64 may
        # be disabled in jax)
        return np.asarray(
            jnp.asarray(x).astype(jnp.bfloat16).astype(jnp.float32)
        ).astype(np.float64)
    return x.astype(np.float64)


def oracle_masked(
    q: np.ndarray,  # (hq, m, d) float64
    k: np.ndarray,  # (hkv, n, d) float64
    v: np.ndarray,  # (hkv, n, dv) float64
    *,
    causal: bool = False,
    window: int | None = None,
    sinks: int | None = None,
    softcap: float | None = None,
    q_positions: np.ndarray | None = None,
    n_valid: np.ndarray | int | None = None,
) -> np.ndarray:
    """fp64 attention with the kernels' full masking surface.

    ``q_positions`` gives each query row's sequence position (default
    ``arange(m)``, the aligned self-attention case); ``n_valid`` caps
    the attendable KV prefix.  The window band for a query at position
    p keeps columns ``[p - window + 1, p]`` plus the first ``sinks``
    columns — exactly `flash_attention`/`flash_decode` semantics.
    """
    hq, m, d = q.shape
    hkv, n, _ = k.shape
    group = hq // hkv
    kx = np.repeat(k, group, axis=0)
    vx = np.repeat(v, group, axis=0)
    scores = np.einsum("hmd,hnd->hmn", q, kx) / np.sqrt(float(d))
    if softcap is not None:
        scores = softcap * np.tanh(scores / softcap)
    pos = (np.arange(m) if q_positions is None
           else np.asarray(q_positions))[None, :, None]
    col = np.arange(n)[None, None, :]
    mask = np.ones((1, m, n), dtype=bool)
    if n_valid is not None:
        mask &= col < np.asarray(n_valid).reshape(1, -1, 1)
    if causal:
        mask &= col <= pos
    if window is not None:
        in_band = col >= pos - (window - 1)
        if sinks:
            in_band |= col < sinks
        mask &= in_band
    scores = np.where(mask, scores, -np.inf)
    scores -= scores.max(axis=-1, keepdims=True)
    p = np.exp(scores)
    p /= p.sum(axis=-1, keepdims=True)
    return np.einsum("hmn,hnd->hmd", p, vx)


# ---------------------------------------------------------- case runner


@dataclasses.dataclass
class CaseResult:
    config: FuzzConfig
    ok: bool
    tolerance: float
    max_abs_err: float
    mismatches: int
    total: int
    message: str

    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["config"] = dataclasses.asdict(self.config)
        return d


def _case_inputs(config: FuzzConfig):
    """Seeded unit-normal inputs for one config (fp64 masters)."""
    rng = np.random.default_rng(config.seed)
    hq, hkv, d = config.heads, config.kv_heads, config.head_dim
    if config.family == "flash":
        q = rng.standard_normal((hq, config.m, d))
        k = rng.standard_normal((hkv, config.n, d))
        v = rng.standard_normal((hkv, config.n, d))
        return q, k, v, None
    if config.family == "ragged":
        # one packed mixed step: request 0 decodes a single token, the
        # rest prefill chunks.  ``lengths`` is (b, 2) int32 rows of
        # (kv_pre, q_len); the appended K/V rows for request bi are the
        # dense rows k[bi, :, kv_pre:kv_pre+q_len] — so the post-append
        # ground truth is just the dense prefix of length kv_pre+q_len
        b, n = config.m, config.n
        lo = 1 + (config.sinks or 0)
        q_lens = np.ones((b,), np.int64)
        if b > 1:
            q_lens[1:] = rng.integers(2, 17, size=b - 1)
        kv_pre = rng.integers(lo, n - 16, size=b)
        q = rng.standard_normal((hq, int(q_lens.sum()), d))
        k = rng.standard_normal((b, hkv, n, d))
        v = rng.standard_normal((b, hkv, n, d))
        lengths = np.stack([kv_pre, q_lens], axis=1).astype(np.int32)
        return q, k, v, lengths
    b, n = config.m, config.n
    q = rng.standard_normal((b, hq, d))
    k = rng.standard_normal((b, hkv, n, d))
    v = rng.standard_normal((b, hkv, n, d))
    lo = 8 + (config.sinks or 0)
    if config.ragged:
        lengths = rng.integers(lo, n + 1, size=b)
    else:
        lengths = np.full((b,), n)
    return q, k, v, lengths.astype(np.int32)


def _decode_oracle(config: FuzzConfig, q, k, v, lengths) -> np.ndarray:
    """Per-sequence fp64 decode reference: each query sits at position
    ``len - 1`` of its own sequence."""
    b, hq, d = q.shape
    out = np.empty((b, hq, v.shape[-1]))
    for bi in range(b):
        ln = int(lengths[bi])
        out[bi] = oracle_masked(
            q[bi][:, None, :], k[bi, :, :ln], v[bi, :, :ln],
            window=config.window, sinks=config.sinks,
            softcap=config.softcap,
            q_positions=np.asarray([ln - 1]),
        )[:, 0]
    return out


def _ragged_oracle(config: FuzzConfig, q, k, v, lengths) -> np.ndarray:
    """Per-request fp64 reference for the packed single-launch family:
    request ``bi``'s span queries sit at absolute positions
    ``kv_pre .. kv_pre+q_len-1`` of its own (history + chunk)
    sequence."""
    hq = config.heads
    total = int(lengths[:, 1].sum())
    out = np.empty((hq, total, v.shape[-1]))
    off = 0
    for bi in range(config.m):
        kv_pre, q_len = int(lengths[bi, 0]), int(lengths[bi, 1])
        ln = kv_pre + q_len
        out[:, off:off + q_len] = oracle_masked(
            q[:, off:off + q_len], k[bi, :, :ln], v[bi, :, :ln],
            causal=True, window=config.window, sinks=config.sinks,
            softcap=config.softcap,
            q_positions=np.arange(kv_pre, ln),
        )
        off += q_len
    return out


def _run_ragged(config: FuzzConfig, q, k, v, lengths, dt) -> np.ndarray:
    """Build the packed step (pools via `paged_from_dense`, appended
    rows = the dense tail of each request) and run the single-launch
    kernel; returns the real-token slice of the packed output."""
    import jax.numpy as jnp

    from attention_tpu.ops.paged import PagePool, paged_from_dense
    from attention_tpu.ops.ragged_paged import (
        RaggedPagedStep,
        packed_bucket,
        ragged_paged_append,
        ragged_paged_attention,
        tile_tokens,
    )

    b = config.m
    kv_pre, q_lens = lengths[:, 0], lengths[:, 1]
    num_pages = b * (config.n // PAGE_SIZE)
    pool = PagePool(num_pages)
    base = paged_from_dense(jnp.asarray(k, dt), jnp.asarray(v, dt),
                            jnp.asarray(kv_pre, jnp.int32), pool,
                            num_pages=num_pages, page_size=PAGE_SIZE,
                            # full-capacity table rows: the appended
                            # chunk may cross into the next page
                            total_pages_per_seq=config.n // PAGE_SIZE)
    group = config.heads // config.kv_heads
    total = int(q_lens.sum())
    q_tile = tile_tokens(packed_bucket(int(q_lens.max()), minimum=1),
                         group)
    width = packed_bucket(max(total, q_tile))
    cu = np.zeros((b + 1,), np.int32)
    cu[1:] = np.cumsum(q_lens)
    tok_pos = np.zeros((width,), np.int32)
    tok_slot = np.full((width,), -1, np.int32)
    qp = np.zeros((1, config.heads, width, config.head_dim))
    kn = np.zeros((1, config.kv_heads, width, config.head_dim))
    vn = np.zeros((1, config.kv_heads, width, config.head_dim))
    qp[0, :, :total] = q
    for bi in range(b):
        o, e = int(cu[bi]), int(cu[bi + 1])
        tok_pos[o:e] = np.arange(kv_pre[bi], kv_pre[bi] + q_lens[bi])
        tok_slot[o:e] = bi
        kn[0, :, o:e] = k[bi, :, kv_pre[bi]:kv_pre[bi] + q_lens[bi]]
        vn[0, :, o:e] = v[bi, :, kv_pre[bi]:kv_pre[bi] + q_lens[bi]]
    cache = RaggedPagedStep(
        base.k_pool, base.v_pool,
        jnp.asarray(base.page_table, jnp.int32),
        jnp.asarray(kv_pre, jnp.int32), jnp.asarray(cu),
        jnp.asarray([1, b], jnp.int32), jnp.asarray(tok_pos),
        jnp.asarray(tok_slot), np.zeros((q_tile,), np.int32),
    )
    cache = ragged_paged_append(cache, jnp.asarray(kn, dt),
                                jnp.asarray(vn, dt))
    out = ragged_paged_attention(
        jnp.asarray(qp, dt), cache, softcap=config.softcap,
        window=config.window, sinks=config.sinks,
        max_mode=config.max_mode,
    )
    return np.asarray(out, np.float64)[0, :, :total]


def _run_kernel(config: FuzzConfig, q, k, v, lengths) -> np.ndarray:
    """Lower one config onto the real kernel path it names."""
    import jax.numpy as jnp

    dt = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    kw: dict[str, Any] = dict(softcap=config.softcap,
                              window=config.window, sinks=config.sinks)
    if config.family == "flash":
        from attention_tpu.ops.flash import flash_attention

        out = flash_attention(
            jnp.asarray(q, dt), jnp.asarray(k, dt), jnp.asarray(v, dt),
            causal=config.causal, max_mode=config.max_mode, **kw,
        )
        return np.asarray(out, np.float64)

    if config.family == "ragged":
        return _run_ragged(config, q, k, v, lengths, dt)

    lens = jnp.asarray(lengths, jnp.int32)
    if config.family == "decode":
        from attention_tpu.ops.decode import flash_decode

        out = flash_decode(jnp.asarray(q, dt), jnp.asarray(k, dt),
                           jnp.asarray(v, dt), lens,
                           max_mode=config.max_mode, **kw)
    elif config.family == "paged":
        from attention_tpu.ops.paged import PagePool, paged_from_dense, \
            paged_flash_decode

        num_pages = config.m * (config.n // PAGE_SIZE)
        pool = PagePool(num_pages)
        cache = paged_from_dense(jnp.asarray(k, dt), jnp.asarray(v, dt),
                                 lens, pool, num_pages=num_pages,
                                 page_size=PAGE_SIZE)
        out = paged_flash_decode(jnp.asarray(q, dt), cache, **kw)
    elif config.family == "int8":
        from attention_tpu.ops.quant import flash_decode_quantized, \
            quantize_kv

        cache = quantize_kv(jnp.asarray(k, jnp.float32),
                            jnp.asarray(v, jnp.float32))
        out = flash_decode_quantized(jnp.asarray(q, jnp.float32), cache,
                                     lens, **kw)
    elif config.family == "int4":
        from attention_tpu.ops.quant import flash_decode_int4, \
            quantize_kv_int4

        cache = quantize_kv_int4(jnp.asarray(k, jnp.float32),
                                 jnp.asarray(v, jnp.float32))
        out = flash_decode_int4(jnp.asarray(q, jnp.float32), cache,
                                lens, **kw)
    else:
        raise ValueError(f"unknown family {config.family!r}")
    return np.asarray(out, np.float64)


def run_case(config: FuzzConfig, *,
             defect: Callable[[np.ndarray], np.ndarray] | None = None
             ) -> CaseResult:
    """Run one config against the oracle and the tolerance ledger."""
    config.validate()
    q, k, v, lengths = _case_inputs(config)
    # the kernel reads rounded inputs; so must the oracle
    qr = _round_to(q, config.dtype)
    kr = _round_to(k, config.dtype)
    vr = _round_to(v, config.dtype)
    got = _run_kernel(config, q, k, v, lengths)
    if defect is not None:
        got = defect(got)
    if config.family == "flash":
        want = oracle_masked(qr, kr, vr, causal=config.causal,
                             window=config.window, sinks=config.sinks,
                             softcap=config.softcap)
        min_band = None
    elif config.family == "ragged":
        want = _ragged_oracle(config, qr, kr, vr, lengths)
        min_band = int(np.min(lengths[:, 0] + lengths[:, 1]))
    else:
        want = _decode_oracle(config, qr, kr, vr, lengths)
        min_band = int(np.min(lengths))
    tol = tolerance_for(config.family, window=config.window,
                        min_band=min_band, max_mode=config.max_mode)
    stats = verify_scan(want, got, threshold=tol)
    result = CaseResult(
        config=config, ok=stats.ok, tolerance=tol,
        max_abs_err=stats.max_abs_err, mismatches=stats.mismatches,
        total=stats.total, message=stats.message,
    )
    _CASES.inc(family=config.family,
               result="pass" if result.ok else "fail")
    return result


# ------------------------------------------------------------- campaign


@dataclasses.dataclass
class CampaignReport:
    seed: int
    results: list[CaseResult]

    @property
    def failures(self) -> list[CaseResult]:
        return [r for r in self.results if not r.ok]

    @property
    def ok(self) -> bool:
        return not self.failures

    def to_dict(self) -> dict[str, Any]:
        return {
            "seed": self.seed,
            "cases": len(self.results),
            "failures": len(self.failures),
            "results": [r.to_dict() for r in self.results],
        }


def run_campaign(seed: int, cases: int, *,
                 families: Sequence[str] = FAMILIES,
                 max_mode: str = "online",
                 defect: Callable[[np.ndarray], np.ndarray] | None = None,
                 log: Callable[[str], None] | None = None
                 ) -> CampaignReport:
    """Sample and run ``cases`` configs; fully deterministic in
    ``seed`` (the case list is fixed before any case runs).
    ``max_mode`` pins the rescaling-math variant on families that can
    lower it (the per-variant oracle campaigns)."""
    results = []
    for i, config in enumerate(sample_campaign(seed, cases,
                                               families=families,
                                               max_mode=max_mode)):
        r = run_case(config, defect=defect)
        if log is not None:
            log(f"case {i}: {config.family} "
                f"{'ok' if r.ok else 'FAIL'} "
                f"max_abs_err={r.max_abs_err:.2e} tol={r.tolerance:g}")
        results.append(r)
    return CampaignReport(seed=seed, results=results)
