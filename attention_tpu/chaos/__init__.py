"""Chaos: differential fuzzing + fault injection, as a subsystem.

The reference's correctness machinery is a frozen one-shot verifier
(`attention.c:123-162`, PARITY C17).  This package turns that contract
into a standing correctness-and-robustness machine with two arms:

* **Differential fuzzing** (`configs`/`fuzzer`/`budgets`/`shrink`) —
  seeded sampling of kernel family × shape × dtype × feature flags,
  each case run against the fp64 oracle and judged by the per-family
  tolerance ledger; failures shrink to a minimal repro and, when the
  minimal config is plain, to the reference's ``.bin`` testcase format
  that ``cli run`` (and the upstream C binary) replays.

* **Fault injection** (`faults`/`invariants`) — seeded fault plans
  (OOM windows, preemption storms, cancellations, NaN page payloads,
  watermark flapping) driven through the serving engine, with checkers
  for the four engine invariants: page/refcount conservation, token
  parity for uninjected requests, termination, typed errors.

CLI surface: ``python -m attention_tpu.cli chaos fuzz|replay|shrink|faults``.
Observable through `attention_tpu.obs` (``chaos.fuzz.cases``,
``chaos.faults.injected``, ``chaos.invariant.violations``).
"""

from attention_tpu.chaos.budgets import (  # noqa: F401
    CONTRACT_TOL,
    FAMILY_BUDGETS,
    tolerance_for,
)
from attention_tpu.chaos.configs import (  # noqa: F401
    FAMILIES,
    FuzzConfig,
    sample_campaign,
    sample_config,
)
from attention_tpu.chaos.faults import (  # noqa: F401
    FAULT_KINDS,
    FaultCampaignReport,
    FaultEvent,
    FaultInjector,
    FaultPlan,
    PlanReport,
    random_plan,
    run_plan,
)
from attention_tpu.chaos.faults import run_campaign as run_fault_campaign  # noqa: F401
from attention_tpu.chaos.fuzzer import (  # noqa: F401
    CampaignReport,
    CaseResult,
    DEFECT_AMPLITUDE,
    oracle_masked,
    run_case,
    synthetic_defect,
)
from attention_tpu.chaos.fuzzer import run_campaign as run_fuzz_campaign  # noqa: F401
from attention_tpu.chaos.shrink import (  # noqa: F401
    ShrinkResult,
    read_repro_json,
    shrink,
    write_repro_bin,
    write_repro_json,
)
