"""The tolerance ledger: per-family error budgets, encoded ONCE.

Every numeric claim the fuzzer enforces lives here.  The bf16/fp32
kernel families are held to the reference's frozen ±0.02 elementwise
contract (`attention.c:143` — `core.testcase.VERIFY_THRESHOLD`); the
quantized caches are held to their MEASURED budgets (tests/test_quant.py,
RESULTS.md round 5): int8 sits comfortably inside the contract, int4 is
an opt-in bytes/quality trade whose budget is ~4x the contract (and ~2x
again under a sliding window, where fewer softmax terms average less of
the nibble noise out).

PARITY.md's "Tolerance ledger" table is a human-readable mirror of
:data:`FAMILY_BUDGETS`; ``scripts/check_tolerances.py`` lints the two
against each other (the `check_shipped_table.py` discipline), so a
budget can never drift in only one place.
"""

from __future__ import annotations

from attention_tpu.core.testcase import VERIFY_THRESHOLD

#: the reference harness contract (attention.c:143)
CONTRACT_TOL = VERIFY_THRESHOLD  # 0.02

#: max-abs-error budget per fuzz family (unit-normal inputs, fp64
#: oracle).  Keys are fuzz family names plus the ``int4_short``
#: variant: int4's nibble noise averages out over the softmax band, so
#: the budget is conditioned on how many KV rows a query attends —
#: a sliding window or a short ragged prefix (< INT4_FULL_BAND rows)
#: gets the wider short-band budget.  Both int4 values are the chaos
#: fuzzer's own 40-seed worst-case measurement at d=64 (full band
#: ~0.20, 8-row band ~0.29, plus margin) — WIDER than test_quant's
#: few-seed typical figure of ~4-8e-2, which sits near the center of
#: the distribution, not its tail.
FAMILY_BUDGETS: dict[str, float] = {
    "flash": CONTRACT_TOL,   # fused Pallas forward (fp32/bf16)
    "decode": CONTRACT_TOL,  # dense-cache flash decode
    "paged": CONTRACT_TOL,   # page-table decode
    "ragged": CONTRACT_TOL,  # packed mixed decode/prefill launch
    "int8": CONTRACT_TOL,    # int8 KV cache: measured ~2e-3, held to
                             # the contract (it is contract-grade)
    "int4": 0.25,            # full-band worst case (~0.20 measured)
    "int4_short": 0.35,      # windowed / short-band (~0.29 measured)
    "flashd": CONTRACT_TOL,  # FLASH-D rescaling variant: same fp32
                             # softmax math reassociated (the division
                             # moves into the tile update), measured
                             # ~5e-7 fp32 / ~8e-3 bf16 vs online —
                             # held to the contract across every
                             # max_mode-threading family
    "amla": CONTRACT_TOL,    # AMLA rescaling variant: pow2 rescales
                             # are BIT-EXACT (exponent-field adds);
                             # only the quantized max shifts which
                             # exp2 rounding each term sees — measured
                             # at online's own error scale, held to
                             # the contract likewise
}

#: minimum attended-band width (KV rows) for int4's full-band budget
INT4_FULL_BAND = 64


def tolerance_for(family: str, *, window: int | None = None,
                  min_band: int | None = None,
                  max_mode: str | None = None) -> float:
    """The ledger's budget for one sampled config.

    ``min_band`` is the narrowest softmax band any query in the case
    attends (min over sequences of ``min(length, window)``); int4's
    budget widens below :data:`INT4_FULL_BAND` rows.  ``max_mode``
    names the rescaling-math variant the case lowers: the flashd/amla
    variants carry their OWN ledger rows (one budget per variant,
    whichever family threads it — the variant changes the in-kernel
    recurrence, not the family's masking), while online/bound keep the
    family's row (bound is bit-identical softmax by max-invariance).
    """
    if max_mode in ("flashd", "amla"):
        return FAMILY_BUDGETS[max_mode]
    if family == "int4" and (
        window is not None
        or (min_band is not None and min_band < INT4_FULL_BAND)
    ):
        return FAMILY_BUDGETS["int4_short"]
    try:
        return FAMILY_BUDGETS[family]
    except KeyError:
        raise ValueError(
            f"no tolerance budget for family {family!r}; known: "
            f"{sorted(FAMILY_BUDGETS)}"
        ) from None
