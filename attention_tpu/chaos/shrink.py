"""Shrinker: minimize a failing fuzz config, serialize the repro.

Greedy delta-debugging over :class:`FuzzConfig`: from a failing config,
repeatedly try simplifying moves (drop a feature flag, collapse GQA,
halve a dimension, drop bf16) and keep any move after which the case
STILL fails.  The fixpoint is the minimal repro — the config a human
debugs instead of the 5-flag monster the fuzzer happened to sample.

Serialization is two-tier, mirroring how much of the config the
reference's frozen harness can express:

* every minimal config round-trips as ``repro.json``
  (`cli chaos replay`);
* a config shrunk into the PLAIN subset (single-head flash, no flags —
  `FuzzConfig.is_plain`) additionally serializes to the reference's
  binary ``.bin`` testcase format via `core.testcase.write_testcase`,
  with the fp64 oracle output appended — so ``cli run`` and even the
  upstream C binaries replay the exact failing inputs under the frozen
  ±0.02 contract.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Iterator

import numpy as np

from attention_tpu.chaos.configs import PAGE_SIZE, FuzzConfig
from attention_tpu.chaos.fuzzer import CaseResult, _case_inputs, run_case
from attention_tpu.core.oracle import attention_oracle
from attention_tpu.core.testcase import TestCase, write_testcase

#: shape floors: small enough to read, large enough that every kernel
#: family still accepts the shape
_MIN_MN_FLASH = 16
_MIN_D = 8


def _replace(cfg: FuzzConfig, **kw) -> FuzzConfig:
    return dataclasses.replace(cfg, **kw)


def _moves(cfg: FuzzConfig) -> Iterator[FuzzConfig]:
    """Candidate simplifications, most-semantic first (drop flags before
    shrinking shapes, so the minimal repro is plain when possible)."""
    if cfg.sinks is not None:
        yield _replace(cfg, sinks=None)
    if cfg.window is not None:
        yield _replace(cfg, window=None, sinks=None)
    if cfg.softcap is not None:
        yield _replace(cfg, softcap=None)
    if cfg.causal:
        yield _replace(cfg, causal=False, window=None, sinks=None)
    if cfg.ragged:
        yield _replace(cfg, ragged=False)
    if (cfg.heads, cfg.kv_heads) != (1, 1):
        yield _replace(cfg, heads=1, kv_heads=1)
    if cfg.dtype != "float32":
        yield _replace(cfg, dtype="float32")
    if cfg.family == "flash":
        if cfg.m > _MIN_MN_FLASH:
            yield _replace(cfg, m=max(cfg.m // 2, _MIN_MN_FLASH))
        if cfg.n > _MIN_MN_FLASH:
            yield _replace(cfg, n=max(cfg.n // 2, _MIN_MN_FLASH))
    else:
        if cfg.m > 1:
            yield _replace(cfg, m=1)
        if cfg.n > PAGE_SIZE:
            yield _replace(cfg, n=max(cfg.n // 2, PAGE_SIZE))
    d_floor = max(_MIN_D, 2 if cfg.family == "int4" else 1)
    if cfg.head_dim > d_floor:
        yield _replace(cfg, head_dim=max(cfg.head_dim // 2, d_floor))


@dataclasses.dataclass
class ShrinkResult:
    original: FuzzConfig
    minimal: FuzzConfig
    final: CaseResult       # the minimal config's (still failing) run
    steps: int              # accepted moves
    attempts: int           # total candidate runs


def shrink(config: FuzzConfig, *,
           defect: Callable[[np.ndarray], np.ndarray] | None = None,
           max_attempts: int = 64,
           log: Callable[[str], None] | None = None) -> ShrinkResult:
    """Minimize ``config`` while it keeps failing its ledger budget.

    Raises ValueError if ``config`` does not fail to begin with (a
    shrinker that "minimizes" a passing case would manufacture repros
    out of thin air).
    """
    current = run_case(config, defect=defect)
    if current.ok:
        raise ValueError(
            f"config passes its budget (max_abs_err="
            f"{current.max_abs_err:.3g} <= {current.tolerance:g}); "
            "nothing to shrink"
        )
    steps = attempts = 0
    progress = True
    while progress and attempts < max_attempts:
        progress = False
        for cand in _moves(current.config):
            attempts += 1
            if attempts >= max_attempts:
                break
            try:
                cand.validate()
                r = run_case(cand, defect=defect)
            except Exception:  # noqa: BLE001 - an invalid candidate is
                continue       # just a rejected move, not a failure
            if not r.ok:
                if log is not None:
                    log(f"shrink: kept {cand.to_json()}")
                current = r
                steps += 1
                progress = True
                break  # restart the move list from the simplified config
    return ShrinkResult(original=config, minimal=current.config,
                        final=current, steps=steps, attempts=attempts)


# ---------------------------------------------------------- repro files


def write_repro_json(path: str | os.PathLike, config: FuzzConfig) -> None:
    with open(path, "w") as f:
        f.write(config.to_json())
        f.write("\n")


def read_repro_json(path: str | os.PathLike) -> FuzzConfig:
    with open(path) as f:
        return FuzzConfig.from_json(f.read())


def write_repro_bin(path: str | os.PathLike, config: FuzzConfig) -> None:
    """Serialize a PLAIN minimal config to the reference's frozen
    ``.bin`` format: the exact seeded inputs, with the fp64 oracle
    output appended — replayable by ``cli run`` (any backend) and the
    upstream C binaries under the ±0.02 contract."""
    if not config.is_plain:
        raise ValueError(
            "only plain configs (single-head flash, no flags) fit the "
            f"reference .bin harness; got {config.to_json()}"
        )
    q, k, v, _ = _case_inputs(config)
    q, k, v = q[0], k[0], v[0]  # single head: (m, d)/(n, d)
    expected = attention_oracle(q, k, v)
    write_testcase(path, TestCase(q=q, k=k, v=v, expected=expected))
