"""Fuzz configs: the sampled point in kernel-config space.

A :class:`FuzzConfig` names everything that selects a kernel code path:
family (which kernel), shape, dtype, and the feature flags (causal,
window, sinks, softcap, GQA grouping, ragged lengths).  The sampler
draws configs deterministically from a seed within TIER-1-SAFE bounds —
shapes small enough that the whole smoke campaign runs in interpret
mode on CPU in seconds, drawn from a coarse grid so cases share jit
signatures (each distinct static shape compiles once, then later cases
reuse it).

Configs are plain JSON-able dataclasses: a failing config round-trips
through ``repro.json`` (`cli chaos replay`) and, once the shrinker has
reduced it to the plain single-head subset, through the reference's
frozen ``.bin`` testcase format.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Sequence

import numpy as np

#: kernel families the fuzzer knows how to drive.  "ragged" is the
#: packed mixed decode/prefill single-launch kernel (ops.ragged_paged):
#: ``m`` requests share one token axis — request 0 decodes one token,
#: the rest prefill short chunks — against per-request page tables
FAMILIES = ("flash", "decode", "paged", "ragged", "int8", "int4")

#: the paged kernels' page granule (ops.paged)
PAGE_SIZE = 128

#: families that thread a ``max_mode`` rescaling-math variant to their
#: kernel, and the variants each can lower ("bound" is forward-only;
#: the quantized/paged decode kernels take no max_mode at all)
MAX_MODE_FAMILIES: dict[str, tuple[str, ...]] = {
    "flash": ("online", "bound", "flashd", "amla"),
    "decode": ("online", "flashd", "amla"),
    "ragged": ("online", "flashd", "amla"),
}


@dataclasses.dataclass(frozen=True)
class FuzzConfig:
    """One sampled kernel configuration.

    ``m`` is query rows for the flash family and batch size for the
    cache-decode families; ``n`` is KV rows / cache capacity.  ``seed``
    keys the input generator, so a config IS its repro.
    """

    family: str
    m: int
    n: int
    heads: int
    kv_heads: int
    head_dim: int
    dtype: str = "float32"          # "float32" | "bfloat16"
    causal: bool = False
    window: int | None = None
    sinks: int | None = None
    softcap: float | None = None
    ragged: bool = False            # decode families: varied lengths
    max_mode: str = "online"        # rescaling-math variant (ops kernels)
    seed: int = 0

    def validate(self) -> None:
        if self.family not in FAMILIES:
            raise ValueError(f"unknown family {self.family!r}")
        if self.max_mode != "online" and self.max_mode not in \
                MAX_MODE_FAMILIES.get(self.family, ()):
            raise ValueError(
                f"family {self.family!r} cannot lower max_mode "
                f"{self.max_mode!r}"
            )
        if self.heads % self.kv_heads:
            raise ValueError(
                f"heads {self.heads} not a multiple of kv_heads "
                f"{self.kv_heads}"
            )
        if self.sinks is not None and self.window is None:
            raise ValueError("sinks require window")
        if self.window is not None and self.family == "flash" \
                and not self.causal:
            raise ValueError("flash window requires causal")
        if self.family != "flash" and self.n % PAGE_SIZE:
            raise ValueError(
                f"cache capacity {self.n} must be a {PAGE_SIZE}-multiple"
            )
        if self.family == "int4" and self.head_dim % 2:
            raise ValueError("int4 packing needs an even head_dim")

    @property
    def is_plain(self) -> bool:
        """True iff the config is expressible in the reference's frozen
        ``.bin`` harness: single-head plain attention, no flags (the
        harness has no head dimension and verifies un-masked softmax)."""
        return (
            self.family == "flash"
            and self.heads == 1
            and self.kv_heads == 1
            and not self.causal
            and self.window is None
            and self.sinks is None
            and self.softcap is None
            and self.max_mode == "online"
        )

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "FuzzConfig":
        data = json.loads(text)
        cfg = cls(**{k: data[k] for k in data
                     if k in {f.name for f in dataclasses.fields(cls)}})
        cfg.validate()
        return cfg


def _choice(rng: np.random.Generator, seq: Sequence[Any]) -> Any:
    return seq[int(rng.integers(len(seq)))]


# Tier-1-safe sampling grids.  Deliberately COARSE: the point of the
# fuzzer is flag/shape-combination coverage, not shape diversity — a
# small grid keeps the jit-signature count (and the CPU interpret-mode
# compile bill) bounded while still crossing every feature pair over a
# campaign.
_HEAD_GRID = ((1, 1), (2, 1), (4, 2))
_FLASH_MN = (64, 128)
_FLASH_D = (16, 32)
_CACHE_N = (128, 256)
_CACHE_D = (16, 32)
_QUANT_D = (32, 64)
_INT4_D = (64,)
_SOFTCAP = (None, 15.0)
_DTYPES = ("float32", "bfloat16")


def sample_config(rng: np.random.Generator, *,
                  families: Sequence[str] = FAMILIES,
                  max_mode: str = "online") -> FuzzConfig:
    """Draw one config.  Consumes a deterministic number of rng draws
    per family, so a campaign is reproducible from its seed alone.
    ``max_mode`` pins the rescaling-math variant for families that can
    lower it (the per-variant oracle campaigns); families that cannot
    keep the online default — the draw sequence is unchanged either
    way, so the same seed samples the same shapes per variant."""
    family = _choice(rng, list(families))
    heads, kv_heads = _choice(rng, _HEAD_GRID)
    softcap = _choice(rng, _SOFTCAP)
    seed = int(rng.integers(2**31 - 1))
    mm = (max_mode if max_mode in MAX_MODE_FAMILIES.get(family, ())
          else "online")

    if family == "flash":
        m = n = _choice(rng, _FLASH_MN)
        d = _choice(rng, _FLASH_D)
        dtype = _choice(rng, _DTYPES)
        causal = bool(rng.integers(2))
        window = _choice(rng, (None, 16, 48)) if causal else None
        sinks = _choice(rng, (None, 4)) if window is not None else None
        return FuzzConfig(family=family, m=m, n=n, heads=heads,
                          kv_heads=kv_heads, head_dim=d, dtype=dtype,
                          causal=causal, window=window, sinks=sinks,
                          softcap=softcap, max_mode=mm, seed=seed)

    batch = int(rng.integers(1, 3))
    n = _choice(rng, _CACHE_N)
    if family in ("int8", "int4"):
        d = _choice(rng, _INT4_D if family == "int4" else _QUANT_D)
        dtype = "float32"  # the quantizers define the cache layout
    else:
        d = _choice(rng, _CACHE_D)
        dtype = _choice(rng, _DTYPES)
    window = _choice(rng, (None, 24))
    sinks = _choice(rng, (None, 4)) if window is not None else None
    ragged = bool(rng.integers(2))
    return FuzzConfig(family=family, m=batch, n=n, heads=heads,
                      kv_heads=kv_heads, head_dim=d, dtype=dtype,
                      window=window, sinks=sinks, softcap=softcap,
                      ragged=ragged, max_mode=mm, seed=seed)


def sample_campaign(seed: int, cases: int, *,
                    families: Sequence[str] = FAMILIES,
                    max_mode: str = "online"
                    ) -> list[FuzzConfig]:
    """The deterministic case list for one fuzz campaign: same seed →
    byte-identical configs, independent of which cases later fail."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(cases):
        cfg = sample_config(rng, families=families, max_mode=max_mode)
        cfg.validate()
        out.append(cfg)
    return out
