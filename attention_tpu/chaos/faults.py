"""Seeded fault plans injected into the serving engine's step loop.

The fuzzer (`chaos.fuzzer`) attacks the KERNELS; this module attacks
the ENGINE — the allocator state you never reached and the scheduling
interleavings you never tested.  A :class:`FaultPlan` is a seeded,
JSON-able list of events fired between engine steps:

* ``oom``       — the next N admission-path page allocations raise
                  `OutOfPagesError` (capacity pressure without needing
                  a giant trace);
* ``preempt``   — preemption-by-recompute storm: forcibly preempt the
                  N youngest running requests;
* ``cancel``    — a client abandons the target request mid-flight
                  (`ServingEngine.cancel`);
* ``corrupt``   — NaN-poison one of the target's unshared KV pages
                  (device-memory rot; must stay contained to the
                  target);
* ``watermark`` — flap the allocator's admission reserve.

`run_plan` replays a trace through an engine with the plan attached
and checks the four invariants (`chaos.invariants`); `run_campaign`
does that for many seeded plans against one fault-free baseline.
Everything is deterministic from the seeds, so a violating plan is
its own repro.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import tempfile
from typing import Any, Callable, Sequence

import numpy as np

from attention_tpu import obs
from attention_tpu.chaos import invariants as inv
from attention_tpu.obs import blackbox as obs_blackbox
from attention_tpu.engine import journal as journal_mod
from attention_tpu.engine import snapshot as snapshot_mod
from attention_tpu.engine.engine import EngineConfig, ServingEngine
from attention_tpu.engine.errors import StepInterruptedError
from attention_tpu.engine.metrics import StepMetrics
from attention_tpu.engine.scheduler import ScheduledStep
from attention_tpu.engine.sim import replay, synthetic_trace
from attention_tpu.ops.paged import OutOfPagesError

_INJECTED = obs.counter("chaos.faults.injected",
                        "fault events actually applied, by kind")

FAULT_KINDS = ("oom", "preempt", "cancel", "corrupt", "watermark")


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    step: int
    kind: str
    arg: int = 1                 # count (oom/preempt) or value (watermark)
    target: str | None = None    # request id (cancel/corrupt)


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    seed: int
    events: tuple[FaultEvent, ...]

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "events": [dataclasses.asdict(e) for e in self.events],
        }, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        data = json.loads(text)
        return cls(seed=int(data["seed"]),
                   events=tuple(FaultEvent(**e) for e in data["events"]))


def random_plan(seed: int, request_ids: Sequence[str], *,
                num_events: int = 4, max_step: int = 20,
                kinds: Sequence[str] = FAULT_KINDS) -> FaultPlan:
    """Sample one seeded plan.  Watermark values deliberately include
    the boundary cases (0 and a value near the pool's reserve) — the
    off-by-one class the allocator's watermark test pins."""
    rng = np.random.default_rng(seed)
    events = []
    for _ in range(num_events):
        kind = kinds[int(rng.integers(len(kinds)))]
        step = int(rng.integers(1, max_step))
        arg, target = 1, None
        if kind in ("oom", "preempt"):
            arg = int(rng.integers(1, 3))
        elif kind == "watermark":
            arg = int(rng.integers(0, 4))
        elif kind in ("cancel", "corrupt"):
            target = request_ids[int(rng.integers(len(request_ids)))]
        events.append(FaultEvent(step=step, kind=kind, arg=arg,
                                 target=target))
    events.sort(key=lambda e: (e.step, e.kind, e.target or ""))
    return FaultPlan(seed=seed, events=tuple(events))


class FaultInjector:
    """Attaches a plan to one engine instance: wraps the allocator's
    ``allocate`` (injected OOM windows) and the engine's ``step``
    (between-step event firing).  Bookkeeps what was ACTUALLY applied
    — the invariant checkers exclude corrupted/cancelled targets from
    token parity."""

    def __init__(self, engine: ServingEngine, plan: FaultPlan):
        self.engine = engine
        self.plan = plan
        self.injected = 0
        self.corrupted: list[str] = []
        self.cancelled: list[str] = []
        self.skipped: list[str] = []
        self._oom_admit = 0
        self._orig_allocate = engine.allocator.allocate
        self._orig_step = engine.step
        engine.allocator.allocate = self._allocate
        engine.step = self._step

    # -- hook points ------------------------------------------------------

    def _allocate(self, n: int, *, for_decode: bool = False):
        if not for_decode and self._oom_admit > 0:
            self._oom_admit -= 1
            self._mark("oom")
            raise OutOfPagesError(
                "chaos: injected admission-path OutOfPagesError"
            )
        return self._orig_allocate(n, for_decode=for_decode)

    def _step(self):
        for ev in self.plan.events:
            if ev.step == self.engine.current_step:
                self._fire(ev)
        return self._orig_step()

    # -- event application ------------------------------------------------

    def _mark(self, kind: str) -> None:
        self.injected += 1
        _INJECTED.inc(kind=kind)

    def _fire(self, ev: FaultEvent) -> None:
        if ev.kind == "oom":
            self._oom_admit += ev.arg
            # marked when the allocation actually raises
        elif ev.kind == "preempt":
            self._preempt_storm(ev.arg)
        elif ev.kind == "cancel":
            if self.engine.cancel(ev.target):
                self.cancelled.append(ev.target)
                self._mark("cancel")
            else:
                self.skipped.append(f"cancel:{ev.target}")
        elif ev.kind == "corrupt":
            if self._corrupt(ev.target):
                self.corrupted.append(ev.target)
                self._mark("corrupt")
            else:
                self.skipped.append(f"corrupt:{ev.target}")
        elif ev.kind == "watermark":
            alloc = self.engine.allocator
            alloc.watermark_pages = max(
                0, min(ev.arg, alloc.pool.num_pages - 1))
            self._mark("watermark")
        else:
            raise ValueError(f"unknown fault kind {ev.kind!r}")

    def _preempt_storm(self, count: int) -> None:
        """Forcibly preempt the ``count`` youngest running requests —
        the allocator-pressure path without needing real pressure."""
        sched = self.engine.scheduler
        for _ in range(count):
            if not sched.running:
                return
            victim = max(sched.running, key=sched._fcfs)
            sched._preempt(victim, ScheduledStep(
                step=self.engine.current_step))
            self._mark("preempt")

    def _corrupt(self, target: str) -> bool:
        """NaN-poison one page the target holds EXCLUSIVELY (shared
        prefix-cache pages would leak the fault into other requests —
        the harness injects contained faults; containment is what the
        parity invariant then proves)."""
        import jax.numpy as jnp

        engine = self.engine
        req = next((r for r in engine.scheduler.running
                    if r.request_id == target and r.pages), None)
        if req is None:
            return False
        cached = {e.page for e in engine.allocator._prefix.values()}
        page = next((p for p in reversed(req.pages)
                     if p not in cached
                     and engine.pool.refcount(p) == 1), None)
        if page is None:
            return False
        for layer in range(len(engine._k_pools)):
            engine._k_pools[layer] = \
                engine._k_pools[layer].at[page].set(jnp.nan)
            engine._v_pools[layer] = \
                engine._v_pools[layer].at[page].set(jnp.nan)
        return True


# ------------------------------------------------------------- plan runs


@dataclasses.dataclass
class PlanReport:
    plan: FaultPlan
    injected: int
    corrupted: list[str]
    cancelled: list[str]
    skipped: list[str]
    outputs: dict[str, list[int]]
    violations: list[str]
    surfaced_error: str | None
    drained: bool
    preemptions: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["plan"] = json.loads(self.plan.to_json())
        return d


def default_engine_config(**overrides) -> EngineConfig:
    """Campaign engine geometry: small enough that injected pressure
    means something, large enough to hold the default trace."""
    kw: dict[str, Any] = dict(
        num_pages=16, page_size=128, max_seq_len=192,
        max_decode_batch=4, max_prefill_rows=2, prefill_chunk=16,
        token_budget=32, watermark_pages=1,
    )
    kw.update(overrides)
    return EngineConfig(**kw)


def build_sim_model(*, vocab: int = 43, dim: int = 32, depth: int = 1,
                    q_heads: int = 4, kv_heads: int = 2, seed: int = 0):
    """Deterministic tiny decoder (the `cli serve-sim` discipline:
    params from PRNGKey(seed), so every run is bit-identical)."""
    import jax
    import jax.numpy as jnp

    from attention_tpu.models import TinyDecoder

    model = TinyDecoder(vocab=vocab, dim=dim, depth=depth,
                        num_q_heads=q_heads, num_kv_heads=kv_heads,
                        impl="flash", dtype=jnp.float32)
    probe = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(seed), probe)["params"]
    return model, params


def run_plan(model, params, config: EngineConfig,
             trace: list[dict[str, Any]], plan: FaultPlan, *,
             baseline: dict[str, list[int]] | None = None,
             max_steps: int = 500) -> PlanReport:
    """Replay ``trace`` through a fresh engine with ``plan`` attached;
    check every invariant that applies.  ``baseline`` (a fault-free
    run's outputs) enables the token-parity check."""
    engine = ServingEngine(model, params, config)
    injector = FaultInjector(engine, plan)
    error: BaseException | None = None
    outputs: dict[str, list[int]] = {}
    try:
        _, outputs = replay(engine, trace, max_steps=max_steps)
    except Exception as e:  # noqa: BLE001 - the typed-error invariant
        error = e           # decides what may land here
    drained = error is None and not engine.scheduler.has_work()

    violations = []
    violations += inv.pool_accounting_violations(engine.pool)
    if drained:
        violations += inv.engine_quiescence_violations(engine)
        if baseline is not None:
            untouched_baseline = dict(baseline)
            violations += inv.token_parity_violations(
                untouched_baseline, outputs,
                exclude=set(injector.corrupted) | set(injector.cancelled),
            )
    violations += inv.termination_violations(drained, error,
                                             max_steps=max_steps)
    violations += inv.typed_error_violations(error)
    return PlanReport(
        plan=plan, injected=injector.injected,
        corrupted=injector.corrupted, cancelled=injector.cancelled,
        skipped=injector.skipped, outputs=outputs,
        violations=violations,
        surfaced_error=None if error is None else type(error).__name__,
        drained=drained,
        preemptions=engine.scheduler.num_preemptions,
    )


@dataclasses.dataclass
class FaultCampaignReport:
    seed: int
    baseline_outputs: dict[str, list[int]]
    reports: list[PlanReport]

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.reports)

    @property
    def total_injected(self) -> int:
        return sum(r.injected for r in self.reports)

    def to_dict(self) -> dict[str, Any]:
        return {
            "seed": self.seed,
            "plans": len(self.reports),
            "injected": self.total_injected,
            "violations": sum(len(r.violations) for r in self.reports),
            "reports": [r.to_dict() for r in self.reports],
        }


def run_campaign(seed: int, *, num_plans: int = 5,
                 num_requests: int = 5, temperature: float = 0.0,
                 events_per_plan: int = 4,
                 config: EngineConfig | None = None,
                 model=None, params=None,
                 log: Callable[[str], None] | None = None,
                 ) -> FaultCampaignReport:
    """One seeded fault campaign: a fault-free baseline run, then
    ``num_plans`` seeded plans against the SAME trace, each checked
    for all four invariants."""
    if model is None or params is None:
        model, params = build_sim_model()
    config = config or default_engine_config()
    trace = synthetic_trace(
        num_requests, vocab=model.vocab, seed=seed, max_tokens=6,
        temperature=temperature,
    )
    engine = ServingEngine(model, params, config)
    _, baseline = replay(engine, trace)
    ids = [t["id"] for t in trace]
    reports = []
    for i in range(num_plans):
        plan = random_plan(seed * 1009 + i, ids,
                           num_events=events_per_plan)
        r = run_plan(model, params, config, trace, plan,
                     baseline=baseline)
        if log is not None:
            log(f"plan {i} (seed {plan.seed}): injected={r.injected} "
                f"violations={len(r.violations)} "
                f"error={r.surfaced_error or 'none'}")
        reports.append(r)
    return FaultCampaignReport(seed=seed, baseline_outputs=baseline,
                               reports=reports)


# ------------------------------------------- multi-replica storm plans


FRONTEND_FAULT_KINDS = ("replica_kill", "replica_restart", "oom",
                        "preempt", "cancel")

#: the durability crash points (ISSUE 9) — only meaningful against a
#: snapshot-configured front end, so they live in their own kind set
#: (plain storms keep their historical sampling sequence)
CRASH_FAULT_KINDS = FRONTEND_FAULT_KINDS + (
    "snap_crash",     # arm the next snapshot save to die mid-write
    "snap_corrupt",   # bit-flip a section of the newest snapshot
    "journal_tear",   # truncate the newest journal mid-record
)

#: the gray failures (ISSUE 10) — a replica that is sick but not dead:
#: each arms a WINDOW of ``arg`` affected steps on the target replica's
#: CURRENT engine, exactly the shapes the `ReplicaSupervisor` detects
GRAY_FAULT_KINDS = (
    "slow_step",      # inflate the engine's virtual step cost
    "flaky_step",     # typed StepInterruptedError before the step runs
    "stall",          # silently swallow the step (counter freezes)
    "nan",            # poison the model's output logits with NaN
)

#: the prefix-store faults (ISSUE 17) — only meaningful against a
#: front end with ``FrontendConfig.prefix_store`` set; each attacks a
#: different leg of the fleet-reuse contract (payload integrity,
#: manifest integrity, the single-flight lease, the byte budget)
STORE_FAULT_KINDS = FRONTEND_FAULT_KINDS + (
    "store_poison",   # flip a byte inside a stored record's payload
    "store_crc",      # flip a byte inside a record's manifest line
    "lease_kill",     # kill the replica serving the lease leader
    "store_evict",    # eviction storm: drop every entry at once
)

#: the disaggregation faults (ISSUE 19) — only meaningful against a
#: front end with ``FrontendConfig.fleet`` set; each attacks a leg of
#: the prefill/decode contract (handoff payload integrity, autoscaler
#: hysteresis)
DISAGG_FAULT_KINDS = FRONTEND_FAULT_KINDS + (
    "handoff_poison",  # corrupt the next N prefill->decode payloads
    "demote_storm",    # force N hysteresis-bypassing scale-downs
)


def random_frontend_plan(seed: int, request_ids: Sequence[str],
                         num_replicas: int, *, num_events: int = 5,
                         max_tick: int = 24,
                         kinds: Sequence[str] = FRONTEND_FAULT_KINDS,
                         ) -> FaultPlan:
    """Sample one seeded multi-replica storm plan.  Reuses the
    engine-plan schema (`FaultEvent.target` carries a replica id for
    replica-scoped kinds, a request id for ``cancel``).  Every
    ``replica_kill`` schedules a matching ``replica_restart`` a few
    ticks later with high probability, so storms exercise the
    kill -> requeue -> recover arc and not just attrition."""
    rng = np.random.default_rng(seed)
    events = []
    for _ in range(num_events):
        kind = kinds[int(rng.integers(len(kinds)))]
        step = int(rng.integers(1, max_tick))
        arg, target = 1, None
        if kind == "replica_kill":
            target = f"replica-{int(rng.integers(num_replicas))}"
            if rng.random() < 0.75:
                events.append(FaultEvent(
                    step=step + int(rng.integers(2, 7)),
                    kind="replica_restart", target=target))
        elif kind == "replica_restart":
            target = f"replica-{int(rng.integers(num_replicas))}"
        elif kind in ("oom", "preempt"):
            arg = int(rng.integers(1, 3))
            target = f"replica-{int(rng.integers(num_replicas))}"
        elif kind == "cancel":
            target = request_ids[int(rng.integers(len(request_ids)))]
        events.append(FaultEvent(step=step, kind=kind, arg=arg,
                                 target=target))
    events.sort(key=lambda e: (e.step, e.kind, e.target or ""))
    return FaultPlan(seed=seed, events=tuple(events))


def random_crash_plan(seed: int, request_ids: Sequence[str],
                      num_replicas: int, *, num_events: int = 6,
                      max_tick: int = 24) -> FaultPlan:
    """Sample one seeded crash-storm plan: the ISSUE 6 storm kinds
    PLUS the three durability crash points, with kills biased toward
    warm-recovery coverage.  Every sampled kill still schedules its
    restart; the crash points target a replica's snapshot directory so
    the restart is forced through the warm-or-degrade decision."""
    rng = np.random.default_rng(seed)
    events = []
    crash_kinds = ("snap_crash", "snap_corrupt", "journal_tear")
    for _ in range(num_events):
        kind = CRASH_FAULT_KINDS[int(rng.integers(len(CRASH_FAULT_KINDS)))]
        step = int(rng.integers(1, max_tick))
        arg, target = 1, None
        if kind == "replica_kill":
            target = f"replica-{int(rng.integers(num_replicas))}"
            if rng.random() < 0.9:
                events.append(FaultEvent(
                    step=step + int(rng.integers(2, 7)),
                    kind="replica_restart", target=target))
        elif kind in ("replica_restart", "oom", "preempt") \
                or kind in crash_kinds:
            target = f"replica-{int(rng.integers(num_replicas))}"
            if kind in ("oom", "preempt"):
                arg = int(rng.integers(1, 3))
            elif kind == "journal_tear":
                arg = int(rng.integers(0, 4))
        elif kind == "cancel":
            target = request_ids[int(rng.integers(len(request_ids)))]
        events.append(FaultEvent(step=step, kind=kind, arg=arg,
                                 target=target))
    # guarantee at least one kill+restart pair per plan: a crash storm
    # that never kills anything never exercises warm recovery
    if not any(e.kind == "replica_kill" for e in events):
        victim = f"replica-{int(rng.integers(num_replicas))}"
        step = int(rng.integers(2, max_tick))
        events.append(FaultEvent(step=step, kind="replica_kill",
                                 target=victim))
        events.append(FaultEvent(step=step + int(rng.integers(2, 7)),
                                 kind="replica_restart", target=victim))
    events.sort(key=lambda e: (e.step, e.kind, e.target or ""))
    return FaultPlan(seed=seed, events=tuple(events))


def random_gray_plan(seed: int, request_ids: Sequence[str],
                     num_replicas: int, *, num_events: int = 6,
                     max_tick: int = 24) -> FaultPlan:
    """Sample one seeded gray storm: sick-but-not-dead windows
    (`GRAY_FAULT_KINDS`) plus the occasional client cancel, with one
    guaranteed slow-step window, one flaky-step window, and one
    fail-stop kill per plan — the acceptance mix (detection, live
    migration, AND standby promotion all get exercised)."""
    rng = np.random.default_rng(seed)
    kinds = GRAY_FAULT_KINDS + ("cancel",)
    events = []
    for _ in range(num_events):
        kind = kinds[int(rng.integers(len(kinds)))]
        step = int(rng.integers(1, max_tick))
        arg, target = 1, None
        if kind in GRAY_FAULT_KINDS:
            arg = int(rng.integers(2, 6))   # window length in steps
            target = f"replica-{int(rng.integers(num_replicas))}"
        else:
            target = request_ids[int(rng.integers(len(request_ids)))]
        events.append(FaultEvent(step=step, kind=kind, arg=arg,
                                 target=target))
    for kind in ("slow_step", "flaky_step"):
        if not any(e.kind == kind for e in events):
            events.append(FaultEvent(
                step=int(rng.integers(1, max_tick)), kind=kind,
                arg=int(rng.integers(2, 6)),
                target=f"replica-{int(rng.integers(num_replicas))}"))
    if not any(e.kind == "replica_kill" for e in events):
        events.append(FaultEvent(
            step=int(rng.integers(2, max_tick)), kind="replica_kill",
            target=f"replica-{int(rng.integers(num_replicas))}"))
    events.sort(key=lambda e: (e.step, e.kind, e.target or ""))
    return FaultPlan(seed=seed, events=tuple(events))


def random_store_plan(seed: int, request_ids: Sequence[str],
                      num_replicas: int, *, num_events: int = 6,
                      max_tick: int = 40) -> FaultPlan:
    """Sample one seeded prefix-store storm: the ISSUE 6 storm kinds
    plus the four store attacks, with at least one store fault
    guaranteed per plan (a store storm that never touches the store
    proves nothing).  ``arg`` on the corruption kinds picks WHICH live
    entry gets hit (mod the live count at fire time), so replays are
    deterministic even as the store fills."""
    rng = np.random.default_rng(seed)
    store_kinds = ("store_poison", "store_crc", "lease_kill",
                   "store_evict")
    events = []
    for _ in range(num_events):
        kind = STORE_FAULT_KINDS[int(rng.integers(len(STORE_FAULT_KINDS)))]
        step = int(rng.integers(1, max_tick))
        arg, target = 1, None
        if kind == "replica_kill":
            target = f"replica-{int(rng.integers(num_replicas))}"
            if rng.random() < 0.9:
                events.append(FaultEvent(
                    step=step + int(rng.integers(2, 7)),
                    kind="replica_restart", target=target))
        elif kind in ("replica_restart", "oom", "preempt"):
            target = f"replica-{int(rng.integers(num_replicas))}"
            if kind in ("oom", "preempt"):
                arg = int(rng.integers(1, 3))
        elif kind == "cancel":
            target = request_ids[int(rng.integers(len(request_ids)))]
        elif kind in ("store_poison", "store_crc"):
            arg = int(rng.integers(0, 8))
        events.append(FaultEvent(step=step, kind=kind, arg=arg,
                                 target=target))
    if not any(e.kind in store_kinds for e in events):
        events.append(FaultEvent(
            step=int(rng.integers(2, max_tick)),
            kind=store_kinds[int(rng.integers(len(store_kinds)))],
            arg=int(rng.integers(0, 8))))
    events.sort(key=lambda e: (e.step, e.kind, e.target or ""))
    return FaultPlan(seed=seed, events=tuple(events))


def random_disagg_plan(seed: int, request_ids: Sequence[str],
                       num_replicas: int, *, num_events: int = 6,
                       max_tick: int = 40) -> FaultPlan:
    """Sample one seeded disaggregation storm: the ISSUE 6 kinds plus
    the two fleet attacks, with at least one of each fleet attack
    guaranteed per plan (a disagg storm that never poisons a handoff
    or forces a demotion proves nothing).  ``arg`` is the window size
    — payloads to corrupt, demotions to force."""
    rng = np.random.default_rng(seed)
    specialty = ("handoff_poison", "demote_storm")
    events = []
    for _ in range(num_events):
        kind = DISAGG_FAULT_KINDS[
            int(rng.integers(len(DISAGG_FAULT_KINDS)))]
        step = int(rng.integers(1, max_tick))
        arg, target = 1, None
        if kind == "replica_kill":
            target = f"replica-{int(rng.integers(num_replicas))}"
            if rng.random() < 0.9:
                events.append(FaultEvent(
                    step=step + int(rng.integers(2, 7)),
                    kind="replica_restart", target=target))
        elif kind in ("replica_restart", "oom", "preempt"):
            target = f"replica-{int(rng.integers(num_replicas))}"
            if kind in ("oom", "preempt"):
                arg = int(rng.integers(1, 3))
        elif kind == "cancel":
            target = request_ids[int(rng.integers(len(request_ids)))]
        elif kind in specialty:
            arg = int(rng.integers(1, 4))
        events.append(FaultEvent(step=step, kind=kind, arg=arg,
                                 target=target))
    for kind in specialty:
        if not any(e.kind == kind for e in events):
            events.append(FaultEvent(
                step=int(rng.integers(2, max_tick)), kind=kind,
                arg=int(rng.integers(1, 4))))
    events.sort(key=lambda e: (e.step, e.kind, e.target or ""))
    return FaultPlan(seed=seed, events=tuple(events))


def _flip_byte(path: str) -> None:
    """Bit-flip the middle byte of a file in place — lands inside the
    (dominant) pools section of a snapshot, so restore must fail its
    section checksum, never deserialize garbage."""
    with open(path, "r+b") as f:
        data = f.read()
        if not data:
            return
        mid = len(data) // 2
        f.seek(mid)
        f.write(bytes([data[mid] ^ 0xFF]))


def _tear_tail(path: str, arg: int) -> None:
    """Truncate a journal mid-record: cut at least 3 bytes so the torn
    line can never still parse (tearing only the trailing newline
    would leave a VALID record, which is no tear at all)."""
    size = os.path.getsize(path)
    os.truncate(path, size - min(size, 3 + arg * 5))


class FrontendFaultInjector:
    """Attaches a storm plan to one `ServingFrontend`: wraps ``tick``
    and fires due events before the round runs.  Replica-scoped OOM
    windows wrap the CURRENT engine's allocator (a restarted engine
    starts clean — exactly like a real process restart shedding its
    fault state)."""

    def __init__(self, frontend, plan: FaultPlan):
        self.frontend = frontend
        self.plan = plan
        self.injected = 0
        self.cancelled: list[str] = []
        self.skipped: list[str] = []
        #: (kind, tick) of every fault ACTUALLY applied, in order —
        #: invariant 15 matches this ledger against the incident
        #: bundles the run dumped
        self.fired: list[tuple[str, int]] = []
        self._orig_tick = frontend.tick
        frontend.tick = self._tick

    def _mark(self, kind: str) -> None:
        self.injected += 1
        _INJECTED.inc(kind=kind)
        tick = self.frontend.current_tick
        self.fired.append((kind, tick))
        obs_blackbox.note("fault_injected", tick=tick, fault=kind)
        # every applied fault files its incident at injection time
        # (deduped per (cause, detail), so a multi-shot window at one
        # tick still yields exactly one bundle)
        self.frontend._incident("fault", {"kind": kind, "tick": tick})

    def _tick(self):
        for ev in self.plan.events:
            if ev.step == self.frontend.current_tick:
                self._fire(ev)
        return self._orig_tick()

    def _handle(self, replica_id: str | None):
        return next((h for h in self.frontend.replicas
                     if h.replica_id == replica_id), None)

    def _fire(self, ev: FaultEvent) -> None:
        if ev.kind == "replica_kill":
            if self.frontend.kill_replica(ev.target):
                self._mark("replica_kill")
            else:
                self.skipped.append(f"replica_kill:{ev.target}")
        elif ev.kind == "replica_restart":
            if self.frontend.restart_replica(ev.target):
                self._mark("replica_restart")
            else:
                self.skipped.append(f"replica_restart:{ev.target}")
        elif ev.kind == "oom":
            handle = self._handle(ev.target)
            if handle is None or not handle.alive:
                self.skipped.append(f"oom:{ev.target}")
                return
            self._arm_oom(handle, ev.arg)
        elif ev.kind == "preempt":
            handle = self._handle(ev.target)
            if handle is None or not handle.alive:
                self.skipped.append(f"preempt:{ev.target}")
                return
            self._preempt_storm(handle, ev.arg)
        elif ev.kind == "cancel":
            if self.frontend.cancel(ev.target):
                self.cancelled.append(ev.target)
                self._mark("cancel")
            else:
                self.skipped.append(f"cancel:{ev.target}")
        elif ev.kind == "snap_crash":
            handle = self._handle(ev.target)
            manager = getattr(handle, "_manager", None)
            if handle is None or not handle.alive or manager is None:
                self.skipped.append(f"snap_crash:{ev.target}")
                return
            manager.crash_next = True
            self._mark("snap_crash")
        elif ev.kind == "snap_corrupt":
            handle = self._handle(ev.target)
            snaps = snapshot_mod.list_snapshots(handle.snapshot_dir) \
                if handle is not None and handle.snapshot_dir else []
            if not snaps:
                self.skipped.append(f"snap_corrupt:{ev.target}")
                return
            _flip_byte(snaps[-1][1])
            self._mark("snap_corrupt")
        elif ev.kind == "journal_tear":
            handle = self._handle(ev.target)
            journals = journal_mod.list_journals(handle.snapshot_dir) \
                if handle is not None and handle.snapshot_dir else []
            if not journals:
                self.skipped.append(f"journal_tear:{ev.target}")
                return
            _tear_tail(journals[-1][1], ev.arg)
            self._mark("journal_tear")
        elif ev.kind in ("store_poison", "store_crc"):
            self._corrupt_store_entry(ev)
        elif ev.kind == "lease_kill":
            self._kill_lease_holder()
        elif ev.kind == "store_evict":
            store = getattr(self.frontend, "prefix_store", None)
            if store is None or not len(store):
                self.skipped.append("store_evict:empty")
                return
            store.evict_all(now=self.frontend.current_tick)
            self._mark("store_evict")
        elif ev.kind == "handoff_poison":
            if not getattr(self.frontend, "pool_of", None):
                self.skipped.append("handoff_poison:no-fleet")
                return
            self.frontend._poison_handoffs += max(1, ev.arg)
            self._mark("handoff_poison")
        elif ev.kind == "demote_storm":
            if getattr(self.frontend, "autoscaler", None) is None:
                self.skipped.append("demote_storm:no-autoscaler")
                return
            self.frontend._force_demotions += max(1, ev.arg)
            self._mark("demote_storm")
        elif ev.kind in GRAY_FAULT_KINDS:
            handle = self._handle(ev.target)
            if handle is None or not handle.alive:
                self.skipped.append(f"{ev.kind}:{ev.target}")
                return
            self._arm_gray(handle, ev.kind, max(1, ev.arg))
        else:
            raise ValueError(f"unknown frontend fault kind {ev.kind!r}")

    def _corrupt_store_entry(self, ev: FaultEvent) -> None:
        """Flip one byte of a live record in place — in the payload
        region (``store_poison``: the section CRC must catch it) or in
        the manifest line (``store_crc``: structural validation must
        catch it).  Either way the ONLY acceptable outcome downstream
        is `PrefixStoreCorruptError` handling: count, discard, cold
        re-prefill — never imported garbage (invariant 14 checks the
        token streams)."""
        store = getattr(self.frontend, "prefix_store", None)
        keys = sorted(store._entries) if store is not None else []
        if not keys:
            self.skipped.append(f"{ev.kind}:no-entries")
            return
        entry = store._entries[keys[ev.arg % len(keys)]]
        blob = bytearray(entry.blob)
        nl = blob.index(b"\n")
        if ev.kind == "store_poison":
            pos = nl + 1 + (len(blob) - nl - 1) // 2
        else:
            pos = nl // 2
        blob[pos] ^= 0xFF
        entry.blob = bytes(blob)
        self._mark(ev.kind)

    def _kill_lease_holder(self) -> None:
        """Fail-stop the replica currently prefilling for a
        single-flight lease leader: the leader rides the retry path to
        another replica (still holding its lease via the front end's
        heartbeat), so coalesced waiters must keep waiting and then
        import — exactly one fleet prefill even across the kill."""
        store = getattr(self.frontend, "prefix_store", None)
        if store is None:
            self.skipped.append("lease_kill:no-store")
            return
        victim = None
        for _key, owner in store.leases.active(
                now=self.frontend.current_tick):
            fr = self.frontend.requests.get(owner)
            if fr is not None and fr.replica_id is not None:
                victim = fr.replica_id
                break
        if victim is None or not self.frontend.kill_replica(victim):
            self.skipped.append("lease_kill:no-holder")
            return
        self._mark("lease_kill")

    def _arm_gray(self, handle, kind: str, count: int) -> None:
        """Arm a gray-failure window of ``count`` steps on the target
        replica's CURRENT engine (like `_arm_oom`, a restart sheds the
        fault state — a fresh process is healthy until proven sick).

        * ``slow_step`` — the step runs normally, then its virtual
          cost is inflated; only the supervisor's EWMA notices.
        * ``flaky_step`` — typed `StepInterruptedError` raised BEFORE
          the inner step, so no request state mutates.
        * ``stall`` — the step is silently swallowed (a fake metrics
          row, frozen step counter): the gray failure with no error.
        * ``nan`` — the model's output logits come back NaN; the
          engine's finite guard must skip sampling (never emit
          garbage) and count the event.
        """
        eng = handle.engine
        state = {"left": count}
        if kind == "nan":
            # wrap the logits device sync — the ONE seam both step
            # modes (ragged single-launch and legacy two-call) fetch
            # through, so the injector composes with either loop and
            # with async staging unchanged
            orig_fetch = eng._fetch_logits

            def poisoned(*args, **kwargs):
                out = orig_fetch(*args, **kwargs)
                if state["left"] > 0:
                    state["left"] -= 1
                    self._mark("nan")
                    out = np.full_like(np.asarray(out), np.nan)
                return out

            eng._fetch_logits = poisoned
            return
        orig_step = eng.step

        def wrapped_step():
            if state["left"] > 0 and kind == "flaky_step":
                state["left"] -= 1
                self._mark("flaky_step")
                raise StepInterruptedError(
                    f"chaos: injected step interruption on "
                    f"{handle.replica_id}"
                )
            if state["left"] > 0 and kind == "stall":
                state["left"] -= 1
                self._mark("stall")
                return StepMetrics(step=eng.current_step)
            metrics = orig_step()
            if state["left"] > 0 and kind == "slow_step":
                state["left"] -= 1
                self._mark("slow_step")
                eng.last_step_virtual_cost = 4.0
            return metrics

        eng.step = wrapped_step

    def _arm_oom(self, handle, count: int) -> None:
        """The next ``count`` admission-path allocations on this
        replica's CURRENT engine raise — the scheduler defers those
        admissions, and the front end's stall detector must migrate
        the starved requests elsewhere."""
        alloc = handle.engine.allocator
        state = {"left": count}
        orig = alloc.allocate

        def wrapped(n, *, for_decode=False):
            if not for_decode and state["left"] > 0:
                state["left"] -= 1
                self._mark("oom")
                raise OutOfPagesError(
                    f"chaos: injected admission OutOfPagesError on "
                    f"{handle.replica_id}"
                )
            return orig(n, for_decode=for_decode)

        alloc.allocate = wrapped

    def _preempt_storm(self, handle, count: int) -> None:
        sched = handle.engine.scheduler
        for _ in range(count):
            if not sched.running:
                return
            victim = max(sched.running, key=sched._fcfs)
            sched._preempt(victim, ScheduledStep(
                step=handle.engine.current_step))
            self._mark("preempt")


@dataclasses.dataclass
class FrontendPlanReport:
    """One storm's verdict (the frontend analogue of `PlanReport`)."""

    plan: FaultPlan
    injected: int
    cancelled: list[str]
    skipped: list[str]
    outputs: dict[str, list[int]]
    states: dict[str, str]
    violations: list[str]
    surfaced_error: str | None
    drained: bool
    summary: dict[str, Any]

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["plan"] = json.loads(self.plan.to_json())
        return d


def default_frontend_config(num_replicas: int = 3, **overrides):
    """Storm-campaign front-end geometry: tight retry budget so
    exhaustion paths actually fire, short stall window so injected
    OOM windows visibly migrate requests."""
    from attention_tpu.frontend import FrontendConfig, RetryPolicy

    from attention_tpu.obs.forecast import ForecastPolicy

    kw: dict[str, Any] = dict(
        num_replicas=num_replicas, seed=0,
        retry=RetryPolicy(max_retries=4, base_delay_ticks=1,
                          max_delay_ticks=8),
        stall_ticks=3,
        # forecasting on (passive, advisory off) so every campaign
        # exercises invariant 13 under its storm
        forecast=ForecastPolicy(),
    )
    kw.update(overrides)
    return FrontendConfig(**kw)


def run_frontend_plan(model, params, config: EngineConfig,
                      frontend_config, trace: list[dict[str, Any]],
                      plan: FaultPlan, *,
                      baseline: dict[str, list[int]] | None = None,
                      max_ticks: int = 1000,
                      snapshot_roundtrip: bool = False,
                      incident_root: str | None = None,
                      ) -> FrontendPlanReport:
    """Replay ``trace`` through a fresh front end with ``plan``
    attached; check every invariant that applies — including the two
    ISSUE 6 checkers (no request lost, surviving-replica
    conservation).  ``baseline`` (a fault-free SINGLE-replica run)
    enables token parity over finished requests.
    ``snapshot_roundtrip`` additionally pins invariant 7 on every
    surviving replica of a drained run (``restore(save(engine))``
    state-identical).

    The whole plan runs inside ``obs.trace.capture()`` so invariant 12
    (trace completeness) has chains to judge even with telemetry off —
    capture clears the store on entry, isolating each plan's chains.
    ``obs.blackbox.capture()`` wraps it too: every applied fault lands
    in the flight-recorder ring AND dumps an incident bundle under
    ``incident_root`` (a throwaway directory when not given and the
    config carries none), which invariant 15 then audits for
    completeness — no injected fault without its bundle, no
    fault-cause bundle without its injection."""
    from attention_tpu.frontend import ServingFrontend, replay_frontend
    from attention_tpu.obs import trace as obs_trace

    with contextlib.ExitStack() as stack:
        if getattr(frontend_config, "incident_dir", None) is None:
            if incident_root is None:
                incident_root = stack.enter_context(
                    tempfile.TemporaryDirectory(
                        prefix="atp-incidents-"))
            frontend_config = dataclasses.replace(
                frontend_config, incident_dir=incident_root)
        return _run_frontend_plan_inner(
            model, params, config, frontend_config, trace, plan,
            baseline=baseline, max_ticks=max_ticks,
            snapshot_roundtrip=snapshot_roundtrip)


def _run_frontend_plan_inner(model, params, config, frontend_config,
                             trace, plan, *, baseline, max_ticks,
                             snapshot_roundtrip) -> FrontendPlanReport:
    from attention_tpu.frontend import ServingFrontend, replay_frontend
    from attention_tpu.obs import trace as obs_trace

    with obs_trace.capture(), obs_blackbox.capture():
        frontend = ServingFrontend(model, params, config,
                                   frontend_config)
        injector = FrontendFaultInjector(frontend, plan)
        error: BaseException | None = None
        outputs: dict[str, list[int]] = {}
        summary: dict[str, Any] = {}
        try:
            summary, outputs = replay_frontend(frontend, trace,
                                               max_ticks=max_ticks)
        except Exception as e:  # noqa: BLE001 - the typed-error
            error = e           # invariant decides what may land here
            outputs = frontend.outputs()
        drained = error is None and not frontend.has_work()

    from attention_tpu.frontend.frontend import FrontendRequestState

    violations = []
    violations += inv.replica_conservation_violations(frontend,
                                                      drained=drained)
    if drained:
        violations += inv.no_request_lost_violations(frontend)
        if baseline is not None:
            finished = {
                fr.request_id
                for fr in frontend.requests.values()
                if fr.state is FrontendRequestState.FINISHED
            }
            violations += inv.token_parity_violations(
                {rid: toks for rid, toks in baseline.items()
                 if rid in finished},
                outputs,
            )
    # the gray-failure trio (ISSUE 10): all three are no-ops on a
    # front end whose supervisor never issued a verdict
    violations += inv.no_double_serve_violations(frontend)
    violations += inv.supervisor_consistency_violations(frontend)
    if drained and baseline is not None:
        violations += inv.migration_parity_violations(frontend,
                                                      baseline)
    if baseline is not None:
        # invariant 14: a no-op on storeless front ends; with a store
        # attached, finished streams must match the NO-STORE fault-free
        # run and the store's byte ledger must balance
        violations += inv.prefix_import_parity_violations(frontend,
                                                          baseline)
    violations += inv.termination_violations(drained, error,
                                             max_steps=max_ticks)
    violations += inv.typed_error_violations(error)
    # invariant 12: the capture scope above recorded a chain for every
    # submitted request; judge them (incl. gray + crash campaigns,
    # which all funnel through this runner)
    violations += inv.trace_completeness_violations(frontend)
    # invariant 15: the incident ledger balances — every applied fault
    # dumped exactly one bundle naming its kind and tick, and every
    # fault/detector bundle traces back to a real cause
    violations += inv.incident_completeness_violations(frontend,
                                                       injector)
    # invariant 16: a no-op on monolithic front ends; with a fleet
    # attached, every pool resize balances against the blackbox ring
    # and no pool flaps inside the cooldown window
    violations += inv.actuation_ledger_violations(frontend)
    # invariant 13: campaigns enable forecasting (see
    # default_frontend_config) — the observatory report must be a
    # pure function of the recorded samples, storm or no storm
    violations += inv.forecast_determinism_violations(frontend)
    if snapshot_roundtrip and drained:
        for handle in frontend.replicas:
            if handle.alive:
                violations += [
                    f"{handle.replica_id}: {v}"
                    for v in inv.snapshot_roundtrip_violations(
                        handle.engine)
                ]
    return FrontendPlanReport(
        plan=plan, injected=injector.injected,
        cancelled=injector.cancelled, skipped=injector.skipped,
        outputs=outputs,
        states={fr.request_id: fr.state.value
                for fr in sorted(frontend.requests.values(),
                                 key=lambda f: f.seq)},
        violations=violations,
        surfaced_error=None if error is None else type(error).__name__,
        drained=drained,
        summary=summary,
    )


@dataclasses.dataclass
class FrontendCampaignReport:
    seed: int
    num_replicas: int
    baseline_outputs: dict[str, list[int]]
    reports: list[FrontendPlanReport]

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.reports)

    @property
    def total_injected(self) -> int:
        return sum(r.injected for r in self.reports)

    def to_dict(self) -> dict[str, Any]:
        return {
            "seed": self.seed,
            "replicas": self.num_replicas,
            "plans": len(self.reports),
            "injected": self.total_injected,
            "violations": sum(len(r.violations) for r in self.reports),
            "reports": [r.to_dict() for r in self.reports],
        }


def run_frontend_campaign(seed: int, *, num_plans: int = 5,
                          num_requests: int = 6, num_replicas: int = 3,
                          temperature: float = 0.0,
                          events_per_plan: int = 5,
                          config: EngineConfig | None = None,
                          model=None, params=None,
                          log: Callable[[str], None] | None = None,
                          ) -> FrontendCampaignReport:
    """One seeded storm campaign: a fault-free SINGLE-replica baseline
    run, then ``num_plans`` seeded replica-kill/OOM/preemption storms
    against the same trace through an N-replica front end, each
    checked for all six invariants."""
    if model is None or params is None:
        model, params = build_sim_model()
    config = config or default_engine_config()
    trace = synthetic_trace(
        num_requests, vocab=model.vocab, seed=seed, max_tokens=6,
        temperature=temperature,
    )
    engine = ServingEngine(model, params, config)
    _, baseline = replay(engine, trace)
    ids = [t["id"] for t in trace]
    reports = []
    for i in range(num_plans):
        plan = random_frontend_plan(seed * 2003 + i, ids, num_replicas,
                                    num_events=events_per_plan)
        r = run_frontend_plan(
            model, params, config,
            default_frontend_config(num_replicas), trace, plan,
            baseline=baseline,
        )
        if log is not None:
            log(f"storm {i} (seed {plan.seed}): injected={r.injected} "
                f"violations={len(r.violations)} "
                f"states={sorted(set(r.states.values()))} "
                f"error={r.surfaced_error or 'none'}")
        reports.append(r)
    return FrontendCampaignReport(seed=seed, num_replicas=num_replicas,
                                  baseline_outputs=baseline,
                                  reports=reports)


def run_crash_campaign(seed: int, snapshot_root: str, *,
                       num_plans: int = 5, num_requests: int = 6,
                       num_replicas: int = 2, snapshot_every: int = 2,
                       temperature: float = 0.0,
                       events_per_plan: int = 6,
                       config: EngineConfig | None = None,
                       model=None, params=None,
                       log: Callable[[str], None] | None = None,
                       ) -> FrontendCampaignReport:
    """The ISSUE 9 crash storm: `run_frontend_campaign` with durable
    replicas (periodic snapshots + journals under ``snapshot_root``)
    and the three crash points in the plan mix.  Kills now recover
    WARM when a valid snapshot survives the plan's corruption; on top
    of the six storm invariants each drained plan is checked for
    invariant 7 (round trip on every survivor) and invariant 8
    (every finished stream token-identical to the fault-free run —
    crash points may cost warmth, never tokens).

    Mesh replicas join the same storm by passing ``config`` with
    ``mesh_shards`` > 1 (and a ``model`` whose KV heads divide by
    it): every replica then serves through KV-head-sharded kernels,
    snapshots carry per-shard ``pools.<s>`` sections, and the SAME
    invariants apply unchanged — the fault-free baseline is computed
    with the identical config, so parity failures cannot hide behind
    the sharding."""
    if model is None or params is None:
        model, params = build_sim_model()
    config = config or default_engine_config()
    trace = synthetic_trace(
        num_requests, vocab=model.vocab, seed=seed, max_tokens=6,
        temperature=temperature,
    )
    engine = ServingEngine(model, params, config)
    _, baseline = replay(engine, trace)
    ids = [t["id"] for t in trace]
    reports = []
    for i in range(num_plans):
        plan = random_crash_plan(seed * 5009 + i, ids, num_replicas,
                                 num_events=events_per_plan)
        frontend_config = default_frontend_config(
            num_replicas,
            snapshot_dir=os.path.join(snapshot_root, f"plan-{i}"),
            snapshot_every=snapshot_every,
        )
        r = run_frontend_plan(
            model, params, config, frontend_config, trace, plan,
            baseline=baseline, snapshot_roundtrip=True,
        )
        if r.drained:
            finished = [rid for rid, state in r.states.items()
                        if state == "finished"]
            r.violations += inv.warm_recovery_parity_violations(
                baseline, r.outputs, finished)
        if log is not None:
            log(f"crash storm {i} (seed {plan.seed}): "
                f"injected={r.injected} "
                f"violations={len(r.violations)} "
                f"states={sorted(set(r.states.values()))} "
                f"error={r.surfaced_error or 'none'}")
        reports.append(r)
    return FrontendCampaignReport(seed=seed, num_replicas=num_replicas,
                                  baseline_outputs=baseline,
                                  reports=reports)


def run_gray_campaign(seed: int, snapshot_root: str, *,
                      num_plans: int = 5, num_requests: int = 6,
                      num_replicas: int = 2, standbys: int = 1,
                      snapshot_every: int = 2,
                      temperature: float = 0.0,
                      events_per_plan: int = 6,
                      config: EngineConfig | None = None,
                      model=None, params=None,
                      log: Callable[[str], None] | None = None,
                      ) -> FrontendCampaignReport:
    """The ISSUE 10 gray storm: seeded slow-step / flaky-step / stall /
    NaN windows (plus one guaranteed kill) against a supervised front
    end with ``standbys`` warm spares and durable replicas.  On top of
    the storm and durability invariants each plan is checked for the
    gray trio: migration token parity, no double serve, and supervisor
    consistency — a detected-and-drained replica costs re-prefills,
    never tokens, and never serves after its verdict."""
    from attention_tpu.frontend import SupervisorPolicy

    if model is None or params is None:
        model, params = build_sim_model()
    config = config or default_engine_config()
    trace = synthetic_trace(
        num_requests, vocab=model.vocab, seed=seed, max_tokens=6,
        temperature=temperature,
    )
    engine = ServingEngine(model, params, config)
    _, baseline = replay(engine, trace)
    ids = [t["id"] for t in trace]
    reports = []
    for i in range(num_plans):
        plan = random_gray_plan(seed * 7019 + i, ids, num_replicas,
                                num_events=events_per_plan)
        frontend_config = default_frontend_config(
            num_replicas,
            standbys=standbys,
            snapshot_dir=os.path.join(snapshot_root, f"plan-{i}"),
            snapshot_every=snapshot_every,
            supervisor=SupervisorPolicy(suspect_after=2,
                                        degrade_after=2, dead_after=2,
                                        stall_ticks=2),
        )
        r = run_frontend_plan(
            model, params, config, frontend_config, trace, plan,
            baseline=baseline,
        )
        if r.drained:
            finished = [rid for rid, state in r.states.items()
                        if state == "finished"]
            r.violations += inv.warm_recovery_parity_violations(
                baseline, r.outputs, finished)
        if log is not None:
            log(f"gray storm {i} (seed {plan.seed}): "
                f"injected={r.injected} "
                f"violations={len(r.violations)} "
                f"states={sorted(set(r.states.values()))} "
                f"error={r.surfaced_error or 'none'}")
        reports.append(r)
    return FrontendCampaignReport(seed=seed, num_replicas=num_replicas,
                                  baseline_outputs=baseline,
                                  reports=reports)


def shared_prefix_trace(num_requests: int, *, vocab: int, seed: int,
                        header_tokens: int = 256, tail_tokens: int = 4,
                        max_tokens: int = 4, max_arrival: int = 6,
                        ) -> list[dict[str, Any]]:
    """A RAG-shaped trace: every request shares a ``header_tokens``
    document header (page-aligned so the store can share it) and adds
    a short unique question tail.  Greedy decoding keeps the fault-
    free baseline deterministic.  This is the workload the prefix
    store exists for — the storm campaign runs it so store faults land
    while records are actually live and leased."""
    rng = np.random.default_rng(seed)
    header = [int(t) for t in rng.integers(1, vocab,
                                           size=header_tokens)]
    trace = []
    for i in range(num_requests):
        tail = [int(t) for t in rng.integers(1, vocab,
                                             size=tail_tokens)]
        trace.append({
            "id": f"s{i}", "prompt": header + tail,
            "arrival": int(rng.integers(0, max_arrival)),
            "max_tokens": max_tokens, "temperature": 0.0,
        })
    return trace


def run_store_campaign(seed: int, *, num_plans: int = 4,
                       num_requests: int = 5, num_replicas: int = 2,
                       events_per_plan: int = 6,
                       config: EngineConfig | None = None,
                       model=None, params=None,
                       log: Callable[[str], None] | None = None,
                       ) -> FrontendCampaignReport:
    """The ISSUE 17 store storm: a shared-prefix trace through a
    store-enabled front end under `random_store_plan` faults (poison,
    manifest flip, lease-holder kill, eviction storm, plus the ISSUE 6
    kinds).  The fault-free baseline is a SINGLE storeless engine run,
    so invariant 14 (prefix import parity) judges every finished
    stream against tokens the store could not possibly have touched —
    a poisoned record must cost a re-prefill, never a token."""
    from attention_tpu.prefixstore import PrefixStoreConfig

    if model is None or params is None:
        model, params = build_sim_model()
    config = config or default_engine_config(max_seq_len=384,
                                             num_pages=24)
    trace = shared_prefix_trace(num_requests, vocab=model.vocab,
                                seed=seed)
    engine = ServingEngine(model, params, config)
    _, baseline = replay(engine, trace)
    ids = [t["id"] for t in trace]
    reports = []
    for i in range(num_plans):
        plan = random_store_plan(seed * 9007 + i, ids, num_replicas,
                                 num_events=events_per_plan)
        r = run_frontend_plan(
            model, params, config,
            default_frontend_config(
                num_replicas, prefix_store=PrefixStoreConfig()),
            trace, plan, baseline=baseline,
        )
        if log is not None:
            log(f"store storm {i} (seed {plan.seed}): "
                f"injected={r.injected} "
                f"violations={len(r.violations)} "
                f"states={sorted(set(r.states.values()))} "
                f"error={r.surfaced_error or 'none'}")
        reports.append(r)
    return FrontendCampaignReport(seed=seed, num_replicas=num_replicas,
                                  baseline_outputs=baseline,
                                  reports=reports)


def default_fleet_config(num_replicas: int = 3, *,
                         standbys: int = 2, **overrides):
    """Disagg-campaign front-end geometry: `default_frontend_config`
    plus a 1:N-1 prefill:decode split, a standby bench for the
    autoscaler to work with, and a short-hysteresis policy so storms
    actually actuate inside campaign-length runs."""
    from attention_tpu.fleet import AutoscalerPolicy, FleetTopology

    kw: dict[str, Any] = dict(
        standbys=standbys,
        fleet=FleetTopology(prefill_replicas=1,
                            decode_replicas=num_replicas - 1),
        autoscaler=AutoscalerPolicy(
            scale_up_after=2, scale_down_after=4, cooldown_ticks=8,
            guard_window=6),
    )
    kw.update(overrides)
    return default_frontend_config(num_replicas, **kw)


def run_disagg_campaign(seed: int, *, num_plans: int = 4,
                        num_requests: int = 10, num_replicas: int = 3,
                        events_per_plan: int = 6,
                        temperature: float = 0.0,
                        config: EngineConfig | None = None,
                        model=None, params=None,
                        log: Callable[[str], None] | None = None,
                        ) -> FrontendCampaignReport:
    """The ISSUE 19 disagg storm: a mixed prefill/decode trace
    (`engine.sim.disagg_trace`) through a fleet front end (prefill +
    decode pools, standbys, autoscaler armed) under
    `random_disagg_plan` faults — poisoned handoff payloads, forced
    demotion storms, plus the ISSUE 6 kinds.  The fault-free baseline
    is a SINGLE monolithic engine run, so token parity judges every
    finished stream against tokens no handoff, resize, or fallback
    could have touched; invariant 16 balances the actuation ledger
    per plan."""
    from attention_tpu.engine.sim import disagg_trace

    if model is None or params is None:
        model, params = build_sim_model()
    # RAG headers longer than one 128-token page so handoffs actually
    # ship KV (a payload-less handoff can't exercise the
    # poison/fallback arc)
    config = config or default_engine_config(max_seq_len=384,
                                             num_pages=24)
    trace = disagg_trace(num_requests, vocab=model.vocab, seed=seed,
                         max_tokens=6, rag_prefill_len=160,
                         burst_every=4, burst_size=2)
    engine = ServingEngine(model, params, config)
    _, baseline = replay(engine, trace)
    ids = [t["id"] for t in trace]
    reports = []
    for i in range(num_plans):
        plan = random_disagg_plan(seed * 11003 + i, ids, num_replicas,
                                  num_events=events_per_plan)
        r = run_frontend_plan(
            model, params, config, default_fleet_config(num_replicas),
            trace, plan, baseline=baseline,
        )
        if log is not None:
            log(f"disagg storm {i} (seed {plan.seed}): "
                f"injected={r.injected} "
                f"violations={len(r.violations)} "
                f"states={sorted(set(r.states.values()))} "
                f"error={r.surfaced_error or 'none'}")
        reports.append(r)
    return FrontendCampaignReport(seed=seed, num_replicas=num_replicas,
                                  baseline_outputs=baseline,
                                  reports=reports)
