"""FLOPs accounting and MXU-utilization math.

The reference publishes only relative speedups (BASELINE.md); this repo's
north-star metric is absolute — attention GFLOPs/chip and % of peak
matmul FLOPs (BASELINE.json).  These helpers define that accounting in
one place so bench and tests agree.
"""

from __future__ import annotations

import jax

# Peak dense matmul TFLOP/s per chip by TPU generation (bf16).
# v5e (reported as "TPU v5 lite"): 197 TFLOP/s bf16 — 394 is the int8
# TOPS number, not the bf16 peak.
_PEAK_TFLOPS_BF16 = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5": 459.0,  # v5p
    "TPU v6 lite": 918.0,
}


def attention_flops(m: int, n: int, dk: int, dv: int, *, causal: bool = False,
                    heads: int = 1) -> int:
    """Matmul FLOPs for one attention: QK^T (2·m·n·dk) + P·V (2·m·n·dv).

    Softmax exp/add FLOPs are excluded — the metric is *matmul-FLOPs*
    utilization (BASELINE.json).  ``causal`` halves the score matrix.
    """
    total = 2 * m * n * (dk + dv) * heads
    return total // 2 if causal else total


def peak_flops(device=None) -> float:
    """Peak bf16 matmul FLOP/s for the given (default: first) device."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    for prefix, tflops in _PEAK_TFLOPS_BF16.items():
        if kind.startswith(prefix):
            return tflops * 1e12
    # unknown hardware (e.g. CPU test runs): nominal 1 TFLOP to keep
    # utilization numbers finite but obviously non-physical
    return 1e12


def utilization(flops: int, seconds: float, device=None) -> float:
    """Fraction of peak matmul FLOPs achieved."""
    return flops / seconds / peak_flops(device)
