from attention_tpu.utils.flops import attention_flops, peak_flops, utilization  # noqa: F401
from attention_tpu.utils.timing import benchmark, Timing  # noqa: F401
