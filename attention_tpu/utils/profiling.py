"""Profiling and structured metrics (SURVEY §5 observability plan).

The reference's observability is a printf of wall time and correctness
(`attention.c:186-188`), and its per-phase analysis was done by ablation
builds rather than instrumentation (report Q2).  Here:

  * :func:`trace` wraps ``jax.profiler.trace`` so any benchmark or test
    can capture an XLA/TPU trace (xplane) for the profiler UI;
  * :func:`annotate` names a phase so it shows up on the trace timeline
    (the instrumentation the reference lacked);
  * :class:`RunRecord` is the structured per-run JSON record
    (config, timing, GFLOPs, utilization, device) that replaces printf.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import time
from typing import Any, Iterator

import jax


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler trace of the enclosed block."""
    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield


def annotate(name: str):
    """Named region on the profiler timeline (and in HLO op names)."""
    return jax.named_scope(name)


@dataclasses.dataclass
class RunRecord:
    """One benchmark run, JSON-serializable."""

    config: str
    backend: str
    m: int
    n: int
    dk: int
    dv: int
    dtype: str
    best_us: float
    median_us: float
    gflops_per_chip: float
    utilization: float
    device_kind: str
    n_devices: int
    mesh_axes: dict[str, int] | None = None
    extra: dict[str, Any] | None = None
    timestamp: float = dataclasses.field(default_factory=time.time)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))


def append_jsonl(path: str, record: RunRecord) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a") as f:
        f.write(record.to_json() + "\n")


def _latest_capture(log_dir: str) -> str | None:
    """Newest ``.trace.json.gz`` under a ``trace(log_dir)`` capture,
    by mtime.  Capture directories are timestamp-named, but path sort
    order is NOT capture order across a rollover boundary (e.g.
    ``..._09_59`` sorts after ``..._10_01`` under some stamp formats),
    so recency must come from the filesystem, not the name."""
    import glob

    paths = glob.glob(f"{log_dir}/plugins/profile/*/*.trace.json.gz")
    if not paths:
        return None
    return max(paths, key=os.path.getmtime)


def device_module_slices(
    log_dir: str,
) -> list[tuple[str, float, float]] | None:
    """Per-slice device events from a ``trace(log_dir)`` capture.

    Parses the newest Chrome-trace export under ``log_dir`` and returns
    every complete event on the device "XLA Modules" lane as
    ``(module_name, ts_us, dur_us)`` tuples (trace-local clock), or
    None when no trace/device lane exists (e.g. CPU platforms).  The
    slice-level view feeds `obs.export.chrome_trace`'s merged timeline;
    :func:`device_module_seconds` aggregates it.
    """
    import gzip
    import json as _json

    path = _latest_capture(log_dir)
    if path is None:
        return None
    try:
        data = _json.load(gzip.open(path))
        lanes = {}
        for e in data["traceEvents"]:
            if e.get("ph") == "M" and e.get("name") == "thread_name":
                lanes[(e["pid"], e["tid"])] = e["args"]["name"]
        slices = [
            (e["name"].split("(")[0], float(e["ts"]), float(e["dur"]))
            for e in data["traceEvents"]
            if (e.get("ph") == "X"
                and lanes.get((e.get("pid"), e.get("tid")))
                == "XLA Modules")
        ]
    except (ValueError, KeyError, EOFError, OSError):
        # a truncated/partial capture (interrupted profiler) must read
        # as "no device lane" so benchmark_auto's slope fallback engages
        # rather than aborting the whole benchmark
        return None
    return slices or None


def device_module_seconds(log_dir: str) -> dict[str, float] | None:
    """Per-module device seconds from a ``trace(log_dir)`` capture.

    Sums the duration of each module on the device "XLA Modules" lane
    of the newest capture.  Returns ``{module_name: seconds}``, or None
    when no trace/device lane exists — the shared parser for every
    device-time clock (`utils.timing.benchmark_traced`,
    `scripts/speculative_bench.py`).
    """
    slices = device_module_slices(log_dir)
    if slices is None:
        return None
    per_module: dict[str, float] = {}
    for key, _, dur_us in slices:
        per_module[key] = per_module.get(key, 0.0) + dur_us / 1e6
    return per_module or None
