"""Wall-clock benchmarking with the reference's timing discipline.

The reference times the slowest rank (`MPI_Wtime` + `MPI_Reduce(MAX)`,
`attention-mpi.c:519-528`) and reports minimum-over-repeats execution time
(weak_scalability.png).  Under JAX's single-controller model a
``block_until_ready`` fence already waits for the slowest chip, so
"max over ranks" is implicit; we keep the min-over-repeats convention and
also report the median.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

import jax


@dataclasses.dataclass
class Timing:
    times_s: list[float]

    @property
    def best_s(self) -> float:  # min-over-repeats, the reference's metric
        return min(self.times_s)

    @property
    def median_s(self) -> float:
        s = sorted(self.times_s)
        return s[len(s) // 2]

    @property
    def best_us(self) -> float:
        return self.best_s * 1e6


def benchmark(
    fn: Callable,
    *args,
    repeats: int = 5,
    warmup: int = 2,
    **kwargs,
) -> Timing:
    """Time ``fn(*args)`` with compile warmup and device fencing.

    Warmup runs absorb jit compilation (first TPU compile is tens of
    seconds); each timed run fences with ``block_until_ready`` so the
    measurement covers every chip's work — the `MPI_Reduce(MAX)` analog.
    """
    for _ in range(warmup):
        jax.block_until_ready(fn(*args, **kwargs))
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    return Timing(times_s=times)
