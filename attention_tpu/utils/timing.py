"""Wall-clock benchmarking with the reference's timing discipline.

The reference times the slowest rank (`MPI_Wtime` + `MPI_Reduce(MAX)`,
`attention-mpi.c:519-528`) and reports minimum-over-repeats execution time
(weak_scalability.png).  Under JAX's single-controller model a
``block_until_ready`` fence already waits for the slowest chip, so
"max over ranks" is implicit; we keep the min-over-repeats convention and
also report the median.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

import jax


@dataclasses.dataclass
class Timing:
    times_s: list[float]

    @property
    def best_s(self) -> float:  # min-over-repeats, the reference's metric
        return min(self.times_s)

    @property
    def median_s(self) -> float:
        s = sorted(self.times_s)
        return s[len(s) // 2]

    @property
    def best_us(self) -> float:
        return self.best_s * 1e6


def benchmark(
    fn: Callable,
    *args,
    repeats: int = 5,
    warmup: int = 2,
    **kwargs,
) -> Timing:
    """Time ``fn(*args)`` with compile warmup and device fencing.

    Warmup runs absorb jit compilation (first TPU compile is tens of
    seconds); each timed run fences with ``block_until_ready`` so the
    measurement covers every chip's work — the `MPI_Reduce(MAX)` analog.
    """
    for _ in range(warmup):
        jax.block_until_ready(fn(*args, **kwargs))
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    return Timing(times_s=times)


def _tunnel_transport() -> bool:
    """True when devices sit behind a remote tunnel (axon) whose
    ``block_until_ready`` completes before pallas kernels finish.

    Positive detection only: the axon plugin registers itself as platform
    'tpu', so we sniff its PJRT version string (and the JAX_PLATFORMS
    env as a fallback) rather than exclude known-direct platforms.
    """
    import os

    try:
        version = getattr(jax.devices()[0].client, "platform_version", "")
    except Exception:  # noqa: BLE001 - no devices -> no tunnel
        return False
    return "axon" in (version or "").lower() or "axon" in os.environ.get(
        "JAX_PLATFORMS", ""
    )


def benchmark_attention(fn, q, k, v, *, repeats: int = 5, warmup: int = 2,
                        **kwargs) -> Timing:
    """Time an attention call with the honest clock for the transport.

    On direct backends (cpu/gpu/tpu) this is plain fence timing
    (:func:`benchmark`).  On tunnel transports the fence lies, so the
    call is timed by the chained-scan clock instead
    (:func:`benchmark_auto`: device-trace time preferred — wall-clock
    slope drowns in tens-of-ms tunnel variance for sub-ms ops, observed
    reporting a 45 us flash call as 4.4 ms — with the slope as
    fallback), chaining each iteration's output back into the next Q
    (sliced/zero-padded when dv != dk — the iterated values are
    garbage, but the per-iteration work is identical); the returned
    ``Timing`` then holds the single per-iteration estimate.
    """
    if not _tunnel_transport():
        return benchmark(fn, q, k, v, repeats=repeats, warmup=warmup, **kwargs)

    import jax.numpy as jnp

    dk = q.shape[-1]

    def step(x, kk, vv):
        out = fn(x, kk, vv, **kwargs)
        dv = out.shape[-1]
        if dv > dk:
            out = out[..., :dk]
        elif dv < dk:
            out = jnp.pad(out, [(0, 0)] * (out.ndim - 1) + [(0, dk - dv)])
        return out

    per = benchmark_auto(step, q, repeats=max(2, repeats // 2),
                         operands=(k, v))
    return Timing(times_s=[per])


def _chained_scan(fn):
    """Jitted n-fold application of ``fn`` with a data dependency.

    Shared builder for the two chained clocks (:func:`benchmark_amortized`,
    :func:`benchmark_traced`): each iteration consumes the previous
    output (cast back to the input dtype), and the return value is one
    scalar so fetching it cannot be transfer-dominated.  Big side inputs
    must come through ``ops`` — closure-captured arrays become jaxpr
    constants and make lowering take minutes at hundreds of MB.
    """
    import functools

    import jax.numpy as jnp
    from jax import lax

    @functools.partial(jax.jit, static_argnums=2)
    def chained(x0, ops, n):
        def body(carry, _):
            return fn(carry, *ops).astype(x0.dtype), None

        out, _ = lax.scan(body, x0, None, length=n)
        return jnp.sum(out.astype(jnp.float32))

    return chained


def benchmark_amortized(
    fn: Callable,
    x,
    *,
    repeats: int = 3,
    n_short: int = 4,
    n_long: int = 20,
    operands: tuple = (),
    _chained=None,
) -> float:
    """Per-iteration seconds of ``fn`` via scan-chained slope timing.

    Remote-tunnel device transports (axon) may complete a
    ``block_until_ready`` fence before a pallas call has actually run, and
    fetching the full output is dominated by tunnel transfer time.  This
    measures honestly: chain ``n`` applications of ``fn`` inside one jit
    with a data dependency (each iteration consumes the previous output),
    fetch ONE scalar, and take the slope (t_long - t_short)/(n_long -
    n_short) — fixed tunnel latency cancels.

    ``fn`` maps ``(x, *operands)`` to an array of x's shape; its output
    is cast back to ``x.dtype`` between iterations.  Pass big side
    inputs (K/V, caches) via ``operands``, NOT closure: closure-captured
    arrays are flattened into the jaxpr as constants, and at
    hundreds-of-MB that makes lowering/compilation take minutes.
    """
    chained = _chained if _chained is not None else _chained_scan(fn)
    jax.device_get(chained(x, operands, n_short))  # compile both lengths
    jax.device_get(chained(x, operands, n_long))
    slopes, longs = [], []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.device_get(chained(x, operands, n_short))
        t_short = time.perf_counter() - t0
        t0 = time.perf_counter()
        jax.device_get(chained(x, operands, n_long))
        t_long = time.perf_counter() - t0
        # Slope per back-to-back pair: the shared chip's contention
        # varies a lot between windows, and mixing a min(short) from one
        # window with a min(long) from another biases the difference —
        # observed producing impossible >100%-of-peak rates.  Each pair
        # sees similar conditions; the median across pairs is robust.
        slopes.append((t_long - t_short) / (n_long - n_short))
        longs.append(t_long)
    import statistics

    slope = statistics.median(slopes)
    if slope <= 0:
        # Timer noise swamped the slope (per-iteration cost << dispatch
        # jitter).  Fall back to the amortized upper bound — still honest,
        # just conservative: fixed overhead is charged to the iterations.
        slope = statistics.median(longs) / n_long
    return slope


def benchmark_traced(
    fn: Callable,
    x,
    *,
    n: int = 20,
    operands: tuple = (),
    repeats: int = 3,
    _chained=None,
) -> float | None:
    """Per-iteration seconds from DEVICE-side profiler time, or None.

    Chains ``n`` applications of ``fn`` (same contract as
    :func:`benchmark_amortized`), captures a ``jax.profiler`` trace, and
    sums the trace's "XLA Modules" device lane (shared parser:
    `utils.profiling.device_module_seconds`).  Device module time is
    deterministic on the shared chip (measured identical to the decimal
    across repeats) where wall-clock sways with tunnel latency and
    contention — so this is the preferred clock when a device trace is
    available.  Returns the median over ``repeats`` captures, or None
    when the platform's profiler exports no device lane (e.g. CPU);
    callers fall back to :func:`benchmark_amortized`.
    """
    import shutil
    import statistics
    import tempfile

    from attention_tpu.utils.profiling import device_module_seconds

    chained = _chained if _chained is not None else _chained_scan(fn)
    jax.device_get(chained(x, operands, n))  # compile + warm

    def one_capture(log_dir) -> float | None:
        shutil.rmtree(log_dir, ignore_errors=True)
        with jax.profiler.trace(log_dir):
            jax.device_get(chained(x, operands, n))
        mods = device_module_seconds(log_dir)
        if not mods:
            return None
        # the chained scan dominates; stray scalar modules (the sum
        # fetch) are orders of magnitude smaller
        return max(mods.values()) / n

    with tempfile.TemporaryDirectory(prefix="bench_trace_") as td:
        samples = []
        for i in range(repeats):
            sec = one_capture(f"{td}/{i}")
            if sec is None:
                return None
            samples.append(sec)
    return statistics.median(samples)


def benchmark_candidate(
    fn: Callable,
    x,
    *,
    operands: tuple = (),
    repeats: int = 3,
) -> float:
    """Per-iteration seconds for one AUTOTUNE candidate.

    The tuner's clock (`attention_tpu.tuning.search`): same honest
    chained-scan measurement as :func:`benchmark_auto` (device-trace
    preferred, wall-clock slope fallback — median-of-``repeats`` either
    way), with deliberately short chains (2/8 vs the bench default
    4/20): a sweep compiles and times a dozen candidates per shape, so
    per-candidate wall time matters more than squeezing the last few
    percent of clock variance — rank order between tiles is far coarser
    than the short-chain noise floor.
    """
    return benchmark_auto(fn, x, operands=operands, repeats=repeats,
                          n_short=2, n_long=8)


def benchmark_auto(
    fn: Callable,
    x,
    *,
    operands: tuple = (),
    repeats: int = 3,
    n_short: int = 4,
    n_long: int = 20,
) -> float:
    """Per-iteration seconds via the best available clock.

    Builds the chained-scan program ONCE, tries the deterministic
    device-trace clock, and falls back to the wall-clock slope on the
    same compiled function when no device lane exists — so fallback
    platforms pay a single compile, not two.
    """
    chained = _chained_scan(fn)
    traced = benchmark_traced(fn, x, n=n_long, operands=operands,
                              repeats=max(1, repeats), _chained=chained)
    if traced is not None:
        return traced
    return benchmark_amortized(fn, x, repeats=repeats, n_short=n_short,
                               n_long=n_long, operands=operands,
                               _chained=chained)
