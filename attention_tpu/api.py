"""Public attention API and backend registry.

The reference exposes one function compiled two ways — serial
(`attention.c:20-21`) vs MPI-distributed (`attention-mpi.c:191-192`),
selected by which binary you build.  Here the same split is a runtime
backend registry:

  * ``oracle``     — fp64 NumPy serial oracle (the `attention.c` role).
  * ``native``     — compiled-C fp64 serial oracle (ctypes, csrc/) — the
                     native CPU baseline.
  * ``xla``        — un-fused JAX implementation, XLA-scheduled.
  * ``flash``      — fused single-device Pallas flash kernel.
  * ``kv-sharded`` — KV rows sharded over a device mesh, two-phase
                     pmax/psum softmax (the `attention-mpi.c` role).
  * ``q-sharded``  — Q rows sharded, KV replicated (the zero-collective
                     small-KV arm of the adaptive placement policy).
  * ``ring``       — ring attention (Q and KV both sharded; KV rotates
                     over the ICI ring) for long context.
  * ``ulysses``    — all-to-all head/sequence reshard for multi-head runs.
  * ``auto``       — picks q-sharded vs kv-sharded by KV size, the
                     reference's adaptive 64 MB Bcast/Scatterv policy
                     (`attention-mpi.c:210-266`).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

_BACKENDS: dict[str, Callable[..., Any]] = {}
_BUILTINS_LOADED = False


def register_backend(name: str):
    def deco(fn):
        _BACKENDS[name] = fn
        return fn

    return deco


def available_backends() -> list[str]:
    _ensure_registered()
    return sorted(_BACKENDS)


def _ensure_registered() -> None:
    global _BUILTINS_LOADED
    if _BUILTINS_LOADED:
        return
    _BUILTINS_LOADED = True
    from attention_tpu.core.oracle import attention_oracle
    from attention_tpu.ops.flash import flash_attention
    from attention_tpu.ops.reference import attention_xla

    _BACKENDS["oracle"] = lambda q, k, v, **kw: attention_oracle(q, k, v, **kw)
    _BACKENDS["xla"] = attention_xla
    _BACKENDS["flash"] = flash_attention

    def _native(q, k, v, **kw):
        from attention_tpu.core.native import attention_native

        return attention_native(q, k, v, **kw)

    _BACKENDS["native"] = _native

    def _kv_sharded(q, k, v, **kw):
        from attention_tpu.parallel.kv_sharded import kv_sharded_attention

        return kv_sharded_attention(q, k, v, **kw)

    def _ring(q, k, v, **kw):
        from attention_tpu.parallel.ring import ring_attention

        return ring_attention(q, k, v, **kw)

    def _ulysses(q, k, v, **kw):
        from attention_tpu.parallel.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, **kw)

    def _q_sharded(q, k, v, **kw):
        from attention_tpu.parallel.kv_sharded import q_sharded_attention

        return q_sharded_attention(q, k, v, **kw)

    def _auto(q, k, v, threshold_bytes=None, **kw):
        # The adaptive distribution policy (attention-mpi.c:210-266): small
        # KV -> replicate KV / shard Q (zero per-batch collectives); large
        # KV -> shard KV rows + two-phase softmax collectives.  Round 5:
        # with the full call shape in hand the decision is the measured
        # byte-ratio model (`choose_kv_placement` with m) — an explicit
        # ``threshold_bytes`` forces the legacy bytes-only comparison
        # (escape hatch + test hook).
        from attention_tpu.parallel.kv_sharded import (
            kv_sharded_attention,
            q_sharded_attention,
        )
        from attention_tpu.parallel.mesh import choose_kv_placement

        n, dk = k.shape[-2], k.shape[-1]
        dv = v.shape[-1]
        kv_heads = 1
        for dim in k.shape[:-2]:
            kv_heads *= dim
        q_heads = 1
        for dim in q.shape[:-2]:
            q_heads *= dim
        if threshold_bytes is not None:
            placement = choose_kv_placement(
                n, dk, dv, itemsize=k.dtype.itemsize,
                kv_heads=kv_heads, threshold_bytes=threshold_bytes,
            )
        else:
            placement = choose_kv_placement(
                n, dk, dv, itemsize=k.dtype.itemsize,
                kv_heads=kv_heads, m=q.shape[-2], q_heads=q_heads,
            )
        if placement == "replicate":
            kw.pop("impl", None)  # q-sharded is always the fused kernel
            return q_sharded_attention(q, k, v, **kw)
        return kv_sharded_attention(q, k, v, **kw)

    _BACKENDS["kv-sharded"] = _kv_sharded
    _BACKENDS["q-sharded"] = _q_sharded
    _BACKENDS["ring"] = _ring
    _BACKENDS["ulysses"] = _ulysses
    _BACKENDS["auto"] = _auto

    def _chaos_broken(q, k, v, **kw):
        # The chaos subsystem's known-bad backend: the oracle plus the
        # fuzzer's synthetic defect (one element pushed past every
        # tolerance budget).  Exists so a shrunk `.bin` repro replays
        # to the same Wrong! verdict through the frozen `cli run`
        # harness — the fuzz->shrink->replay pipeline's ground truth.
        from attention_tpu.chaos.fuzzer import synthetic_defect

        return synthetic_defect(attention_oracle(q, k, v, **kw))

    _BACKENDS["chaos-broken"] = _chaos_broken


def attention(
    q,
    k,
    v,
    *,
    backend: str = "flash",
    **kwargs,
) -> np.ndarray:
    """Compute softmax(Q K^T / sqrt(dk)) V with the named backend.

    Mirrors the reference's `attention(Q, K, V, result, m, n, dk, dv)`
    entry point (`attention.c:20-21`) — shapes are carried by the arrays,
    and the output is returned rather than written into a caller buffer.
    """
    _ensure_registered()
    try:
        fn = _BACKENDS[backend]
    except KeyError:
        raise ValueError(
            f"unknown backend {backend!r}; available: {available_backends()}"
        ) from None
    return fn(q, k, v, **kwargs)
