"""Symbolic shape lattice + abstract interpreter (ATP901).

The Pallas passes (ATP201-204) and the runtime ``MeshConfigError``
guards bracket the shape story from two ends: literals are linted,
everything else is caught when a kernel traces on real hardware.  This
module fills the middle — a small abstract domain over array shapes
that is *sound for firing*: a finding is emitted only when a violation
is provable from the source (concrete values disagree after constant
propagation), and anything uncertain stays silent.  The lattice:

- **Dim** — ``coeff * prod(sym_i ** p_i)``: a concrete int when it has
  no symbols, else an opaque-but-fixed product.  Symbols are minted
  deterministically from parameter names and ``x.shape[i]`` reads, so
  the same quantity read twice unifies, and two *different* quantities
  can never be forced equal (collisions only ever silence, never fire).
- **Shape** — a tuple of Dims, or ``None`` (unknown rank).
- **Facts** — divisibility pairs ``a % b == 0`` harvested from
  ``assert x % y == 0`` statements, ``if x % y: raise`` guards (incl.
  ``or``-chained clauses, the ``ops/flash.py`` idiom), and NamedTuple
  field defaults (``BlockSizes().block_q`` is 256 by constant
  propagation through the constructor).  Facts only ever *certify* —
  they silence a divisibility demand, they never fire one.

Interpretation is per lexical scope (module, function, nested
function), in source order, with bindings recorded per line so a use
site sees exactly the bindings that dominate it: re-bindings inside
conditionals or loops poison the name (become unknown) instead of
guessing which branch ran.  Scope environments are memoized per scope
node and shared with the Pallas (ATP902) and sharding (ATP903-906)
passes; in-tree calls are summarized per ``(callee, arg shapes)`` with
a depth cap, mirroring ``dataflow.py``.

ATP901 fires on dot/concat/where operand shapes that are provably
inconsistent under the fact base — both sides concrete and unequal
(and, for broadcasts, neither side 1).
"""

from __future__ import annotations

import ast
import dataclasses

from attention_tpu.analysis.core import (
    Finding,
    Severity,
    dotted_name,
    file_pass,
    register_code,
    scope_list,
    walk_list,
)

ATP901 = register_code(
    "ATP901", "provable-shape-mismatch", Severity.ERROR,
    "dot/concat/where operand shapes are provably inconsistent under "
    "the symbolic fact base (concrete dims disagree)")

#: interprocedural summary depth cap (call edges followed per query)
_SUMMARY_DEPTH = 2

#: import roots treated as array-library modules when the name has no
#: local value binding (``jnp.reshape(x, s)`` vs ``x.reshape(s)``)
_MODULE_ROOTS = {"jnp", "np", "numpy", "lax", "jax", "math"}


# -- the Dim lattice -------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Dim:
    """``coeff * prod(sym**pow)``; concrete iff ``syms`` is empty."""

    coeff: int = 1
    syms: tuple[tuple[str, int], ...] = ()

    @property
    def concrete(self) -> bool:
        return not self.syms

    def __repr__(self) -> str:
        if self.concrete:
            return str(self.coeff)
        body = "*".join(s.rsplit(":", 1)[-1] if p == 1
                        else f"{s.rsplit(':', 1)[-1]}^{p}"
                        for s, p in self.syms)
        return body if self.coeff == 1 else f"{self.coeff}*{body}"


def con(n: int) -> Dim:
    return Dim(n, ())


def sym(name: str) -> Dim:
    return Dim(1, ((name, 1),))


def dim_mul(a: Dim, b: Dim) -> Dim:
    pows: dict[str, int] = {}
    for s, p in a.syms + b.syms:
        pows[s] = pows.get(s, 0) + p
    return Dim(a.coeff * b.coeff,
               tuple(sorted((s, p) for s, p in pows.items() if p)))


def dim_div(a: Dim, b: Dim) -> Dim | None:
    """Exact quotient ``a / b`` when structurally provable, else None."""
    if b.coeff == 0:
        return None
    pows = dict(a.syms)
    for s, p in b.syms:
        if pows.get(s, 0) < p:
            return None
        pows[s] -= p
    if a.coeff % b.coeff:
        return None
    return Dim(a.coeff // b.coeff,
               tuple(sorted((s, p) for s, p in pows.items() if p)))


class Facts:
    """A bag of proven divisibility pairs ``a % b == 0``.

    Facts only certify: :meth:`divisible` answers "provably divisible"
    — its False means *unknown*, never "provably not divisible".
    """

    def __init__(self, parent: "Facts | None" = None):
        self.parent = parent
        self.pairs: set[tuple[Dim, Dim]] = set()

    def add(self, a: Dim, b: Dim) -> None:
        self.pairs.add((a, b))

    def _iter_pairs(self):
        f: Facts | None = self
        while f is not None:
            yield from f.pairs
            f = f.parent

    def divisor_facts(self, a: Dim) -> list[Dim]:
        """Every divisor some fact proves for ``a``."""
        return [b for (x, b) in self._iter_pairs() if x == a]

    def divisible(self, a: Dim, b: Dim) -> bool:
        if b.concrete and b.coeff in (1, -1):
            return True
        if a == b:
            return True
        if a.concrete and b.concrete:
            return b.coeff != 0 and a.coeff % b.coeff == 0
        # structural containment: b*h % h == 0, 4h % 2h == 0
        if dim_div(a, b) is not None:
            return True
        # coefficient multiples: (8*n) % 4 == 0
        if b.concrete and b.coeff != 0 and a.coeff % b.coeff == 0:
            return True
        for (x, m) in self._iter_pairs():
            if x != a:
                continue
            if m == b:
                return True
            # a % 256 == 0 certifies a % 128 == 0
            if m.concrete and b.concrete and b.coeff != 0 \
                    and m.coeff % b.coeff == 0:
                return True
        return False


# -- scope environments ----------------------------------------------------

#: binding kinds: the value slot holds a Shape / Dim / tuple[Dim|None]
#: / dict[field -> Dim] respectively; a ``None`` value is poison
_ARRAY, _DIM, _TUPLE, _RECORD = "arr", "dim", "tup", "rec"


class ScopeEnv:
    """Per-line bindings for one lexical scope.

    ``bindings[name]`` is a source-ordered list of ``(lineno, kind,
    value)``; a lookup at line L returns the last entry strictly before
    L, so a use site only ever sees bindings that dominate it.  Entries
    recorded from conditional/loop bodies carry ``value=None`` (poison)
    unless the name was previously unbound or re-bound to the same
    value.
    """

    def __init__(self, scope: ast.AST, key: str,
                 parent: "ScopeEnv | None"):
        self.scope = scope
        self.key = key
        self.parent = parent
        self.bindings: dict[str, list[tuple[int, str, object]]] = {}
        self.params: set[str] = set()
        self.facts = Facts(parent.facts if parent else None)

    # -- recording ---------------------------------------------------------

    def bind(self, name: str, lineno: int, kind: str, value,
             conditional: bool) -> None:
        lst = self.bindings.setdefault(name, [])
        if conditional and lst:
            _, pk, pv = lst[-1]
            if pk == kind and pv == value:
                return  # re-binding to the same value: keep it
            value = None
        lst.append((lineno, kind, value))

    def poison(self, name: str, lineno: int) -> None:
        self.bindings.setdefault(name, []).append((lineno, _ARRAY, None))

    # -- lookup ------------------------------------------------------------

    def _visible(self, name: str, line: int):
        lst = self.bindings.get(name)
        if lst is None:
            return None  # not a local — caller falls through to parent
        got = None
        for (ln, kind, value) in lst:
            if ln < line:
                got = (kind, value)
            else:
                break
        return got or ("unbound", None)

    def lookup(self, name: str, line: int):
        """(kind, value) | None; poisoned / not-yet-bound / unknown
        names are None."""
        got = self._visible(name, line)
        if got is not None:
            kind, value = got
            if kind == "unbound" or value is None:
                # a local that is not yet bound at this line (or is
                # poisoned) never falls through to an outer scope
                return None
            return got
        if self.parent is not None:
            return self.parent.lookup_closure(name)
        return None

    def lookup_closure(self, name: str):
        """A read from a nested scope: only trustworthy when the name
        has exactly one (non-poison) binding here — the closure may run
        between any two re-bindings."""
        lst = self.bindings.get(name)
        if lst is None:
            if self.parent is not None:
                return self.parent.lookup_closure(name)
            return None
        if len(lst) == 1 and lst[0][2] is not None:
            return (lst[0][1], lst[0][2])
        return None

    def name_state(self, name: str, line: int) -> str:
        """'value' (a local/param/outer binding holds a usable value),
        'opaque' (bound to something undecidable), or 'absent'."""
        if name in self.params:
            return "value"
        lst = self.bindings.get(name)
        if lst is not None:
            got = self._visible(name, line)
            if got and got[0] != "unbound" and got[1] is not None:
                return "value"
            return "opaque"
        if self.parent is not None:
            # ancestor scopes: closure rules
            e = self.parent
            while e is not None:
                if name in e.params:
                    return "value"
                lst = e.bindings.get(name)
                if lst is not None:
                    if len(lst) == 1 and lst[0][2] is not None:
                        return "value"
                    return "opaque"
                e = e.parent
        return "absent"


# -- record (NamedTuple) classes ------------------------------------------

def _namedtuple_fields(cls: ast.ClassDef) -> "dict[str, Dim | None] | None":
    """field -> default Dim (int defaults only) for a NamedTuple class,
    None when ``cls`` is not a NamedTuple."""
    if not any((dotted_name(b) or "").endswith("NamedTuple")
               for b in cls.bases):
        return None
    fields: dict[str, Dim | None] = {}
    for node in cls.body:
        if isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                          ast.Name):
            default = None
            if isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, int) \
                    and not isinstance(node.value.value, bool):
                default = con(node.value.value)
            fields[node.target.id] = default
    return fields or None


# -- the interpreter -------------------------------------------------------

_ELEMENTWISE = {
    "exp", "exp2", "log", "log2", "sqrt", "rsqrt", "tanh", "abs",
    "negative", "sign", "relu", "sigmoid", "softmax", "astype",
    "asarray", "stop_gradient", "logistic", "copy",
}
_SHAPE_LIKE = {"zeros_like", "ones_like", "full_like", "empty_like"}
_SHAPE_CTOR = {"zeros", "ones", "empty"}
_REDUCERS = {"sum", "mean", "prod", "max", "min", "amax", "amin",
             "all", "any", "argmax", "argmin"}
#: call leaves whose presence makes a scope worth checking
TRIGGER_LEAVES = {"dot", "dot_general", "matmul", "einsum",
                  "concatenate", "stack", "where"}


class ShapeInterp:
    """Shape/dim abstract interpretation over one parsed module."""

    def __init__(self, path: str, tree: ast.Module, index=None):
        self.path = path
        self.tree = tree
        self.index = index
        self._envs: dict[int, ScopeEnv] = {}
        self._parents: dict[int, ast.AST] = {}
        self._records: dict[str, dict | None] = {}
        self._summaries: dict = {}
        self._local_records: dict[str, ast.ClassDef] = {}
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self._local_records[node.name] = node
        # walrus targets are rare; one module-wide check lets every
        # env build skip the nested-def walk in _poison_walruses
        self._has_walrus = any(isinstance(n, ast.NamedExpr)
                               for n in walk_list(tree))
        # DFS parent map: each def's nearest enclosing *function* scope
        # (class bodies are not closure scopes); defs are statements, so
        # only statement bodies need walking
        todo: list[tuple[list, ast.AST]] = [(tree.body, tree)]
        while todo:
            stmts, owner = todo.pop()
            for s in stmts:
                if isinstance(s, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                    self._parents[id(s)] = owner
                    todo.append((s.body, s))
                elif isinstance(s, ast.ClassDef):
                    todo.append((s.body, owner))
                else:
                    d = s.__dict__
                    for fld in ("body", "orelse", "finalbody"):
                        sub = d.get(fld)
                        if sub:
                            todo.append((sub, owner))
                    for h in d.get("handlers") or ():
                        todo.append((h.body, owner))
                    for c in d.get("cases") or ():
                        todo.append((c.body, owner))

    def scopes(self) -> list[ast.AST]:
        out: list[ast.AST] = [self.tree]
        out.extend(n for n in walk_list(self.tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)))
        return out

    # -- env construction --------------------------------------------------

    def env(self, scope: ast.AST) -> ScopeEnv:
        got = self._envs.get(id(scope))
        if got is not None:
            return got
        if isinstance(scope, ast.Module):
            parent = None
            key = f"{self.path}::<module>"
        else:
            parent = self.env(self._parents[id(scope)])
            key = f"{self.path}::{scope.name}@{scope.lineno}"
        env = ScopeEnv(scope, key, parent)
        self._envs[id(scope)] = env
        if not isinstance(scope, ast.Module):
            a = scope.args
            for p in a.posonlyargs + a.args + a.kwonlyargs:
                env.params.add(p.arg)
            for p in (a.vararg, a.kwarg):
                if p:
                    env.params.add(p.arg)
        self._exec_block(scope.body, env, conditional=False)
        self._poison_walruses(scope, env)
        for lst in env.bindings.values():
            lst.sort(key=_by_line)
        return env

    def _exec_block(self, stmts, env: ScopeEnv, conditional: bool,
                    in_loop: bool = False) -> None:
        for stmt in stmts:
            self._exec_stmt(stmt, env, conditional, in_loop)

    def _exec_stmt(self, stmt: ast.stmt, env: ScopeEnv,
                   conditional: bool, in_loop: bool) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            env.poison(stmt.name, stmt.lineno)
            return
        if isinstance(stmt, (ast.Global, ast.Nonlocal)):
            for n in stmt.names:
                env.poison(n, stmt.lineno)
            return
        if isinstance(stmt, (ast.Import, ast.ImportFrom)):
            return  # import aliases stay 'absent' — resolved lexically
        if isinstance(stmt, ast.Assign):
            self._exec_assign(stmt.targets, stmt.value, stmt.lineno, env,
                              conditional or in_loop)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._exec_assign([stmt.target], stmt.value, stmt.lineno, env,
                              conditional or in_loop)
        elif isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Name):
                env.poison(stmt.target.id, stmt.lineno)
        elif isinstance(stmt, ast.Assert):
            self._harvest_assert(stmt, env)
        elif isinstance(stmt, ast.If):
            self._harvest_guard(stmt, env)
            self._exec_block(stmt.body, env, True, in_loop)
            self._exec_block(stmt.orelse, env, True, in_loop)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._poison_target(stmt.target, stmt.lineno, env)
            self._exec_block(stmt.body, env, True, True)
            self._exec_block(stmt.orelse, env, True, True)
        elif isinstance(stmt, ast.While):
            self._exec_block(stmt.body, env, True, True)
            self._exec_block(stmt.orelse, env, True, True)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    self._poison_target(item.optional_vars, stmt.lineno,
                                        env)
            self._exec_block(stmt.body, env, conditional, in_loop)
        elif isinstance(stmt, ast.Try):
            self._exec_block(stmt.body, env, True, in_loop)
            for h in stmt.handlers:
                if h.name:
                    env.poison(h.name, h.lineno)
                self._exec_block(h.body, env, True, in_loop)
            self._exec_block(stmt.orelse, env, True, in_loop)
            self._exec_block(stmt.finalbody, env, conditional, in_loop)
        elif isinstance(stmt, ast.Match):
            for case in stmt.cases:
                for n in ast.walk(case.pattern):
                    if isinstance(n, ast.MatchAs) and n.name:
                        env.poison(n.name, stmt.lineno)
                self._exec_block(case.body, env, True, in_loop)

    def _poison_walruses(self, scope: ast.AST, env: ScopeEnv) -> None:
        """Walrus targets become poison; binding lists are re-sorted by
        line afterwards, so out-of-order appends are fine.  Nested defs
        and lambdas are walked whole — walruses in their default args
        (and lambda bodies) bind in THIS scope, and over-poisoning from
        their inner walruses only ever silences."""
        if not self._has_walrus:
            return
        for n in _scope_nodes(scope):
            if isinstance(n, ast.NamedExpr):
                if isinstance(n.target, ast.Name):
                    env.poison(n.target.id, n.lineno)
            elif isinstance(n, (ast.Lambda, ast.FunctionDef,
                                ast.AsyncFunctionDef)):
                for m in ast.walk(n):
                    if isinstance(m, ast.NamedExpr) \
                            and isinstance(m.target, ast.Name):
                        env.poison(m.target.id, m.lineno)

    def _poison_target(self, tgt: ast.expr, lineno: int,
                       env: ScopeEnv) -> None:
        for n in ast.walk(tgt):
            if isinstance(n, ast.Name):
                env.poison(n.id, lineno)

    def _exec_assign(self, targets, value: ast.expr, lineno: int,
                     env: ScopeEnv, conditional: bool) -> None:
        line = lineno + 1  # RHS sees bindings up to (and on) this line
        if len(targets) == 1 and isinstance(targets[0],
                                            (ast.Tuple, ast.List)):
            self._exec_unpack(targets[0], value, lineno, env, conditional)
            return
        for tgt in targets:
            if not isinstance(tgt, ast.Name):
                continue  # attribute/subscript stores: out of scope
            dim = self._dim_of(value, env, line, _SUMMARY_DEPTH)
            if dim is not None:
                env.bind(tgt.id, lineno, _DIM, dim, conditional)
                continue
            shape = self._shape_of(value, env, line, _SUMMARY_DEPTH)
            if shape is not None:
                env.bind(tgt.id, lineno, _ARRAY, shape, conditional)
                continue
            tup = self._tuple_of(value, env, line)
            if tup is not None:
                env.bind(tgt.id, lineno, _TUPLE, tup, conditional)
                continue
            rec = self._record_of(value, env, line)
            if rec is not None:
                env.bind(tgt.id, lineno, _RECORD, rec, conditional)
                continue
            env.poison(tgt.id, lineno)

    def _exec_unpack(self, tgt, value: ast.expr, lineno: int,
                     env: ScopeEnv, conditional: bool) -> None:
        """``b, h, d = x.shape`` binds the dims AND back-fills x's
        rank; other unpacks poison their names."""
        if any(isinstance(e, ast.Starred) for e in tgt.elts):
            for e in tgt.elts:
                self._poison_target(e, lineno, env)
            return
        names = [e.id if isinstance(e, ast.Name) else None
                 for e in tgt.elts]
        line = lineno + 1
        dims = self._shape_value_of(value, env, line, len(names))
        if dims is not None:
            for name, d in zip(names, dims):
                if name is not None:
                    env.bind(name, lineno, _DIM, d, conditional)
            root = self._shape_root(value)
            if root is not None and env.lookup(root, line) is None:
                env.bind(root, lineno, _ARRAY, tuple(dims), conditional)
            return
        tup = self._tuple_of(value, env, line)
        if tup is not None and len(tup) == len(names):
            for name, d in zip(names, tup):
                if name is None:
                    continue
                if d is not None:
                    env.bind(name, lineno, _DIM, d, conditional)
                else:
                    env.poison(name, lineno)
            return
        for name in names:
            if name is not None:
                env.poison(name, lineno)

    @staticmethod
    def _shape_root(value: ast.expr) -> str | None:
        if isinstance(value, ast.Attribute) and value.attr == "shape" \
                and isinstance(value.value, ast.Name):
            return value.value.id
        return None

    def _shape_value_of(self, value: ast.expr, env: ScopeEnv, line: int,
                        arity: int) -> "list[Dim] | None":
        """Dims of an ``x.shape`` expression: the known shape, or fresh
        symbols at the arity the unpack announces."""
        if not (isinstance(value, ast.Attribute)
                and value.attr == "shape"):
            return None
        base = dotted_name(value.value)
        if base is None:
            return None
        shape = self._shape_of(value.value, env, line, 0)
        if shape is not None:
            return list(shape) if len(shape) == arity else None
        return [sym(f"{env.key}:{base}.s{i}") for i in range(arity)]

    # -- fact harvesting ---------------------------------------------------

    def _harvest_assert(self, stmt: ast.Assert, env: ScopeEnv) -> None:
        line = stmt.lineno + 1
        test = stmt.test
        # assert x % y == 0   /  assert not x % y
        if isinstance(test, ast.Compare) and len(test.ops) == 1 \
                and isinstance(test.ops[0], ast.Eq) \
                and isinstance(test.comparators[0], ast.Constant) \
                and test.comparators[0].value == 0:
            self._add_mod_fact(test.left, env, line)
        elif isinstance(test, ast.UnaryOp) \
                and isinstance(test.op, ast.Not):
            self._add_mod_fact(test.operand, env, line)

    def _harvest_guard(self, stmt: ast.If, env: ScopeEnv) -> None:
        """``if x % y [!= 0] [or ...]: raise`` proves x % y == 0 on the
        fall-through path; harvested whenever the body raises."""
        if not any(isinstance(s, ast.Raise) for s in stmt.body):
            return
        line = stmt.lineno + 1
        clauses = (stmt.test.values
                   if isinstance(stmt.test, ast.BoolOp)
                   and isinstance(stmt.test.op, ast.Or)
                   else [stmt.test])
        for clause in clauses:
            if isinstance(clause, ast.Compare) and len(clause.ops) == 1 \
                    and isinstance(clause.ops[0], ast.NotEq) \
                    and isinstance(clause.comparators[0], ast.Constant) \
                    and clause.comparators[0].value == 0:
                clause = clause.left
            self._add_mod_fact(clause, env, line)

    def _add_mod_fact(self, expr: ast.expr, env: ScopeEnv,
                      line: int) -> None:
        if not (isinstance(expr, ast.BinOp)
                and isinstance(expr.op, ast.Mod)):
            return
        a = self._dim_of(expr.left, env, line, 0)
        b = self._dim_of(expr.right, env, line, 0)
        if a is not None and b is not None:
            env.facts.add(a, b)

    # -- expression evaluation: dims --------------------------------------

    def _dim_of(self, node: ast.expr, env: ScopeEnv, line: int,
                depth: int) -> Dim | None:
        """The expression as an int-valued Dim, or None."""
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool) \
                    or not isinstance(node.value, int):
                return None
            return con(node.value)
        if isinstance(node, ast.Name):
            got = env.lookup(node.id, line)
            if got is not None:
                kind, value = got
                return value if kind == _DIM else None
            if node.id in env.params:
                return sym(f"{env.key}:{node.id}")
            return None
        if isinstance(node, ast.UnaryOp) and isinstance(node.op,
                                                        ast.USub):
            d = self._dim_of(node.operand, env, line, depth)
            return Dim(-d.coeff, d.syms) if d is not None else None
        if isinstance(node, ast.BinOp):
            a = self._dim_of(node.left, env, line, depth)
            b = self._dim_of(node.right, env, line, depth)
            if a is None or b is None:
                return None
            if isinstance(node.op, ast.Mult):
                return dim_mul(a, b)
            if isinstance(node.op, ast.FloorDiv):
                return dim_div(a, b)
            if a.concrete and b.concrete:
                if isinstance(node.op, ast.Add):
                    return con(a.coeff + b.coeff)
                if isinstance(node.op, ast.Sub):
                    return con(a.coeff - b.coeff)
                if isinstance(node.op, ast.Mod) and b.coeff:
                    return con(a.coeff % b.coeff)
                if isinstance(node.op, ast.Pow) and b.coeff >= 0:
                    return con(a.coeff ** b.coeff)
            return None
        if isinstance(node, ast.Subscript):
            return self._shape_elem(node, env, line)
        if isinstance(node, ast.Attribute):
            # record projection: bs.block_q
            if isinstance(node.value, ast.Name):
                got = env.lookup(node.value.id, line)
                if got is not None and got[0] == _RECORD:
                    return got[1].get(node.attr)
            return None
        return None

    def _shape_elem(self, node: ast.Subscript, env: ScopeEnv,
                    line: int) -> Dim | None:
        """``x.shape[i]`` / ``shp[i]`` with a literal index."""
        idx = node.slice
        if isinstance(idx, ast.UnaryOp) and isinstance(idx.op, ast.USub) \
                and isinstance(idx.operand, ast.Constant) \
                and isinstance(idx.operand.value, int):
            i = -idx.operand.value
        elif isinstance(idx, ast.Constant) and isinstance(idx.value, int):
            i = idx.value
        else:
            return None
        base = node.value
        if isinstance(base, ast.Attribute) and base.attr == "shape":
            root = dotted_name(base.value)
            shape = self._shape_of(base.value, env, line, 0)
            if shape is not None:
                return shape[i] if -len(shape) <= i < len(shape) else None
            if root is not None and i >= 0:
                return sym(f"{env.key}:{root}.s{i}")
            return None
        if isinstance(base, ast.Name):
            got = env.lookup(base.id, line)
            if got is not None and got[0] == _TUPLE:
                tup = got[1]
                if -len(tup) <= i < len(tup):
                    return tup[i]
        return None

    def _tuple_of(self, node: ast.expr, env: ScopeEnv,
                  line: int) -> "tuple[Dim | None, ...] | None":
        """A tuple-of-ints value (block shapes, grids): per-element
        Dims, with None holes for undecidable entries."""
        if isinstance(node, (ast.Tuple, ast.List)):
            return tuple(self._dim_of(e, env, line, 0) for e in node.elts)
        if isinstance(node, ast.Name):
            got = env.lookup(node.id, line)
            if got is not None and got[0] == _TUPLE:
                return got[1]
            return None
        if isinstance(node, ast.Attribute) and node.attr == "shape":
            shape = self._shape_of(node.value, env, line, 0)
            if shape is not None:
                return shape
        return None

    def _record_of(self, node: ast.expr, env: ScopeEnv,
                   line: int) -> "dict[str, Dim | None] | None":
        """``BlockSizes(block_q=bq)`` -> field map with defaults."""
        if not isinstance(node, ast.Call):
            return None
        fields = self._record_fields(dotted_name(node.func))
        if fields is None:
            return None
        rec = dict(fields)
        names = list(fields)
        for i, arg in enumerate(node.args):
            if i < len(names):
                rec[names[i]] = self._dim_of(arg, env, line, 0)
        for kw in node.keywords:
            if kw.arg in rec:
                rec[kw.arg] = self._dim_of(kw.value, env, line, 0)
        return rec

    def _record_fields(self, name: str | None):
        """NamedTuple field defaults for a constructor name, resolved
        locally or (with the index) across modules."""
        if not name:
            return None
        got = self._records.get(name, "miss")
        if got != "miss":
            return got
        fields = None
        cls = self._local_records.get(name) if "." not in name else None
        if cls is None and self.index is not None:
            mod = self.index.modules.get(self.path)
            if mod is not None:
                t = self.index._resolve_dotted_in(mod, name, 8)
                if t is not None and t[0] == "class":
                    cinfo = self.index.classes.get(t[1])
                    if cinfo is not None:
                        for node in self.index.modules[
                                cinfo.path].tree.body:
                            if isinstance(node, ast.ClassDef) \
                                    and node.name == cinfo.name:
                                cls = node
                                break
        if cls is not None:
            fields = _namedtuple_fields(cls)
        self._records[name] = fields
        return fields

    # -- call-form resolution ---------------------------------------------

    def _recv(self, call: ast.Call, env: ScopeEnv, line: int):
        """('method', base, rest_args) for ``x.f(...)`` on an in-scope
        value, ('module', base, rest_args) for ``jnp.f(x, ...)``, or
        (None, None, None) when the form is undecidable."""
        f = call.func
        if isinstance(f, ast.Name):
            if call.args:
                return ("module", call.args[0], call.args[1:])
            return (None, None, None)
        if not isinstance(f, ast.Attribute):
            return (None, None, None)
        base = f.value
        d = dotted_name(base)
        if d is None:
            # f(x).reshape(...): a value when its shape is derivable
            if self._shape_of(base, env, line, 0) is not None:
                return ("method", base, call.args)
            return (None, None, None)
        root = d.split(".")[0]
        state = env.name_state(root, line)
        if state == "value":
            return ("method", base, call.args)
        if state == "opaque":
            return (None, None, None)
        if root in _MODULE_ROOTS:
            if call.args:
                return ("module", call.args[0], call.args[1:])
            return (None, None, None)
        if self.index is not None:
            canon = self.index.canonical_name(self.path,
                                              d + "." + f.attr)
            if canon.split(".")[0] in ("jax", "numpy"):
                if call.args:
                    return ("module", call.args[0], call.args[1:])
        return (None, None, None)

    # -- shape transfer ----------------------------------------------------

    def _shape_of(self, node: ast.expr, env: ScopeEnv, line: int,
                  depth: int):
        if isinstance(node, ast.Name):
            got = env.lookup(node.id, line)
            if got is not None and got[0] == _ARRAY:
                return got[1]
            return None
        if isinstance(node, ast.Attribute):
            if node.attr == "T":
                s = self._shape_of(node.value, env, line, depth)
                return tuple(reversed(s)) if s is not None else None
            return None
        if isinstance(node, ast.BinOp):
            if isinstance(node.op, ast.MatMult):
                a = self._shape_of(node.left, env, line, depth)
                b = self._shape_of(node.right, env, line, depth)
                return self._check_dot(a, b, node, None, None)
            a = self._shape_of(node.left, env, line, depth)
            b = self._shape_of(node.right, env, line, depth)
            return _broadcast(a, b)
        if isinstance(node, ast.Subscript):
            # x[i]: a literal integer index drops the leading dim
            s = self._shape_of(node.value, env, line, depth)
            if s is not None and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, int) and len(s):
                return s[1:]
            return None
        if isinstance(node, ast.Call):
            return self._call_shape(node, env, line, depth)
        return None

    def _call_shape(self, call: ast.Call, env: ScopeEnv, line: int,
                    depth: int):
        f = call.func
        leaf = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if leaf is None:
            return None
        # module-form-only constructors
        if leaf in _SHAPE_CTOR or leaf == "full":
            arg = call.args[0] if call.args else None
            for kw in call.keywords:
                if kw.arg == "shape":
                    arg = kw.value
            if arg is None:
                return None
            tup = self._tuple_of(arg, env, line)
            if tup is not None and all(x is not None for x in tup):
                return tuple(tup)
            d0 = self._dim_of(arg, env, line, 0)
            return (d0,) if d0 is not None else None
        if leaf in _SHAPE_LIKE:
            if call.args:
                return self._shape_of(call.args[0], env, line, depth)
            return None
        if leaf in ("concatenate", "stack"):
            return self._concat_shape(call, env, line, depth, leaf,
                                      None, None)
        if leaf in ("dot", "matmul"):
            return self._dot_shape(call, env, line, depth, None, None)
        if leaf == "where":
            return self._where_shape(call, env, line, depth, None, None)
        if leaf == "einsum":
            return self._einsum_shape(call, env, line, depth, None, None)
        if leaf == "broadcast_to":
            form, base, rest = self._recv(call, env, line)
            if form is None or not rest:
                return None
            tup = self._tuple_of(rest[0], env, line)
            if tup is not None and all(x is not None for x in tup):
                return tuple(tup)
            return None
        if leaf == "reshape":
            form, base, rest = self._recv(call, env, line)
            if form is None:
                return None
            return self._reshape_dims(rest, call, env, line)
        if leaf in ("transpose", "swapaxes"):
            form, base, rest = self._recv(call, env, line)
            if form is None:
                return None
            return self._transpose_shape(base, rest, env, line, depth,
                                         leaf)
        if leaf in _ELEMENTWISE:
            if leaf == "astype" and isinstance(f, ast.Attribute):
                return self._shape_of(f.value, env, line, depth)
            form, base, rest = self._recv(call, env, line)
            if form is None or base is None:
                return None
            return self._shape_of(base, env, line, depth)
        if leaf in _REDUCERS:
            form, base, rest = self._recv(call, env, line)
            if form is None or base is None:
                return None
            return self._reduce_shape(base, rest, call, env, line, depth)
        if leaf in ("expand_dims", "squeeze"):
            form, base, rest = self._recv(call, env, line)
            if form is None or base is None:
                return None
            return self._axis_shape(base, rest, call, env, line, depth,
                                    leaf)
        # in-tree call: summarize the callee's return shape
        if depth > 0 and self.index is not None:
            return self._summary_shape(call, env, line, depth)
        return None

    def _reshape_dims(self, rest, call, env: ScopeEnv, line: int):
        if len(rest) == 1 and not (
                isinstance(rest[0], ast.Constant)
                or (isinstance(rest[0], ast.UnaryOp))):
            tup = self._tuple_of(rest[0], env, line)
            if tup is None:
                d0 = self._dim_of(rest[0], env, line, 0)
                tup = (d0,) if d0 is not None else None
            dims = list(tup) if tup is not None else None
        else:
            dims = [self._dim_of(a, env, line, 0) for a in rest]
        if not dims:
            return None
        out = []
        for i, d in enumerate(dims):
            if d is None or (d.concrete and d.coeff == -1):
                d = sym(f"{env.key}:reshape@{call.lineno}.{i}")
            out.append(d)
        return tuple(out)

    def _transpose_shape(self, base, rest, env, line, depth, leaf):
        if base is None:
            return None
        s = self._shape_of(base, env, line, depth)
        if s is None:
            return None
        if leaf == "swapaxes":
            if len(rest) == 2 and all(
                    isinstance(a, ast.Constant)
                    and isinstance(a.value, int) for a in rest):
                i, j = rest[0].value, rest[1].value
                if -len(s) <= i < len(s) and -len(s) <= j < len(s):
                    out = list(s)
                    out[i], out[j] = out[j], out[i]
                    return tuple(out)
            return None
        if not rest:
            return tuple(reversed(s))
        elts = (rest[0].elts if len(rest) == 1
                and isinstance(rest[0], (ast.Tuple, ast.List)) else rest)
        perm = [e.value if isinstance(e, ast.Constant)
                and isinstance(e.value, int) else None for e in elts]
        if len(perm) != len(s) or any(p is None for p in perm) \
                or sorted(perm) != list(range(len(s))):
            return None
        return tuple(s[p] for p in perm)

    def _reduce_shape(self, base, rest, call, env, line, depth):
        s = self._shape_of(base, env, line, depth)
        if s is None:
            return None
        axis = rest[0] if rest else None
        keep = False
        for kw in call.keywords:
            if kw.arg == "axis":
                axis = kw.value
            elif kw.arg == "keepdims":
                keep = isinstance(kw.value, ast.Constant) \
                    and kw.value.value is True
        if axis is None:
            return tuple(con(1) for _ in s) if keep else ()
        if not (isinstance(axis, ast.Constant)
                and isinstance(axis.value, int)):
            return None
        i = axis.value
        if not (-len(s) <= i < len(s)):
            return None
        i %= len(s)
        if keep:
            return s[:i] + (con(1),) + s[i + 1:]
        return s[:i] + s[i + 1:]

    def _axis_shape(self, base, rest, call, env, line, depth, leaf):
        s = self._shape_of(base, env, line, depth)
        if s is None:
            return None
        axis = rest[0] if rest else None
        for kw in call.keywords:
            if kw.arg == "axis":
                axis = kw.value
        if not (isinstance(axis, ast.Constant)
                and isinstance(axis.value, int)):
            return None
        i = axis.value
        if leaf == "expand_dims":
            if not (-len(s) - 1 <= i <= len(s)):
                return None
            i %= (len(s) + 1)
            return s[:i] + (con(1),) + s[i:]
        if not (-len(s) <= i < len(s)):
            return None
        i %= len(s)
        if s[i].concrete and s[i].coeff != 1:
            return None  # squeezing a non-1 dim fails at runtime anyway
        return s[:i] + s[i + 1:]

    # -- checked sites (shape transfer + ATP901) --------------------------

    def _concat_shape(self, call, env, line, depth, leaf, path,
                      findings):
        seq = call.args[0] if call.args else None
        if not isinstance(seq, (ast.Tuple, ast.List)):
            return None
        shapes = [self._shape_of(e, env, line, depth) for e in seq.elts]
        axis = 0
        if len(call.args) > 1:
            a = call.args[1]
            if isinstance(a, ast.Constant) and isinstance(a.value, int):
                axis = a.value
            else:
                return None
        for kw in call.keywords:
            if kw.arg == "axis":
                if isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, int):
                    axis = kw.value.value
                else:
                    return None
        known = [s for s in shapes if s is not None]
        if len(known) < 2:
            return None
        rank = len(known[0])
        if any(len(s) != rank for s in known):
            if findings is not None:
                findings.append(Finding(
                    ATP901,
                    f"{leaf} operands provably have different ranks "
                    f"({', '.join(str(len(s)) for s in known)})",
                    path, call.lineno, call.col_offset))
            return None
        if leaf == "stack":
            cmp_axes = list(range(rank))
        else:
            if not (-rank <= axis < rank):
                return None
            axis %= rank
            cmp_axes = [i for i in range(rank) if i != axis]
        for i in cmp_axes:
            vals = {s[i].coeff for s in known if s[i].concrete}
            if len(vals) > 1:
                if findings is not None:
                    findings.append(Finding(
                        ATP901,
                        f"{leaf} operands provably disagree on axis "
                        f"{i}: sizes {sorted(vals)}",
                        path, call.lineno, call.col_offset))
                return None
        if len(known) != len(shapes):
            return None
        if leaf == "stack":
            if not (-rank - 1 <= axis <= rank):
                return None
            axis %= (rank + 1)
            return known[0][:axis] + (con(len(shapes)),) \
                + known[0][axis:]
        out = list(known[0])
        total = 0
        for s in known:
            if not s[axis].concrete:
                total = None
                break
            total += s[axis].coeff
        out[axis] = (con(total) if total is not None
                     else sym(f"{env.key}:concat@{call.lineno}"))
        return tuple(out)

    def _dot_shape(self, call, env, line, depth, path, findings):
        if len(call.args) < 2:
            return None
        a = self._shape_of(call.args[0], env, line, depth)
        b = self._shape_of(call.args[1], env, line, depth)
        return self._check_dot(a, b, call, path, findings)

    def _check_dot(self, a, b, node, path, findings):
        if a is None or b is None or not a or not b:
            return None
        inner_a = a[-1]
        inner_b = b[-2] if len(b) >= 2 else b[0]
        if inner_a.concrete and inner_b.concrete \
                and inner_a.coeff != inner_b.coeff:
            if findings is not None:
                findings.append(Finding(
                    ATP901,
                    "dot/matmul contraction dims provably disagree: "
                    f"lhs last dim {inner_a.coeff} vs rhs "
                    f"{inner_b.coeff}",
                    path, node.lineno, node.col_offset))
            return None
        if len(a) == 2 and len(b) == 2:
            return (a[0], b[1])
        if len(a) == 1 and len(b) == 1:
            return ()
        if len(a) == len(b) and len(a) > 2:
            return a[:-1] + (b[-1],)
        return None

    def _where_shape(self, call, env, line, depth, path, findings):
        if len(call.args) < 3:
            return None
        shapes = [self._shape_of(a, env, line, depth)
                  for a in call.args[:3]]
        out = None
        for s in shapes:
            if s is None:
                continue
            if out is None:
                out = s
                continue
            if findings is not None and _broadcast_conflict(out, s):
                findings.append(Finding(
                    ATP901,
                    "where operands are provably broadcast-"
                    f"incompatible ({_fmt(out)} vs {_fmt(s)})",
                    path, call.lineno, call.col_offset))
                return None
            out = _broadcast(out, s)
            if out is None:
                return None
        return out if all(s is not None for s in shapes) else None

    def _einsum_shape(self, call, env, line, depth, path, findings):
        if not call.args or not (
                isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)):
            return None
        spec = call.args[0].value.replace(" ", "")
        if "..." in spec or "->" not in spec:
            return None
        lhs, rhs = spec.split("->", 1)
        subs = lhs.split(",")
        operands = call.args[1:]
        if len(subs) != len(operands):
            return None
        letter_dims: dict[str, Dim] = {}
        for sub, op in zip(subs, operands):
            s = self._shape_of(op, env, line, depth)
            if s is None:
                continue
            if len(s) != len(sub):
                if findings is not None:
                    findings.append(Finding(
                        ATP901,
                        f"einsum subscript {sub!r} has {len(sub)} "
                        "indices but the operand provably has rank "
                        f"{len(s)}",
                        path, call.lineno, call.col_offset))
                return None
            for ch, d in zip(sub, s):
                prev = letter_dims.get(ch)
                if prev is None:
                    letter_dims[ch] = d
                elif prev.concrete and d.concrete \
                        and prev.coeff != d.coeff:
                    if findings is not None:
                        findings.append(Finding(
                            ATP901,
                            f"einsum index {ch!r} is provably bound "
                            f"to two different sizes ({prev.coeff} "
                            f"vs {d.coeff})",
                            path, call.lineno, call.col_offset))
                    return None
        if any(ch not in letter_dims for ch in rhs):
            return None
        return tuple(letter_dims[ch] for ch in rhs)

    def _dot_general_check(self, call, env, line, depth, path,
                           findings):
        if len(call.args) < 2:
            return
        dn = call.args[2] if len(call.args) > 2 else None
        for kw in call.keywords:
            if kw.arg == "dimension_numbers":
                dn = kw.value
        pairs = _dn_contract_pairs(dn)
        if pairs is None:
            return
        a = self._shape_of(call.args[0], env, line, depth)
        b = self._shape_of(call.args[1], env, line, depth)
        if a is None or b is None:
            return
        for (la, rb) in pairs:
            if not (-len(a) <= la < len(a) and -len(b) <= rb < len(b)):
                continue
            da, db = a[la], b[rb]
            if da.concrete and db.concrete and da.coeff != db.coeff:
                findings.append(Finding(
                    ATP901,
                    f"dot_general contracts lhs dim {la} ({da.coeff}) "
                    f"against rhs dim {rb} ({db.coeff}) — provably "
                    "unequal",
                    path, call.lineno, call.col_offset))
                return

    # -- interprocedural return-shape summaries ---------------------------

    def _summary_shape(self, call: ast.Call, env: ScopeEnv, line: int,
                       depth: int):
        callee, _ = self.index.resolve_call(self.path, None, call)
        if callee is None:
            return None
        arg_shapes = [self._shape_of(a, env, line, depth - 1)
                      for a in call.args]
        key = (callee, tuple(s if s is None else tuple(s)
                             for s in arg_shapes))
        if key in self._summaries:
            return self._summaries[key]
        self._summaries[key] = None  # cycle guard
        info = self.index.functions.get(callee)
        if info is None or info.cls is not None:
            return None
        got = self._return_shape(info, arg_shapes, depth - 1)
        self._summaries[key] = got
        return got

    def _return_shape(self, info, arg_shapes, depth):
        """Interpret the callee with positional params bound to the
        caller's shapes; a unique known return shape is the summary."""
        if info.path == self.path:
            sub = self
        else:
            mod = self.index.modules.get(info.path)
            if mod is None:
                return None
            sub = interp_for(info.path, mod.tree, self.index)
        env = sub.env(info.node)
        a = info.node.args
        names = [p.arg for p in a.posonlyargs + a.args]
        overlay = ScopeEnv(info.node, env.key, env.parent)
        overlay.bindings = {k: list(v) for k, v in env.bindings.items()}
        overlay.params = env.params
        overlay.facts = env.facts
        for name, s in zip(names, arg_shapes):
            if s is not None and name not in overlay.bindings:
                overlay.bindings[name] = [
                    (info.node.lineno, _ARRAY, tuple(s))]
        out = None
        for r in scope_list(info.node):
            if not isinstance(r, ast.Return) or r.value is None:
                continue
            s = sub._shape_of(r.value, overlay, r.lineno + 1, depth)
            if s is None:
                return None
            if out is None:
                out = s
            elif out != s:
                return None
        return out

    # -- the check walk ----------------------------------------------------

    def check_scope(self, scope: ast.AST,
                    findings: list[Finding]) -> None:
        env = self.env(scope)
        for node in _scope_nodes(scope):
            line = getattr(node, "lineno", 0) + 1
            if isinstance(node, ast.BinOp) \
                    and isinstance(node.op, ast.MatMult):
                a = self._shape_of(node.left, env, line, _SUMMARY_DEPTH)
                b = self._shape_of(node.right, env, line,
                                   _SUMMARY_DEPTH)
                self._check_dot(a, b, node, self.path, findings)
            elif isinstance(node, ast.Call):
                f = node.func
                leaf = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None)
                if leaf in ("dot", "matmul"):
                    self._dot_shape(node, env, line, _SUMMARY_DEPTH,
                                    self.path, findings)
                elif leaf == "dot_general":
                    self._dot_general_check(node, env, line,
                                            _SUMMARY_DEPTH, self.path,
                                            findings)
                elif leaf == "einsum":
                    self._einsum_shape(node, env, line, _SUMMARY_DEPTH,
                                       self.path, findings)
                elif leaf in ("concatenate", "stack"):
                    self._concat_shape(node, env, line, _SUMMARY_DEPTH,
                                       leaf, self.path, findings)
                elif leaf == "where":
                    self._where_shape(node, env, line, _SUMMARY_DEPTH,
                                      self.path, findings)


def _scope_nodes(scope: ast.AST) -> list[ast.AST]:
    """The nodes belonging to one scope (module scope stops at defs)."""
    if not isinstance(scope, ast.Module):
        return scope_list(scope)
    out: list[ast.AST] = []
    stack = list(scope.body)
    while stack:
        node = stack.pop()
        out.append(node)
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(node))
    return out


def _broadcast(a, b):
    if a is None or b is None:
        return None
    if len(a) < len(b):
        a, b = b, a
    out = list(a)
    for i in range(1, len(b) + 1):
        da, db = a[-i], b[-i]
        if da.concrete and da.coeff == 1:
            out[-i] = db
        elif (db.concrete and db.coeff == 1) or da == db:
            out[-i] = da
        elif da.concrete and db.concrete and da.coeff != db.coeff:
            return None
        else:
            out[-i] = da if da.concrete else db
    return tuple(out)


def _broadcast_conflict(a, b) -> bool:
    """Provably incompatible: some aligned pair is concrete, unequal,
    and neither side is 1."""
    for i in range(1, min(len(a), len(b)) + 1):
        da, db = a[-i], b[-i]
        if da.concrete and db.concrete and da.coeff != db.coeff \
                and da.coeff != 1 and db.coeff != 1:
            return True
    return False


def _fmt(shape) -> str:
    return "(" + ", ".join(repr(d) for d in shape) + ")"


def _dn_contract_pairs(dn):
    """Literal ``((lhs_contract, rhs_contract), ...)`` index pairs."""
    if not isinstance(dn, ast.Tuple) or not dn.elts:
        return None
    c = dn.elts[0]
    if not isinstance(c, ast.Tuple) or len(c.elts) != 2:
        return None
    sides = []
    for side in c.elts:
        if not isinstance(side, (ast.Tuple, ast.List)):
            return None
        vals = [e.value if isinstance(e, ast.Constant)
                and isinstance(e.value, int) else None
                for e in side.elts]
        if any(v is None for v in vals):
            return None
        sides.append(vals)
    if len(sides[0]) != len(sides[1]):
        return None
    return list(zip(sides[0], sides[1]))


# -- shared entry points ---------------------------------------------------

#: id(tree) -> (tree, ShapeInterp); shared across the shapes, pallas
#: and sharding passes within one analyze() run
_INTERP_CACHE: dict[int, tuple[ast.Module, ShapeInterp]] = {}
_INTERP_CACHE_MAX = 512


def interp_for(path: str, tree: ast.Module, index=None) -> ShapeInterp:
    hit = _INTERP_CACHE.get(id(tree))
    if hit is not None and hit[0] is tree:
        return hit[1]
    interp = ShapeInterp(path, tree, index)
    if len(_INTERP_CACHE) >= _INTERP_CACHE_MAX:
        _INTERP_CACHE.clear()
    _INTERP_CACHE[id(tree)] = (tree, interp)
    return interp


def _is_trigger(node: ast.AST) -> bool:
    if isinstance(node, ast.BinOp):
        return isinstance(node.op, ast.MatMult)
    if isinstance(node, ast.Call):
        f = node.func
        leaf = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        return leaf in TRIGGER_LEAVES
    return False


def _scope_has_trigger(scope: ast.AST) -> bool:
    for node in _scope_nodes(scope):
        if _is_trigger(node):
            return True
    return False


def _by_line(entry) -> int:
    return entry[0]


@file_pass("shapes", [ATP901], needs_index=True)
def check_shapes(path: str, tree: ast.Module, src: str, index=None):
    """Provable dot/concat/where shape mismatches (symbolic domain)."""
    # cheap prefilter on the shared walk cache: most files have no
    # dot/einsum/concat/where/@ site at all
    if not any(_is_trigger(n) for n in walk_list(tree)):
        return []
    findings: list[Finding] = []
    interp = interp_for(path, tree, index)
    for scope in interp.scopes():
        if _scope_has_trigger(scope):
            interp.check_scope(scope, findings)
    return findings
