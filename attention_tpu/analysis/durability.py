"""Durability-hygiene pass: torn-write-prone persistence (ATP701).

ISSUE 9's snapshot/journal layer exists because a process can die at
ANY byte of a write.  The repo-wide idiom that survives that (already
used by ``TuningTable.save``, now pinned here) is write-to-temp +
``os.replace``: the destination path either holds the complete old
file or the complete new file, never a torn prefix.

ATP701 (error) flags, inside the durable-persistence modules
(``engine/snapshot.py``, ``engine/journal.py``, ``tuning/cache.py``),
any ``open``/``os.fdopen`` call with a truncating/creating mode
(``"w"``/``"x"``) in a function that never calls ``os.replace`` —
that open either clobbers the destination in place (a crash mid-write
leaves a torn file where a valid one used to be) or is a temp file
that never atomically lands.

Append mode (``"a"``/``"ab"``) is exempt: that IS the write-ahead-log
idiom — a torn appended record is detected by the journal's per-record
CRC and dropped, while every earlier record stays intact.  Reads are
exempt.  Deliberate crash-point writes (the chaos hook that simulates
dying mid-snapshot) carry an inline ``# atp: disable=ATP701``.
"""

from __future__ import annotations

import ast

from attention_tpu.analysis.core import (
    Finding,
    Severity,
    dotted_name,
    file_pass,
    register_code,
    walk_list,
)

ATP701 = register_code(
    "ATP701", "torn-write-prone-persistence", Severity.ERROR,
    "open(..., 'w*') in a durable-persistence module without "
    "os.replace in the same function — write to a temp file and "
    "os.replace it over the destination (append mode is the WAL "
    "idiom and exempt)")

#: the modules whose files must survive a crash at any byte
_DURABLE_PATHS = (
    "attention_tpu/engine/snapshot.py",
    "attention_tpu/engine/journal.py",
    "attention_tpu/tuning/cache.py",
)


def _call_mode(node: ast.Call) -> str | None:
    """The constant mode string of an ``open``/``os.fdopen`` call, or
    None when the call isn't one / the mode isn't a literal (default
    mode is read: exempt)."""
    name = dotted_name(node.func)
    if name not in ("open", "os.fdopen", "io.open"):
        return None
    mode: ast.expr | None = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


def _scopes(tree: ast.Module):
    """(scope_node, body_nodes) for every function (nested defs stay
    part of the enclosing function's scope — a helper closure that
    does the os.replace still makes the write atomic) plus the
    module's own top-level statements."""
    funcs = [n for n in walk_list(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    owned = set()
    for fn in funcs:
        owned.update(id(n) for n in ast.walk(fn) if n is not fn)
    yield tree, [n for n in walk_list(tree)
                 if id(n) not in owned and n not in funcs]
    for fn in funcs:
        if id(fn) not in owned:  # nested defs ride their enclosing scope
            yield fn, list(ast.walk(fn))


@file_pass("durability", [ATP701])
def check_durability(path: str, tree: ast.Module, src: str):
    """Truncating opens without os.replace in durable modules."""
    if path not in _DURABLE_PATHS:
        return []
    findings: list[Finding] = []
    seen: set[tuple[int, int]] = set()
    for scope, nodes in _scopes(tree):
        calls = [n for n in nodes if isinstance(n, ast.Call)]
        has_replace = any(
            dotted_name(c.func) == "os.replace" for c in calls)
        if has_replace:
            continue
        for call in calls:
            mode = _call_mode(call)
            if mode is None or not any(c in mode for c in "wx"):
                continue
            loc = (call.lineno, call.col_offset)
            if loc in seen:
                continue
            seen.add(loc)
            findings.append(Finding(
                ATP701,
                f"open(..., {mode!r}) without os.replace in scope — "
                "a crash mid-write tears the file; write a sibling "
                "temp file and os.replace it (see TuningTable.save)",
                path, call.lineno, call.col_offset))
    return findings
