"""Error-taxonomy pass: generic raises where typed errors exist.

PR 2 introduced typed capacity/accounting errors
(``attention_tpu.ops.paged.OutOfPagesError`` / ``PageAccountingError``)
precisely so the engine's callers — and the chaos invariant checkers —
can tell "pool exhausted, schedule around it" from "accounting bug,
stop the world".  A bare ``RuntimeError`` three layers down erases
that distinction, so inside the ``engine/`` and ``chaos/`` trees:

- ATP401 (error): ``raise RuntimeError/Exception/AssertionError`` —
  runtime-path failures must be a typed subclass;
- ATP402 (warning): ``raise ValueError`` — usually constructor/argument
  validation at the public API boundary, which is legitimate; the
  existing ones are pinned per-file (with counts) in
  ``analysis/baseline.json`` so a *new* one forces a conscious choice
  between a typed error and a justified baseline bump.

ISSUE 6 extended the scope over ``frontend/``: the resilient front
end grew its own typed trio (``DeadlineExceededError`` /
``ReplicaDeadError`` / ``RequestShedError`` in
``attention_tpu.engine.errors``), so a bare RuntimeError there is just
as much an erasure as in the engine.  ISSUE 13 extended it over
``obs/``: the trace/SLO/digest modules are the fleet's forensic
surface, and their validation-ValueErrors are pinned per file in the
baseline like everyone else's.

Raising a *name that ends in Error but is locally defined or imported
from this package* is the blessed pattern and never flagged.
"""

from __future__ import annotations

import ast

from attention_tpu.analysis.core import (
    Finding,
    Severity,
    dotted_name,
    file_pass,
    register_code,
    walk_list,
)

ATP401 = register_code(
    "ATP401", "generic-runtime-raise-in-typed-path", Severity.ERROR,
    "raise RuntimeError/Exception/AssertionError under engine/, "
    "chaos/, fleet/, frontend/, obs/, or prefixstore/ — use a typed "
    "error (OutOfPagesError lineage)")
ATP402 = register_code(
    "ATP402", "generic-value-raise-in-typed-path", Severity.WARNING,
    "raise ValueError under engine/, chaos/, fleet/, frontend/, obs/, "
    "or prefixstore/ — argument validation is baselined per file; new "
    "ones need a typed error or a justified baseline entry")

#: trees where the typed taxonomy is the contract
_TYPED_PATHS = ("attention_tpu/engine/", "attention_tpu/chaos/",
                "attention_tpu/fleet/", "attention_tpu/frontend/",
                "attention_tpu/obs/", "attention_tpu/prefixstore/")
_GENERIC = {"RuntimeError", "Exception", "AssertionError"}


def _raised_name(node: ast.Raise) -> str | None:
    exc = node.exc
    if isinstance(exc, ast.Call):
        exc = exc.func
    d = dotted_name(exc) if exc is not None else None
    return d.split(".")[-1] if d else None


@file_pass("errors", [ATP401, ATP402])
def check_errors(path: str, tree: ast.Module, src: str):
    """Generic RuntimeError/ValueError raises in typed-error trees."""
    if not any(path.startswith(p) for p in _TYPED_PATHS):
        return []
    findings: list[Finding] = []
    for node in walk_list(tree):
        if not isinstance(node, ast.Raise):
            continue
        name = _raised_name(node)
        if name in _GENERIC:
            findings.append(Finding(
                ATP401,
                f"raise {name} in a typed-error path — subclass a "
                "typed error (see attention_tpu.ops.paged."
                "OutOfPagesError / PageAccountingError)",
                path, node.lineno, node.col_offset))
        elif name == "ValueError":
            findings.append(Finding(
                ATP402,
                "raise ValueError in a typed-error path — if this is "
                "API-boundary validation, baseline it with a "
                "justification; otherwise use a typed error",
                path, node.lineno, node.col_offset))
    return findings
