"""Project-scope symbol table + call graph for interprocedural passes.

PR 5's passes are file-scope: a hazard one helper call away from a jit
body — or a wall-clock read three modules from the artifact it taints —
is invisible.  This module builds the whole-tree index those passes
need:

- a per-module **symbol table**: module-level ``def``s and ``class``es,
  every ``import``/``from-import`` binding (followed lazily through
  re-export chains, so ``obs.counter`` resolves through
  ``obs/__init__`` to ``obs/registry.py::counter``), plus module-level
  aliases ``g = f`` and ``g = functools.partial(f, ...)``;
- a **call graph**: one :class:`CallSite` per call expression in every
  top-level function/method (nested defs ride their enclosing
  function), with the callee resolved to a :class:`FunctionInfo` when
  the chain is decidable — ``self.m()`` via the enclosing class (and
  its in-index bases), ``mod.sub.f()`` via the import tables,
  ``partial(f, ...)()`` via unwrap;
- the **canonical name** of every call that does NOT resolve in-tree
  (``np.random.normal`` -> ``numpy.random.normal``,
  ``from time import time as now; now()`` -> ``time.time``), so
  source/sink matchers in the determinism passes see through aliasing;
- the ``--changed`` **reverse closure**: the set of files holding
  callers (transitively) of anything defined in a changed file.

Bounded, never guessing: any link that is not decidable — a call on a
subscript, an attribute of an unknown object, a name rebound
dynamically — yields an *opaque* call site (``callee=None``) rather
than a wrong edge.  Resolution chains are depth-limited and
cycle-guarded.  The whole index is plain ``ast`` — jax-free, one parse
per file, built once per ``analyze()`` run.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable

from attention_tpu.analysis.core import (dotted_name, iter_source_files,
                                        walk_list)

#: maximum hops when chasing import/alias chains (cycle insurance)
_RESOLVE_DEPTH = 8

_PARTIAL = ("partial", "functools.partial")


@dataclasses.dataclass(frozen=True)
class FunctionInfo:
    """One module-level function or class method."""

    qual: str                 # "path::name" or "path::Class.name"
    path: str
    name: str
    cls: str | None           # owning class qual, None for free functions
    node: ast.FunctionDef | ast.AsyncFunctionDef


@dataclasses.dataclass(frozen=True)
class ClassInfo:
    qual: str                 # "path::Name"
    path: str
    name: str
    bases: tuple[str, ...]    # base expressions as written (dotted)
    methods: dict = dataclasses.field(default_factory=dict, hash=False)


@dataclasses.dataclass(frozen=True)
class CallSite:
    """One call expression inside ``caller``.

    ``callee`` is the resolved in-tree function qual, or None when the
    call is opaque; ``name`` is then the best canonical dotted name
    (``numpy.random.normal``) or the raw text when even that is
    unknown.
    """

    caller: str
    callee: str | None
    name: str | None
    lineno: int
    col: int
    node: ast.Call = dataclasses.field(hash=False, compare=False)


class _Module:
    __slots__ = ("path", "dotted", "tree", "src", "symbols")

    def __init__(self, path: str, dotted: str, tree: ast.Module, src: str):
        self.path = path
        self.dotted = dotted
        self.tree = tree
        self.src = src
        #: name -> ("func", qual) | ("class", qual) | ("import", dotted)
        #:         | ("ext", dotted)
        self.symbols: dict[str, tuple[str, str]] = {}


def _module_dotted(path: str) -> str:
    """``attention_tpu/obs/naming.py`` -> ``attention_tpu.obs.naming``;
    ``pkg/__init__.py`` -> ``pkg``."""
    p = path[:-3] if path.endswith(".py") else path
    parts = p.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _is_partial(node: ast.expr) -> bool:
    return dotted_name(node) in _PARTIAL


class ProjectIndex:
    """Symbol tables + call graph over one source tree."""

    def __init__(self):
        self.modules: dict[str, _Module] = {}
        self._by_dotted: dict[str, str] = {}      # dotted -> path
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.calls: dict[str, list[CallSite]] = {}
        self.callers: dict[str, set[str]] = {}    # callee qual -> callers
        #: full-depth resolutions, keyed (module path, dotted) — the
        #: same names recur at thousands of call sites
        self._resolve_memo: dict[tuple[str, str],
                                 tuple[str, str] | None] = {}
        #: id(scope node) -> flattened source-order statement list;
        #: shared by every dataflow query over this index (the index's
        #: module trees keep the nodes alive, so ids stay valid)
        self._stmt_cache: dict[int, list] = {}

    # -- construction -----------------------------------------------------

    @classmethod
    def build(cls, root: str,
              rel_paths: Iterable[str] | None = None) -> "ProjectIndex":
        """Index every scanned ``.py`` file under ``root``."""
        sources: dict[str, str] = {}
        for rel in (rel_paths if rel_paths is not None
                    else iter_source_files(root)):
            if not rel.endswith(".py"):
                continue
            full = os.path.join(root, rel)
            if not os.path.isfile(full):
                continue
            with open(full, encoding="utf-8") as f:
                sources[rel] = f.read()
        return cls.from_sources(sources)

    @classmethod
    def from_sources(cls, sources: dict[str, str]) -> "ProjectIndex":
        """Index in-memory ``{rel_path: source}`` (the test seam)."""
        idx = cls()
        for path, src in sorted(sources.items()):
            try:
                tree = ast.parse(src, filename=path)
            except SyntaxError:
                continue  # ATP001's problem, not the call graph's
            mod = _Module(path, _module_dotted(path), tree, src)
            idx.modules[path] = mod
            idx._by_dotted[mod.dotted] = path
        for mod in idx.modules.values():
            idx._collect_defs(mod)
        for mod in idx.modules.values():
            idx._collect_imports_and_aliases(mod)
        for mod in idx.modules.values():
            idx._collect_calls(mod)
        return idx

    def _collect_defs(self, mod: _Module) -> None:
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{mod.path}::{node.name}"
                info = FunctionInfo(qual, mod.path, node.name, None, node)
                self.functions[qual] = info
                mod.symbols[node.name] = ("func", qual)
            elif isinstance(node, ast.ClassDef):
                cqual = f"{mod.path}::{node.name}"
                bases = tuple(d for d in (dotted_name(b)
                                          for b in node.bases) if d)
                cinfo = ClassInfo(cqual, mod.path, node.name, bases)
                self.classes[cqual] = cinfo
                mod.symbols[node.name] = ("class", cqual)
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        fqual = f"{mod.path}::{node.name}.{sub.name}"
                        finfo = FunctionInfo(fqual, mod.path, sub.name,
                                             cqual, sub)
                        self.functions[fqual] = finfo
                        cinfo.methods[sub.name] = finfo

    def _collect_imports_and_aliases(self, mod: _Module) -> None:
        # imports anywhere in the file feed one module-wide table — a
        # bounded over-approximation (function-local imports are the
        # idiom here, and a name is never re-imported as two different
        # things in this tree)
        for node in walk_list(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        mod.symbols[alias.asname] = ("import", alias.name)
                    else:
                        head = alias.name.split(".")[0]
                        mod.symbols.setdefault(head, ("import", head))
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:  # relative: anchor at this file's package
                    pkg = mod.dotted.split(".")
                    if mod.path.endswith("__init__.py"):
                        pkg = pkg  # package dotted already
                    else:
                        pkg = pkg[:-1]
                    pkg = pkg[:len(pkg) - (node.level - 1)] if node.level > 1 \
                        else pkg
                    base = ".".join(pkg + ([node.module]
                                           if node.module else []))
                for alias in node.names:
                    if alias.name == "*":
                        continue  # never guess star imports
                    full = f"{base}.{alias.name}" if base else alias.name
                    mod.symbols[alias.asname or alias.name] = (
                        "import", full)
        # module-level aliases: g = f  /  g = partial(f, ...)
        for node in mod.tree.body:
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            tgt = node.targets[0].id
            val = node.value
            if isinstance(val, ast.Call) and _is_partial(val.func) \
                    and val.args:
                val = val.args[0]
            d = dotted_name(val)
            if d and tgt not in mod.symbols:
                mod.symbols[tgt] = ("alias", d)

    # -- symbol resolution ------------------------------------------------

    def _module_attr(self, path: str, name: str,
                     depth: int) -> tuple[str, str] | None:
        mod = self.modules.get(path)
        if mod is None:
            return None
        return self._resolve_symbol(mod, name, depth)

    def _resolve_symbol(self, mod: _Module, name: str,
                        depth: int) -> tuple[str, str] | None:
        """A module-table entry chased to ("func"|"class"|"mod"|"ext",
        ref) — None when the name is unbound (builtins stay opaque)."""
        if depth <= 0:
            return None
        t = mod.symbols.get(name)
        if t is None:
            return None
        kind, ref = t
        if kind in ("func", "class"):
            return t
        if kind == "alias":
            return self._resolve_dotted_in(mod, ref, depth - 1)
        if kind == "import":
            return self._resolve_import(ref, depth - 1)
        return t

    def _resolve_import(self, dotted: str,
                        depth: int) -> tuple[str, str] | None:
        if depth <= 0:
            return None
        if dotted in self._by_dotted:
            return ("mod", self._by_dotted[dotted])
        if "." in dotted:
            parent, leaf = dotted.rsplit(".", 1)
            if parent in self._by_dotted:
                got = self._module_attr(self._by_dotted[parent], leaf,
                                        depth - 1)
                return got  # None: member we can't see — opaque
            head = dotted.split(".")[0]
            if head in self._by_dotted:
                return None  # deep path into an indexed pkg we can't chase
        return ("ext", dotted)

    def _resolve_dotted_in(self, mod: _Module, dotted: str,
                           depth: int) -> tuple[str, str] | None:
        """Resolve ``a.b.c`` as written inside ``mod``."""
        if depth <= 0:
            return None
        memo_key = (mod.path, dotted) if depth == _RESOLVE_DEPTH else None
        if memo_key is not None and memo_key in self._resolve_memo:
            return self._resolve_memo[memo_key]
        got = self._resolve_dotted_uncached(mod, dotted, depth)
        if memo_key is not None:
            self._resolve_memo[memo_key] = got
        return got

    def _resolve_dotted_uncached(self, mod: _Module, dotted: str,
                                 depth: int) -> tuple[str, str] | None:
        parts = dotted.split(".")
        t = self._resolve_symbol(mod, parts[0], depth)
        if t is None:
            return None
        for i, part in enumerate(parts[1:], start=1):
            kind, ref = t
            if kind == "mod":
                t = self._module_attr(ref, part, depth - 1)
                if t is None:
                    return None
            elif kind == "ext":
                return ("ext", ref + "." + ".".join(parts[i:]))
            elif kind == "class":
                m = self.classes[ref].methods.get(part) \
                    or self._inherited_method(ref, part)
                return ("func", m.qual) if m and i == len(parts) - 1 \
                    else None
            else:
                return None  # attribute of a function: opaque
        return t

    def _inherited_method(self, cqual: str,
                          name: str) -> FunctionInfo | None:
        """Walk in-index base classes (bounded, cycle-guarded)."""
        seen = set()
        stack = [cqual]
        while stack and len(seen) < _RESOLVE_DEPTH:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            cls = self.classes.get(q)
            if cls is None:
                continue
            m = cls.methods.get(name)
            if m is not None:
                return m
            mod = self.modules[cls.path]
            for b in cls.bases:
                t = self._resolve_dotted_in(mod, b, _RESOLVE_DEPTH)
                if t and t[0] == "class":
                    stack.append(t[1])
        return None

    def canonical_name(self, path: str, dotted: str) -> str:
        """``np.random.normal`` written in ``path`` -> the canonical
        external dotted name (``numpy.random.normal``); unresolvable
        names come back as written."""
        mod = self.modules.get(path)
        if mod is None or not dotted:
            return dotted
        t = self._resolve_dotted_in(mod, dotted, _RESOLVE_DEPTH)
        if t and t[0] == "ext":
            return t[1]
        return dotted

    # -- call resolution --------------------------------------------------

    def resolve_call(self, path: str, cls_qual: str | None,
                     call: ast.Call,
                     local_aliases: dict[str, str] | None = None,
                     shadowed: set[str] | None = None,
                     ) -> tuple[str | None, str | None]:
        """(callee qual | None, canonical/raw dotted | None)."""
        mod = self.modules.get(path)
        func = call.func
        if isinstance(func, ast.Call):  # partial(f, ...)(args)
            if _is_partial(func.func) and func.args:
                inner = ast.Call(func=func.args[0], args=[], keywords=[])
                ast.copy_location(inner, call)
                return self.resolve_call(path, cls_qual, inner,
                                         local_aliases, shadowed)
            return None, None
        d = dotted_name(func)
        if d is None or mod is None:
            return None, d
        parts = d.split(".")
        head = parts[0]
        if head in ("self", "cls") and cls_qual and len(parts) == 2:
            # before the shadow check: self/cls are always parameters
            m = (self.classes[cls_qual].methods.get(parts[1])
                 or self._inherited_method(cls_qual, parts[1]))
            return (m.qual, d) if m else (None, d)
        if shadowed and head in shadowed:
            return None, d
        if local_aliases and head in local_aliases and len(parts) == 1:
            d = local_aliases[head]
            parts = d.split(".")
            head = parts[0]
        t = self._resolve_dotted_in(mod, d, _RESOLVE_DEPTH)
        if t is None:
            return None, d
        kind, ref = t
        if kind == "func":
            return ref, d
        if kind == "class":
            # constructor call: resolve to __init__ when indexed
            m = self.classes[ref].methods.get("__init__") \
                or self._inherited_method(ref, "__init__")
            return (m.qual if m else None), d
        if kind == "ext":
            return None, ref
        return None, d

    def _collect_calls(self, mod: _Module) -> None:
        for qual, info in list(self.functions.items()):
            if info.path != mod.path:
                continue
            assigns, calls = _scan_fn(info.node)
            aliases, shadowed = _local_env_from(info.node, assigns)
            sites = []
            for node in calls:
                callee, name = self.resolve_call(
                    mod.path, info.cls, node, aliases, shadowed)
                site = CallSite(qual, callee, name, node.lineno,
                                node.col_offset, node)
                sites.append(site)
                if callee is not None:
                    self.callers.setdefault(callee, set()).add(qual)
            self.calls[qual] = sites

    def sites_in(self, fn: ast.AST, path: str,
                 cls_qual: str | None = None) -> list[CallSite]:
        """Resolve every call under an arbitrary node (for passes that
        walk scopes the function table doesn't cover)."""
        aliases, shadowed = (_local_env(fn)
                             if isinstance(fn, (ast.FunctionDef,
                                                ast.AsyncFunctionDef))
                             else ({}, set()))
        out = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                callee, name = self.resolve_call(path, cls_qual, node,
                                                 aliases, shadowed)
                out.append(CallSite("<adhoc>", callee, name, node.lineno,
                                    node.col_offset, node))
        return out

    # -- --changed reverse closure ---------------------------------------

    def files_calling(self, changed: Iterable[str]) -> set[str]:
        """Every file holding a (transitive) caller of any function
        defined in ``changed`` — the extra files a ``--changed`` run
        must lint once interprocedural passes are active."""
        target_files = set(changed)
        out: set[str] = set()
        grew = True
        while grew:
            grew = False
            for qual, sites in self.calls.items():
                cpath = self.functions[qual].path
                if cpath in target_files or cpath in out:
                    continue
                for s in sites:
                    if s.callee is None:
                        continue
                    callee_path = self.functions[s.callee].path
                    if callee_path in target_files or callee_path in out:
                        out.add(cpath)
                        grew = True
                        break
        return out


def _scan_fn(fn: ast.AST) -> tuple[list, list]:
    """(Assign nodes, Call nodes) under ``fn`` in ONE ``ast.walk``-order
    traversal — the index build used to walk every function subtree
    twice (local aliases, then call sites); merged here it is the
    single biggest term in the tree-wide index time."""
    AST = ast.AST
    assigns: list[ast.Assign] = []
    calls: list[ast.Call] = []
    todo: list[ast.AST] = [fn]
    i = 0
    while i < len(todo):
        node = todo[i]
        i += 1
        d = node.__dict__
        for field in node._fields:
            value = d.get(field)
            if isinstance(value, AST):
                todo.append(value)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, AST):
                        todo.append(v)
        if isinstance(node, ast.Call):
            calls.append(node)
        elif isinstance(node, ast.Assign):
            assigns.append(node)
    return assigns, calls


def _local_env(fn: ast.FunctionDef | ast.AsyncFunctionDef,
               ) -> tuple[dict[str, str], set[str]]:
    """(local aliases ``g -> f.dotted``, names shadowed by params or
    non-alias assignment — those must NOT fall through to the module
    table)."""
    return _local_env_from(fn, _scan_fn(fn)[0])


def _local_env_from(fn: ast.FunctionDef | ast.AsyncFunctionDef,
                    assigns: list,
                    ) -> tuple[dict[str, str], set[str]]:
    """`_local_env` over pre-collected Assign nodes (in walk order)."""
    a = fn.args
    shadowed = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
    for p in (a.vararg, a.kwarg):
        if p:
            shadowed.add(p.arg)
    aliases: dict[str, str] = {}
    for node in assigns:
        if not (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        tgt = node.targets[0].id
        val = node.value
        if isinstance(val, ast.Call) and _is_partial(val.func) and val.args:
            val = val.args[0]
        d = dotted_name(val)
        if d and tgt not in shadowed:
            aliases.setdefault(tgt, d)
        else:
            shadowed.add(tgt)
            aliases.pop(tgt, None)
    return aliases, shadowed
