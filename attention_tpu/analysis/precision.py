"""Precision passes: silent low-precision accumulation.

On the MXU, ``dot(bf16, bf16)`` accumulates in bf16 unless the call
asks for fp32 (``preferred_element_type``) — numerically the single
most expensive thing to forget in an attention kernel, and invisible
until a chaos campaign trips a tolerance three layers downstream.
``exp``/``softmax`` in sub-fp32 is the same hazard on the VPU side:
the online-softmax running max/sum must live in fp32 (the contract
ops/flash.py states in prose).

Lexical inference, two triggers, no guessing:

- an operand expression that is literally ``<x>.astype(<lowprec>)``
  (or ``jnp.asarray/zeros/... (..., dtype=<lowprec>)``);
- a Name assigned from such an expression earlier in the same (or an
  enclosing) function scope.

An explicit ``.astype`` to fp32+ marks a name clean again, so the
``k32 = k.astype(jnp.float32)`` idiom never fires.

With the project index (``needs_index``) the pass also follows ONE
call-graph level out of traced (jit/pallas) bodies: a visibly
low-precision value passed into an in-tree helper that dots it without
``preferred_element_type`` (and without an ``.astype`` re-pin) is
reported at the call site — the wrapper-function blind spot.  The
helper's own ``.astype(float32)`` re-pins still mark the name clean,
and helpers of helpers are out of scope by design.
"""

from __future__ import annotations

import ast

from attention_tpu.analysis.core import (
    Finding,
    Severity,
    dotted_name,
    file_pass,
    register_code,
    scope_list,
)

ATP301 = register_code(
    "ATP301", "lowprec-dot-no-preferred-type", Severity.ERROR,
    "dot/dot_general/einsum/matmul/@ on bf16/fp16/int8/int4 operands "
    "without preferred_element_type — accumulates in low precision")
ATP302 = register_code(
    "ATP302", "sub-fp32-exp-softmax", Severity.WARNING,
    "exp/exp2/softmax computed on a sub-fp32 operand — the softmax "
    "accumulator must be fp32")

#: dtypes whose accumulation needs an explicit preferred_element_type
_LOWPREC = {"bfloat16", "float16", "int8", "int4", "uint8", "float8_e4m3fn",
            "float8_e5m2"}
#: dot-like callables, by trailing attribute
_DOT_LEAVES = {"dot", "dot_general", "matmul", "einsum"}
#: constructors whose dtype= kwarg fixes the result dtype
_CTOR_LEAVES = {"asarray", "array", "zeros", "ones", "full", "empty",
                "zeros_like", "ones_like", "full_like", "empty_like"}
_EXP_NAMES = {"jnp.exp", "jnp.exp2", "jnp.softmax", "jax.nn.softmax",
              "nn.softmax", "jax.lax.exp", "lax.exp"}


def _dtype_of(node: ast.expr) -> str | None:
    from attention_tpu.analysis.pallas import _dtype_literal

    return _dtype_literal(node)


def _explicit_dtype(call: ast.Call) -> str | None:
    """The literal dtype an .astype()/constructor call pins, if any."""
    d = dotted_name(call.func)
    if isinstance(call.func, ast.Attribute) and call.func.attr == "astype":
        if call.args:
            return _dtype_of(call.args[0])
        return None
    if d and d.split(".")[-1] in _CTOR_LEAVES:
        for kw in call.keywords:
            if kw.arg == "dtype":
                return _dtype_of(kw.value)
    return None


def _is_lowprec(node: ast.expr, env: dict[str, bool]) -> bool:
    """True when ``node`` is inferably a low-precision array."""
    if isinstance(node, ast.Name):
        return env.get(node.id, False)
    if isinstance(node, ast.Call):
        dt = _explicit_dtype(node)
        if dt is not None:
            return dt in _LOWPREC
        return False
    if isinstance(node, ast.BinOp):
        return (_is_lowprec(node.left, env)
                or _is_lowprec(node.right, env))
    if isinstance(node, ast.UnaryOp):
        return _is_lowprec(node.operand, env)
    return False


def _scope_nodes(fn) -> list:
    """The scope's flattened node list, cached (one flatten feeds the
    env build, the check walk, and the nested-scope recursion)."""
    if isinstance(fn, ast.Module):
        return _module_scope_list(fn)
    return scope_list(fn)


def _scope_env(fn, inherited: dict[str, bool]) -> dict[str, bool]:
    """Name -> is-low-precision, from assignments in ``fn``'s scope."""
    env = dict(inherited)
    nodes = _scope_nodes(fn)
    for node in nodes:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            if isinstance(node.value, ast.Call):
                dt = _explicit_dtype(node.value)
                if dt is not None:
                    env[tgt.id] = dt in _LOWPREC
                    continue
            env[tgt.id] = _is_lowprec(node.value, env)
    return env


def _has_kw(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


def _check_scope(fn, inherited: dict[str, bool], path: str,
                 findings: list[Finding]) -> None:
    env = _scope_env(fn, inherited)
    walk = _scope_nodes(fn)
    for node in walk:
        if isinstance(node, ast.Call):
            d = dotted_name(node.func) or ""
            leaf = d.split(".")[-1]
            if leaf in _DOT_LEAVES and not _has_kw(
                    node, "preferred_element_type"):
                operands = (node.args[1:] if leaf == "einsum"
                            else node.args[:2])
                if any(_is_lowprec(a, env) for a in operands):
                    findings.append(Finding(
                        ATP301,
                        f"{d}() on low-precision operand(s) without "
                        "preferred_element_type — accumulates in the "
                        "operand dtype on the MXU",
                        path, node.lineno, node.col_offset))
            elif d in _EXP_NAMES and node.args and _is_lowprec(
                    node.args[0], env):
                findings.append(Finding(
                    ATP302,
                    f"{d}() on a sub-fp32 operand — softmax/exp "
                    "accumulators must be fp32",
                    path, node.lineno, node.col_offset))
        elif isinstance(node, ast.BinOp) and isinstance(node.op,
                                                        ast.MatMult):
            if (_is_lowprec(node.left, env)
                    or _is_lowprec(node.right, env)):
                findings.append(Finding(
                    ATP301,
                    "@ (matmul) on low-precision operand(s) — use "
                    "dot_general with preferred_element_type=float32",
                    path, node.lineno, node.col_offset))
    for node in walk:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _check_scope(node, env, path, findings)


#: id(module tree) -> (tree, flattened module scope) — the module-level
#: statement list is re-read once per function during the check pass
_MODULE_SCOPE_CACHE: dict[int, tuple] = {}


def _module_scope_list(tree: ast.Module) -> list:
    hit = _MODULE_SCOPE_CACHE.get(id(tree))
    if hit is not None and hit[0] is tree:
        return hit[1]
    nodes = list(_module_scope(tree))
    if len(_MODULE_SCOPE_CACHE) >= 1024:
        _MODULE_SCOPE_CACHE.clear()
    _MODULE_SCOPE_CACHE[id(tree)] = (tree, nodes)
    return nodes


def _module_scope(tree: ast.Module):
    """Module-level statements, not descending into function bodies."""
    stack = list(ast.iter_child_nodes(tree))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _helper_dot_hit(index, qual: str, lp_pos: tuple[int, ...],
                    lp_kw: tuple[str, ...],
                    memo: dict) -> tuple[str, int] | None:
    """Does seeding ``qual``'s named/positional params as low-precision
    reach a dot without preferred_element_type (or a sub-fp32 exp)?
    Returns (code, helper lineno) for the first hit."""
    key = (qual, lp_pos, lp_kw)
    if key in memo:
        return memo[key]
    memo[key] = None  # cycle guard (helper aliasing back)
    helper = index.functions.get(qual)
    if helper is None:
        return None
    a = helper.node.args
    names = [p.arg for p in a.posonlyargs + a.args]
    if helper.cls and names and names[0] in ("self", "cls"):
        names = names[1:]
    all_names = set(names) | {p.arg for p in a.kwonlyargs}
    seed = {names[i]: True for i in lp_pos if i < len(names)}
    seed.update({k: True for k in lp_kw if k in all_names})
    if not seed:
        return None
    env = _scope_env(helper.node, seed)
    hit = None
    for node in scope_list(helper.node):
        if isinstance(node, ast.Call):
            d = dotted_name(node.func) or ""
            leaf = d.split(".")[-1]
            if leaf in _DOT_LEAVES and not _has_kw(
                    node, "preferred_element_type"):
                operands = (node.args[1:] if leaf == "einsum"
                            else node.args[:2])
                if any(_is_lowprec(x, env) for x in operands):
                    hit = (ATP301, node.lineno)
                    break
            elif d in _EXP_NAMES and node.args and _is_lowprec(
                    node.args[0], env):
                hit = (ATP302, node.lineno)
                break
        elif isinstance(node, ast.BinOp) and isinstance(node.op,
                                                        ast.MatMult):
            if (_is_lowprec(node.left, env)
                    or _is_lowprec(node.right, env)):
                hit = (ATP301, node.lineno)
                break
    memo[key] = hit
    return hit


def _check_traced_helpers(fn, env: dict[str, bool], path: str, index,
                          memo: dict, findings: list[Finding]) -> None:
    """One call-graph level out of a traced body: low-precision args
    flowing into an in-tree helper that dots them."""
    env = _scope_env(fn, env)
    nodes = _scope_nodes(fn)
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        d = dotted_name(node.func) or ""
        if d.split(".")[-1] in _DOT_LEAVES or d in _EXP_NAMES:
            continue  # the direct checks own these
        lp_pos = tuple(i for i, x in enumerate(node.args)
                       if _is_lowprec(x, env))
        lp_kw = tuple(sorted(kw.arg for kw in node.keywords
                             if kw.arg and _is_lowprec(kw.value, env)))
        if not lp_pos and not lp_kw:
            continue
        callee, name = index.resolve_call(path, None, node)
        if callee is None:
            continue
        hit = _helper_dot_hit(index, callee, lp_pos, lp_kw, memo)
        if hit is None:
            continue
        code, hline = hit
        helper = index.functions[callee]
        what = ("dots it without preferred_element_type"
                if code == ATP301 else "exponentiates it sub-fp32")
        findings.append(Finding(
            code,
            f"low-precision operand flows into helper "
            f"{helper.name!r} ({helper.path}:{hline}) which {what}",
            path, node.lineno, node.col_offset))
    for node in nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _check_traced_helpers(node, env, path, index, memo, findings)


@file_pass("precision", [ATP301, ATP302], needs_index=True)
def check_precision(path: str, tree: ast.Module, src: str, index=None):
    """Low-precision dots without fp32 accumulation; sub-fp32 softmax."""
    findings: list[Finding] = []
    _check_scope(tree, {}, path, findings)
    if index is not None:
        from attention_tpu.analysis.purity import traced_functions

        memo: dict = {}
        menv = _scope_env(tree, {})
        for fn in traced_functions(tree):
            _check_traced_helpers(fn, menv, path, index, memo, findings)
    return findings
