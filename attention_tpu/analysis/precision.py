"""Precision passes: silent low-precision accumulation.

On the MXU, ``dot(bf16, bf16)`` accumulates in bf16 unless the call
asks for fp32 (``preferred_element_type``) — numerically the single
most expensive thing to forget in an attention kernel, and invisible
until a chaos campaign trips a tolerance three layers downstream.
``exp``/``softmax`` in sub-fp32 is the same hazard on the VPU side:
the online-softmax running max/sum must live in fp32 (the contract
ops/flash.py states in prose).

Lexical inference, two triggers, no guessing:

- an operand expression that is literally ``<x>.astype(<lowprec>)``
  (or ``jnp.asarray/zeros/... (..., dtype=<lowprec>)``);
- a Name assigned from such an expression earlier in the same (or an
  enclosing) function scope.

An explicit ``.astype`` to fp32+ marks a name clean again, so the
``k32 = k.astype(jnp.float32)`` idiom never fires.
"""

from __future__ import annotations

import ast

from attention_tpu.analysis.core import (
    Finding,
    Severity,
    dotted_name,
    file_pass,
    iter_scope,
    register_code,
)

ATP301 = register_code(
    "ATP301", "lowprec-dot-no-preferred-type", Severity.ERROR,
    "dot/dot_general/einsum/matmul/@ on bf16/fp16/int8/int4 operands "
    "without preferred_element_type — accumulates in low precision")
ATP302 = register_code(
    "ATP302", "sub-fp32-exp-softmax", Severity.WARNING,
    "exp/exp2/softmax computed on a sub-fp32 operand — the softmax "
    "accumulator must be fp32")

#: dtypes whose accumulation needs an explicit preferred_element_type
_LOWPREC = {"bfloat16", "float16", "int8", "int4", "uint8", "float8_e4m3fn",
            "float8_e5m2"}
#: dot-like callables, by trailing attribute
_DOT_LEAVES = {"dot", "dot_general", "matmul", "einsum"}
#: constructors whose dtype= kwarg fixes the result dtype
_CTOR_LEAVES = {"asarray", "array", "zeros", "ones", "full", "empty",
                "zeros_like", "ones_like", "full_like", "empty_like"}
_EXP_NAMES = {"jnp.exp", "jnp.exp2", "jnp.softmax", "jax.nn.softmax",
              "nn.softmax", "jax.lax.exp", "lax.exp"}


def _dtype_of(node: ast.expr) -> str | None:
    from attention_tpu.analysis.pallas import _dtype_literal

    return _dtype_literal(node)


def _explicit_dtype(call: ast.Call) -> str | None:
    """The literal dtype an .astype()/constructor call pins, if any."""
    d = dotted_name(call.func)
    if isinstance(call.func, ast.Attribute) and call.func.attr == "astype":
        if call.args:
            return _dtype_of(call.args[0])
        return None
    if d and d.split(".")[-1] in _CTOR_LEAVES:
        for kw in call.keywords:
            if kw.arg == "dtype":
                return _dtype_of(kw.value)
    return None


def _is_lowprec(node: ast.expr, env: dict[str, bool]) -> bool:
    """True when ``node`` is inferably a low-precision array."""
    if isinstance(node, ast.Name):
        return env.get(node.id, False)
    if isinstance(node, ast.Call):
        dt = _explicit_dtype(node)
        if dt is not None:
            return dt in _LOWPREC
        return False
    if isinstance(node, ast.BinOp):
        return (_is_lowprec(node.left, env)
                or _is_lowprec(node.right, env))
    if isinstance(node, ast.UnaryOp):
        return _is_lowprec(node.operand, env)
    return False


def _scope_env(fn, inherited: dict[str, bool]) -> dict[str, bool]:
    """Name -> is-low-precision, from assignments in ``fn``'s scope."""
    env = dict(inherited)
    nodes = (iter_scope(fn) if not isinstance(fn, ast.Module)
             else _module_scope(fn))
    for node in nodes:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            if isinstance(node.value, ast.Call):
                dt = _explicit_dtype(node.value)
                if dt is not None:
                    env[tgt.id] = dt in _LOWPREC
                    continue
            env[tgt.id] = _is_lowprec(node.value, env)
    return env


def _has_kw(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


def _check_scope(fn, inherited: dict[str, bool], path: str,
                 findings: list[Finding]) -> None:
    env = _scope_env(fn, inherited)
    walk = (iter_scope(fn) if not isinstance(fn, ast.Module)
            else _module_scope(fn))
    for node in walk:
        if isinstance(node, ast.Call):
            d = dotted_name(node.func) or ""
            leaf = d.split(".")[-1]
            if leaf in _DOT_LEAVES and not _has_kw(
                    node, "preferred_element_type"):
                operands = (node.args[1:] if leaf == "einsum"
                            else node.args[:2])
                if any(_is_lowprec(a, env) for a in operands):
                    findings.append(Finding(
                        ATP301,
                        f"{d}() on low-precision operand(s) without "
                        "preferred_element_type — accumulates in the "
                        "operand dtype on the MXU",
                        path, node.lineno, node.col_offset))
            elif d in _EXP_NAMES and node.args and _is_lowprec(
                    node.args[0], env):
                findings.append(Finding(
                    ATP302,
                    f"{d}() on a sub-fp32 operand — softmax/exp "
                    "accumulators must be fp32",
                    path, node.lineno, node.col_offset))
        elif isinstance(node, ast.BinOp) and isinstance(node.op,
                                                        ast.MatMult):
            if (_is_lowprec(node.left, env)
                    or _is_lowprec(node.right, env)):
                findings.append(Finding(
                    ATP301,
                    "@ (matmul) on low-precision operand(s) — use "
                    "dot_general with preferred_element_type=float32",
                    path, node.lineno, node.col_offset))
    children = (iter_scope(fn) if not isinstance(fn, ast.Module)
                else _module_scope(fn))
    for node in children:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _check_scope(node, env, path, findings)


def _module_scope(tree: ast.Module):
    """Module-level statements, not descending into function bodies."""
    stack = list(ast.iter_child_nodes(tree))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


@file_pass("precision", [ATP301, ATP302])
def check_precision(path: str, tree: ast.Module, src: str):
    """Low-precision dots without fp32 accumulation; sub-fp32 softmax."""
    findings: list[Finding] = []
    _check_scope(tree, {}, path, findings)
    return findings
