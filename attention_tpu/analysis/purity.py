"""Trace-purity passes: impure Python inside traced code.

A ``@jax.jit`` body and a Pallas kernel body run at *trace* time —
once, on abstract values — so host-side effects inside them are
hazards, not features: ``time.time()`` stamps the trace not the step,
``np.random`` freezes one sample into the compiled graph, ``print``
fires per-trace, and mutation of captured state leaks staleness
across retraces.  All of it is decidable lexically, which is the whole
point of catching it here rather than three layers into a chaos run.

Traced scopes are found two ways:

- functions decorated ``@jax.jit`` / ``@jit`` /
  ``@(functools.)partial(jax.jit, ...)``;
- kernel functions passed (directly or via ``functools.partial``) as
  the first argument of a ``pl.pallas_call``; a Name that resolves to
  a module-level ``x = partial(kernel, ...)`` alias follows through.

Nested functions inside a traced scope are traced too (the ``@pl.when``
idiom), and their *captured-ref* stores (``acc_scr[...] = ...`` where
``acc_scr`` is the enclosing kernel's parameter) are pure by design —
the binding environment is threaded down the lexical chain so only
stores whose root name is bound in no enclosing traced scope fire.

With the project index (``needs_index``) the pass also traverses the
call graph ONE level: a helper called from a traced body runs at trace
time too, so an impure call (or global/nonlocal mutation) inside the
helper is reported at the call site in the traced scope — closing the
wrapper-function blind spot.  One level, bounded: helpers of helpers
are out of scope by design.
"""

from __future__ import annotations

import ast

from attention_tpu.analysis.core import (
    Finding,
    Severity,
    dotted_name,
    file_pass,
    iter_scope,
    walk_list,
    register_code,
)

ATP101 = register_code(
    "ATP101", "impure-call-under-trace", Severity.ERROR,
    "time/np.random/print/open-style host call lexically inside a "
    "@jax.jit function or Pallas kernel body")
ATP102 = register_code(
    "ATP102", "host-coercion-under-trace", Severity.WARNING,
    ".item() or float(tracer) inside traced code — forces a "
    "device->host sync (or a trace-time concretization error)")
ATP103 = register_code(
    "ATP103", "state-mutation-under-trace", Severity.ERROR,
    "global/nonlocal statement, or store through a name captured from "
    "outside the traced scope")

#: ``time.<attr>`` calls that read host clocks / sleep
_TIME_ATTRS = {"time", "time_ns", "perf_counter", "perf_counter_ns",
               "monotonic", "monotonic_ns", "process_time", "sleep"}
#: bare-name calls that are host effects wherever they appear
_IMPURE_NAMES = {"print", "input", "breakpoint", "open"}


def _is_jit_expr(node: ast.expr) -> bool:
    """`jax.jit` / bare `jit` (as a decorator or a partial target)."""
    d = dotted_name(node)
    return d in ("jit", "jax.jit")


def _is_jit_decorator(dec: ast.expr) -> bool:
    if _is_jit_expr(dec):
        return True
    if isinstance(dec, ast.Call):
        # @jax.jit(static_argnums=...) or @partial(jax.jit, ...)
        if _is_jit_expr(dec.func):
            return True
        d = dotted_name(dec.func)
        if d in ("partial", "functools.partial") and dec.args:
            return _is_jit_expr(dec.args[0])
    return False


def _kernel_arg_name(node: ast.expr) -> str | None:
    """The kernel name in a ``pallas_call`` first argument: a bare
    Name, or the first argument of a ``partial(...)`` wrapper."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        d = dotted_name(node.func)
        if d in ("partial", "functools.partial") and node.args:
            if isinstance(node.args[0], ast.Name):
                return node.args[0].id
    return None


_TRACED_CACHE: dict[int, tuple[ast.Module, list]] = {}


def traced_functions(tree: ast.Module) -> list[ast.FunctionDef]:
    """Top-level traced scopes: jit-decorated defs + Pallas kernels.

    Memoized by tree identity — purity and precision both call this on
    the same parsed module in one analyze() run."""
    hit = _TRACED_CACHE.get(id(tree))
    if hit is not None and hit[0] is tree:
        return hit[1]
    defs: dict[str, list] = {}
    aliases: dict[str, str] = {}  # x = partial(kernel, ...) at any level
    for node in walk_list(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name) and isinstance(node.value, ast.Call):
                k = _kernel_arg_name(node.value)
                if k:
                    aliases[tgt.id] = k

    out: list[ast.FunctionDef] = []
    seen: set[int] = set()

    def add(fn):
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append(fn)

    for node in walk_list(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                add(node)
        elif isinstance(node, ast.Call):
            if dotted_name(node.func) in ("pallas_call", "pl.pallas_call",
                                          "pallas.pallas_call") and node.args:
                name = _kernel_arg_name(node.args[0])
                name = aliases.get(name, name)
                for fn in defs.get(name or "", []):
                    add(fn)
    if len(_TRACED_CACHE) >= 512:
        _TRACED_CACHE.clear()
    _TRACED_CACHE[id(tree)] = (tree, out)
    return out


def _bound_names(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> set[str]:
    """Names bound in ``fn``'s own scope: parameters plus plain-Name
    binding sites (assignments, for/with targets, comprehensions,
    nested defs, imports) — not through nested function bodies."""
    a = fn.args
    bound = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
    for p in (a.vararg, a.kwarg):
        if p:
            bound.add(p.arg)
    for node in iter_scope(fn):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            bound.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                bound.add((alias.asname or alias.name).split(".")[0])
    return bound


def _store_root(node: ast.expr) -> ast.expr:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node


def _impure_call(node: ast.Call) -> str | None:
    """A human-readable culprit when ``node`` is an impure host call."""
    d = dotted_name(node.func)
    if d is None:
        return None
    parts = d.split(".")
    if d in _IMPURE_NAMES:
        return f"{d}()"
    if parts[0] == "time" and parts[-1] in _TIME_ATTRS:
        return f"{d}()"
    if parts[0] in ("np", "numpy") and len(parts) > 1 and parts[1] == "random":
        return f"{d}()"
    if parts[0] == "random" and len(parts) > 1:
        return f"{d}()"
    if parts[0] == "os" and parts[-1] == "urandom":
        return f"{d}()"
    if parts[0] in ("datetime",) and parts[-1] in ("now", "utcnow", "today"):
        return f"{d}()"
    return None


def _check_scope(fn, inherited: set[str], where: str, path: str,
                 findings: list[Finding]) -> None:
    """Flag hazards in ``fn``'s own scope, then recurse into nested
    functions with the accumulated binding environment."""
    bound = inherited | _bound_names(fn)
    for node in iter_scope(fn):
        if isinstance(node, ast.Call):
            culprit = _impure_call(node)
            if culprit:
                findings.append(Finding(
                    ATP101,
                    f"impure host call {culprit} inside {where} — "
                    "runs at trace time, not per step",
                    path, node.lineno, node.col_offset))
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item" and not node.args):
                findings.append(Finding(
                    ATP102,
                    f".item() inside {where} — device->host sync / "
                    "trace-time concretization",
                    path, node.lineno, node.col_offset))
            elif (isinstance(node.func, ast.Name)
                    and node.func.id == "float"
                    and len(node.args) == 1
                    and not isinstance(node.args[0], ast.Constant)):
                findings.append(Finding(
                    ATP102,
                    f"float(...) coercion inside {where} — "
                    "concretizes a tracer",
                    path, node.lineno, node.col_offset))
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            kw = "global" if isinstance(node, ast.Global) else "nonlocal"
            findings.append(Finding(
                ATP103,
                f"{kw} statement inside {where} — trace-time state "
                "mutation leaks across retraces",
                path, node.lineno, node.col_offset))
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                if not isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    continue
                root = _store_root(tgt)
                if isinstance(root, ast.Name) and root.id not in bound:
                    findings.append(Finding(
                        ATP103,
                        f"store through {root.id!r}, captured from "
                        f"outside {where} — mutates module/closure "
                        "state at trace time",
                        path, tgt.lineno, tgt.col_offset))
    for node in iter_scope(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _check_scope(node, bound, where, path, findings)


def _helper_hazard(fn) -> tuple[str, str, int] | None:
    """The first lexical purity hazard in a helper body:
    (code, culprit, lineno) — or None when the helper is clean."""
    for n in ast.walk(fn):
        if isinstance(n, ast.Call):
            c = _impure_call(n)
            if c:
                return (ATP101, c, n.lineno)
        elif isinstance(n, (ast.Global, ast.Nonlocal)):
            kw = "global" if isinstance(n, ast.Global) else "nonlocal"
            return (ATP103, f"{kw} statement", n.lineno)
    return None


def _check_helpers(fn, where: str, path: str, index,
                   findings: list[Finding]) -> None:
    """One call-graph level: helpers invoked from a traced body run at
    trace time too; report their hazards at the call site."""
    seen: set[str] = set()
    for site in index.sites_in(fn, path):
        if site.callee is None or site.callee in seen:
            continue
        seen.add(site.callee)
        helper = index.functions.get(site.callee)
        if helper is None:
            continue
        hz = _helper_hazard(helper.node)
        if hz is None:
            continue
        code, culprit, hline = hz
        findings.append(Finding(
            code,
            f"helper {helper.name!r} ({helper.path}:{hline}) has "
            f"impure {culprit} and is called from {where} — it runs "
            "at trace time too",
            path, site.lineno, site.col))


@file_pass("purity", [ATP101, ATP102, ATP103], needs_index=True)
def check_purity(path: str, tree: ast.Module, src: str, index=None):
    """Impure host calls / coercions / mutation inside traced scopes."""
    findings: list[Finding] = []
    for fn in traced_functions(tree):
        where = f"traced scope {fn.name!r}"
        _check_scope(fn, set(), where, path, findings)
        if index is not None:
            _check_helpers(fn, where, path, index, findings)
    return findings
