"""Committed benchmark-trajectory gate (ATP506).

Every PR round appends a ``BENCH_r<NN>.json`` at the repo root — the
headline attention benchmark replayed on the then-current tree.  Those
files ARE the performance history, so a silent regression is just a
diff nobody read.  This pass parses the committed trajectory and fails
the gate when the headline kernel time (``parsed.detail.tpu_kernel_ms``)
regresses more than :data:`REGRESSION_PCT` percent between consecutive
rounds.

The gate keys on kernel milliseconds, NOT ``parsed.value``: the value
field is a speedup against a serial CPU baseline whose measurement
basis legitimately changed between rounds (re-measured vs extrapolated
serial time — see r02 -> r03, a 22.5% value drop with the kernel
getting *faster*).  Kernel ms is the only monotone-comparable number
in the trajectory.

Rounds from :data:`PROVENANCE_FROM_ROUND` on must also record *how*
the headline was measured — ``parsed.detail.max_mode`` (the rescaling
math the kernel ran with) and ``parsed.detail.mesh_shards`` (the mesh
layout) — so a future number is only ever compared against one with
the same provenance.  A round missing them is refused outright.

``scripts/bench_trend.py`` is the human-facing shell over the same
functions: prints the per-round trend (ms + MXU), exits nonzero on the
same problems.  `cli analyze` / ``scripts/check_all.py`` run the pass
automatically — registration happens on package import.
"""

from __future__ import annotations

import json
import os
import re

from attention_tpu.analysis.core import (
    Finding,
    Severity,
    project_pass,
    register_code,
)

ATP506 = register_code(
    "ATP506", "bench-trend-regression", Severity.ERROR,
    "committed BENCH_r*.json headline kernel time regressed >10% "
    "between consecutive rounds (or a round is unparsable / missing "
    "its provenance fields)")

#: allowed headline regression between consecutive rounds, percent
REGRESSION_PCT = 10.0

#: provenance fields every round from :data:`PROVENANCE_FROM_ROUND`
#: on must carry in ``parsed.detail`` — a headline number whose
#: measurement mode and mesh layout aren't recorded can't be compared
#: to the next round's.  Earlier rounds are grandfathered (r01/r02
#: predate ``max_mode``; no committed round predates r11 with
#: ``mesh_shards``).
PROVENANCE_FIELDS = ("max_mode", "mesh_shards")
PROVENANCE_FROM_ROUND = 11

_BENCH_RE = re.compile(r"^BENCH_r(\d+)\.json$")


def bench_files(root: str) -> list[tuple[int, str]]:
    """``(round, filename)`` for every committed bench file, by round."""
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return []
    for name in names:
        m = _BENCH_RE.match(name)
        if m:
            out.append((int(m.group(1)), name))
    out.sort()
    return out


def trend_rows(root: str) -> list[dict]:
    """One row per round: the comparable headline numbers (or an
    ``error`` field when a file does not parse into them)."""
    rows = []
    for rnd, name in bench_files(root):
        row: dict = {"round": rnd, "file": name}
        try:
            with open(os.path.join(root, name)) as f:
                doc = json.load(f)
            parsed = doc["parsed"]
            detail = parsed["detail"]
            row["kernel_ms"] = float(detail["tpu_kernel_ms"])
            row["mxu"] = float(detail.get("mxu_utilization_of_peak", 0.0))
            row["value"] = float(parsed.get("value", 0.0))
            if rnd >= PROVENANCE_FROM_ROUND:
                missing = [k for k in PROVENANCE_FIELDS
                           if k not in detail]
                if missing:
                    row["provenance_missing"] = missing
        except (OSError, ValueError, KeyError, TypeError) as e:
            row["error"] = f"{type(e).__name__}: {e}"
        rows.append(row)
    return rows


def trend_problems(root: str) -> list[str]:
    """Regression/parse problems over the committed trajectory
    (legacy-lint strings; empty means the gate passes)."""
    problems = []
    prev = None
    for row in trend_rows(root):
        if "error" in row:
            problems.append(f"{row['file']}: unparsable headline "
                            f"({row['error']})")
            continue
        if row.get("provenance_missing"):
            problems.append(
                f"{row['file']}: missing provenance field(s) "
                f"{', '.join(row['provenance_missing'])} — rounds "
                f">= r{PROVENANCE_FROM_ROUND} must record the "
                "measurement mode and mesh layout in parsed.detail")
        if prev is not None and prev["kernel_ms"] > 0:
            pct = 100.0 * (row["kernel_ms"] - prev["kernel_ms"]) \
                / prev["kernel_ms"]
            if pct > REGRESSION_PCT:
                problems.append(
                    f"{row['file']}: headline kernel time regressed "
                    f"{pct:+.1f}% vs {prev['file']} "
                    f"({prev['kernel_ms']:g} ms -> "
                    f"{row['kernel_ms']:g} ms, budget "
                    f"{REGRESSION_PCT:g}%)")
        prev = row
    return problems


def render_trend(rows: list[dict]) -> list[str]:
    """Human-readable per-round trend lines for the script."""
    out = []
    prev_ms = None
    for row in rows:
        if "error" in row:
            out.append(f"r{row['round']:02d}  {row['file']}: "
                       f"UNPARSABLE ({row['error']})")
            continue
        delta = ""
        if prev_ms:
            pct = 100.0 * (row["kernel_ms"] - prev_ms) / prev_ms
            delta = f"  ({pct:+.1f}%)"
        out.append(f"r{row['round']:02d}  kernel {row['kernel_ms']:7.3f} ms"
                   f"  mxu {row['mxu']:.4f}"
                   f"  speedup {row['value']:9.1f}{delta}")
        prev_ms = row["kernel_ms"]
    return out


@project_pass("bench-trend", [ATP506])
def check_bench_trend(root: str):
    """The committed BENCH_r*.json trajectory has no >10% headline
    kernel-time regression between consecutive rounds."""
    return [Finding(ATP506, p, p.split(":", 1)[0])
            for p in trend_problems(root)]
