"""Determinism-hazard lints (ATP801-804) over the interprocedural core.

Every fleet guarantee — token parity under chaos, byte-identical
``slo_report()``/traces/digests, warm-recovery parity — reduces to
*same seed, byte-identical execution*.  The chaos invariants enforce
that dynamically; this pass family flags the classic ways code breaks
it, statically, across call edges (:mod:`callgraph` resolves the
edges, :mod:`dataflow` carries the taint with a depth cap):

- **ATP801** — a wall-clock read (``time.time``/``monotonic``/
  ``perf_counter``, argless ``datetime.now``) reaches a deterministic
  artifact sink (snapshot/journal serialize, trace/SLO/RunRecord
  emission) or steers an engine/frontend scheduling decision.  The
  sanctioned idioms do NOT fire: virtual-clock ticks are not sources,
  and obs instrument writes (``.observe``/``.set``/``.inc`` — the
  ``_SAVE_MS.observe(...)`` shape) are not sinks.
- **ATP802** — unseeded randomness (``random.*`` stdlib global,
  legacy ``np.random.*`` global, argless ``default_rng()``,
  ``os.urandom``/``secrets``/``uuid4``, ``jax.random.PRNGKey`` from a
  non-literal non-threaded seed) created in — or returned by a helper
  into — engine/frontend/chaos code, where every decision must replay
  from the seeded chain.
- **ATP803** — iterating a ``set``/``frozenset`` of non-literal
  origin into an order-sensitive consumer (list/tuple build, ``join``,
  ``enumerate``, early-exit selection, append/yield loops) without an
  enclosing ``sorted()``.  Literal set displays are exempt; ``dict``
  iteration is insertion-ordered on every supported runtime and only
  fires when the dict itself was built over an unordered iterable.
- **ATP804** — float accumulation (``sum``, ``+=`` in a loop) over an
  unordered container: the result depends on hash-iteration order
  (warning — harmless for ints/counters, wrong for floats).

Scope is ``attention_tpu/`` only (bench/tests/scripts time things on
purpose); findings honour ``# atp: disable=...`` like any file pass.
"""

from __future__ import annotations

import ast

from attention_tpu.analysis import core
from attention_tpu.analysis.callgraph import CallSite, ProjectIndex
from attention_tpu.analysis.core import (
    Finding,
    Severity,
    project_pass,
    register_code,
    walk_list,
)
from attention_tpu.analysis.dataflow import (
    TaintAnalysis,
    _join,
    iter_stmts_ordered,
    ordered_stmts,
)

ATP801 = register_code(
    "ATP801", "wall-clock-into-artifact", Severity.ERROR,
    "a wall-clock read reaches a deterministic artifact sink or "
    "scheduling decision (breaks same-seed byte-identical replay)")
ATP802 = register_code(
    "ATP802", "unseeded-randomness", Severity.ERROR,
    "unseeded randomness (stdlib/np-legacy global RNG, os.urandom, "
    "non-threaded PRNGKey) enters engine/frontend/chaos decision paths")
ATP803 = register_code(
    "ATP803", "unordered-iteration", Severity.ERROR,
    "iteration over a set/frozenset of non-literal origin feeds an "
    "order-sensitive consumer without an enclosing sorted()")
ATP804 = register_code(
    "ATP804", "unordered-float-accumulation", Severity.WARNING,
    "float accumulation (sum / += in a loop) over an unordered "
    "container — result depends on hash-iteration order")

#: the determinism surface: serving code, not the harnesses that
#: legitimately time/randomize (bench.py, tests/, scripts/)
_SCOPE = "attention_tpu/"
#: dirs where a tainted branch condition is a scheduling decision
_DECISION_DIRS = ("attention_tpu/engine/", "attention_tpu/frontend/")
#: dirs whose decisions must replay from the seeded chain
_RNG_DIRS = ("attention_tpu/engine/", "attention_tpu/frontend/",
             "attention_tpu/chaos/")

# -- ATP801: wall clock ---------------------------------------------------

_WALL = {
    "time.time", "time.time_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.process_time", "time.process_time_ns",
}
_NOW_LEAVES = {"now", "utcnow", "today"}

#: final call-name segments that emit deterministic artifacts (the
#: repo's serialize/record/trace surface); obs instrument methods
#: (.observe/.set/.inc/.add) are deliberately absent — that channel is
#: the sanctioned save_ms-style home for wall timings
_ARTIFACT_LEAVES = {
    "serialize", "save_trace", "write_jsonl", "write_slo",
    "append_jsonl", "write_repro_json", "write_repro_bin",
    "write_testcase", "record", "record_event", "record_run",
    "record_step", "record_request", "record_admit", "record_token",
    "record_cancel", "record_finish", "record_timeout",
    "to_run_record",
}
_ARTIFACT_CANON = {"json.dumps", "json.dump"}


def _wall_source(site: CallSite) -> str | None:
    n = site.name
    if not n:
        return None
    if n in _WALL:
        return n
    if n.startswith("datetime.") and n.rsplit(".", 1)[-1] in _NOW_LEAVES \
            and not site.node.args and not site.node.keywords:
        return n
    return None


def _artifact_sink(site: CallSite) -> str | None:
    n = site.name or ""
    if n in _ARTIFACT_CANON:
        return n
    leaf = n.rsplit(".", 1)[-1]
    if leaf in _ARTIFACT_LEAVES:
        return leaf
    return None


def _candidates(index: ProjectIndex, max_depth: int, source_fn,
                *, setcomps: bool = False) -> set[str]:
    """Function quals that could possibly observe this spec's taint:
    they contain a source call (or set comprehension), live in a module
    with a module-level source, share a class with such a method (taint
    threads through ``self.*``), or transitively call one within the
    depth cap.  Everything else is provably clean under the spec, so
    the expensive env construction skips it."""
    base: set[str] = set()
    for qual, sites in index.calls.items():
        for s in sites:
            if source_fn(s):
                base.add(qual)
                break
    if setcomps:
        # one cached module flatten instead of an ast.walk per function;
        # line-span containment attributes each comprehension (function
        # source regions are disjoint, so spans are exact)
        comp_lines: dict[str, list[int]] = {}
        for path, mod in index.modules.items():
            lines = [n.lineno for n in walk_list(mod.tree)
                     if isinstance(n, ast.SetComp)]
            if lines:
                comp_lines[path] = lines
        if comp_lines:
            for info in index.functions.values():
                lines = comp_lines.get(info.path)
                if lines is None or info.qual in base:
                    continue
                start = info.node.lineno
                for dec in info.node.decorator_list:
                    start = min(start, dec.lineno)
                end = info.node.end_lineno or start
                if any(start <= ln <= end for ln in lines):
                    base.add(info.qual)
    mod_paths: set[str] = set()
    for path, mod in index.modules.items():
        for node in ordered_stmts(index, mod.tree):
            if isinstance(node, ast.Call):
                callee, name = index.resolve_call(path, None, node)
                site = CallSite("<module>", callee, name, node.lineno,
                                node.col_offset, node)
                if source_fn(site):
                    mod_paths.add(path)
                    break
            elif setcomps and isinstance(node, ast.SetComp):
                mod_paths.add(path)
                break
    for info in index.functions.values():
        if info.path in mod_paths:
            base.add(info.qual)
    for _ in range(max_depth + 1):
        new: set[str] = set()
        for q in sorted(base):
            new |= index.callers.get(q, set()) - base
            info = index.functions.get(q)
            if info is not None and info.cls:
                for m in index.classes[info.cls].methods.values():
                    if m.qual not in base:
                        new.add(m.qual)
        if not new:
            break
        base |= new
    return base


def _arg_label(ta: TaintAnalysis, call: ast.Call, env, path, cls) -> str:
    parts = [ta.taint_of(a, env, path, cls, ta.max_depth)
             for a in call.args]
    parts += [ta.taint_of(kw.value, env, path, cls, ta.max_depth)
              for kw in call.keywords]
    return _join(*parts) or "wall-clock"


def _run_atp801(index: ProjectIndex, findings: list[Finding]) -> None:
    ta = TaintAnalysis(index, source=_wall_source, sink=_artifact_sink)
    cands = _candidates(index, ta.max_depth, _wall_source)
    for info in index.functions.values():
        if not info.path.startswith(_SCOPE) or info.qual not in cands:
            continue
        env = ta.function_env(info)
        decide = info.path.startswith(_DECISION_DIRS)
        for node in ordered_stmts(index, info.node):
            if isinstance(node, ast.Call):
                kind = ta.sink_hit(node, env, info.path, info.cls,
                                   ta.max_depth)
                if kind:
                    lb = _arg_label(ta, node, env, info.path, info.cls)
                    findings.append(Finding(
                        ATP801,
                        f"wall-clock value ({lb}) reaches deterministic "
                        f"artifact sink `{kind}`",
                        info.path, node.lineno, node.col_offset))
            elif decide and isinstance(node, (ast.If, ast.While)):
                lb = ta.taint_of(node.test, env, info.path, info.cls,
                                 ta.max_depth)
                if lb:
                    findings.append(Finding(
                        ATP801,
                        f"wall-clock value ({lb}) steers a scheduling "
                        f"decision (non-replayable branch)",
                        info.path, node.lineno, node.col_offset))


# -- ATP802: unseeded randomness ------------------------------------------

_NP_SEEDED = {"default_rng", "Generator", "SeedSequence", "PCG64",
              "Philox", "MT19937", "RandomState", "bit_generator"}
_SEED_TOKENS = ("seed", "key", "rng")


def _threaded_seed(call: ast.Call) -> bool:
    """PRNGKey(x): literal seed, or an expression over names that carry
    the seed chain (``seed``/``key``/``rng`` in the name)."""
    exprs = list(call.args) + [kw.value for kw in call.keywords]
    if not exprs:
        return False
    for arg in exprs:
        if isinstance(arg, ast.Constant):
            continue
        toks = [n.id.lower() for n in ast.walk(arg)
                if isinstance(n, ast.Name)]
        toks += [n.attr.lower() for n in ast.walk(arg)
                 if isinstance(n, ast.Attribute)]
        if not any(t for t in toks
                   for s in _SEED_TOKENS if s in t):
            return False
    return True


def _rng_source(site: CallSite) -> str | None:
    n = site.name or ""
    if not n:
        return None
    if n == "os.urandom" or n == "uuid.uuid4" or n.startswith("secrets."):
        return n
    if n in ("jax.random.PRNGKey", "jax.random.key"):
        return None if _threaded_seed(site.node) else n
    if n.startswith("random."):
        leaf = n.split(".", 1)[1]
        if leaf == "SystemRandom":
            return n
        if leaf == "Random":
            return n if not site.node.args and not site.node.keywords \
                else None
        if "." not in leaf and leaf[:1].islower() and leaf != "seed":
            return n  # the module-global functions: random.random(), ...
        return None
    if n.startswith("numpy.random."):
        leaf = n.rsplit(".", 1)[-1]
        if leaf == "default_rng":
            return n if not site.node.args and not site.node.keywords \
                else None
        if leaf in _NP_SEEDED:
            return None
        return n  # legacy global: np.random.normal() etc.
    return None


def _run_atp802(index: ProjectIndex, findings: list[Finding]) -> None:
    ta = TaintAnalysis(index, source=_rng_source)
    for info in index.functions.values():
        if not info.path.startswith(_RNG_DIRS):
            continue
        for node in ordered_stmts(index, info.node):
            if not isinstance(node, ast.Call):
                continue
            site = ta._site(node, info.path, info.cls)
            lb = _rng_source(site)
            if lb:
                findings.append(Finding(
                    ATP802,
                    f"unseeded randomness `{lb}` in a replay-critical "
                    f"path — thread the seeded chain instead",
                    info.path, node.lineno, node.col_offset))
            elif site.callee is not None:
                lb = ta.returns_taint(site.callee, ta.max_depth - 1)
                if lb:
                    findings.append(Finding(
                        ATP802,
                        f"`{site.name}` returns a value derived from "
                        f"unseeded randomness (`{lb}`)",
                        info.path, node.lineno, node.col_offset))
    for path, mod in index.modules.items():
        if not path.startswith(_RNG_DIRS):
            continue
        for node in ordered_stmts(index, mod.tree):
            if isinstance(node, ast.Call):
                lb = _rng_source(ta._site(node, path, None))
                if lb:
                    findings.append(Finding(
                        ATP802,
                        f"unseeded randomness `{lb}` at module scope in "
                        f"a replay-critical path",
                        path, node.lineno, node.col_offset))


# -- ATP803/804: unordered iteration & accumulation -----------------------

_ORDER_SINK_LEAVES = {"list", "tuple", "enumerate", "join"}
#: consumers whose result is independent of iteration order — their
#: comprehension/genexp arguments are exempt from ATP803
_ORDER_FREE = {"sorted", "min", "max", "sum", "len", "any", "all",
               "set", "frozenset"}


def _unordered_source(site: CallSite) -> str | None:
    n = site.name or ""
    if n in ("set", "frozenset"):
        return n
    return None


def _unordered_expr(node: ast.expr, taint_of) -> str | None:
    if isinstance(node, ast.SetComp):
        return "set-comprehension"
    return None


def _is_sorted(site: CallSite) -> bool:
    return (site.name or "") == "sorted"


def _loop_order_sensitivity(loop: ast.For) -> str | None:
    """How the loop body consumes iteration order: ``early-exit``
    (break/return selects the first hit), ``ordered-build``
    (append/yield preserves arrival order), ``accumulate`` (``+=``),
    or None (order-free body, e.g. pure membership adds)."""
    aug = False
    for stmt in loop.body:
        for n in [stmt, *iter_stmts_ordered(stmt)]:
            if isinstance(n, (ast.Break, ast.Return)):
                return "early-exit"
            if isinstance(n, (ast.Yield, ast.YieldFrom)):
                return "ordered-build"
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in ("append", "extend", "write"):
                return "ordered-build"
            if isinstance(n, ast.AugAssign) and isinstance(n.op, ast.Add):
                aug = True
    return "accumulate" if aug else None


def _run_atp803(index: ProjectIndex, findings: list[Finding]) -> None:
    ta = TaintAnalysis(index, source=_unordered_source,
                       sanitizer=_is_sorted, expr_source=_unordered_expr,
                       taint_loop_var=False)
    cands = _candidates(index, ta.max_depth, _unordered_source,
                        setcomps=True)
    for info in index.functions.values():
        if not info.path.startswith(_SCOPE) or info.qual not in cands:
            continue
        env = ta.function_env(info)
        exempt: set[int] = set()
        for node in ordered_stmts(index, info.node):
            if isinstance(node, ast.Call):
                leaf = (ta._site(node, info.path, info.cls).name
                        or "").rsplit(".", 1)[-1]
                if leaf in _ORDER_FREE:
                    for a in node.args:
                        exempt.add(id(a))
        for node in ordered_stmts(index, info.node):
            if isinstance(node, ast.Call):
                site = ta._site(node, info.path, info.cls)
                leaf = (site.name or "").rsplit(".", 1)[-1]
                if leaf in _ORDER_SINK_LEAVES:
                    lb = _join(*(ta.taint_of(a, env, info.path, info.cls,
                                             ta.max_depth)
                                 for a in node.args))
                    if lb:
                        findings.append(Finding(
                            ATP803,
                            f"unordered {lb} feeds order-sensitive "
                            f"`{leaf}` — wrap the iterable in sorted()",
                            info.path, node.lineno, node.col_offset))
                elif leaf == "sum" and node.args:
                    lb = ta.taint_of(node.args[0], env, info.path,
                                     info.cls, ta.max_depth)
                    if lb:
                        findings.append(Finding(
                            ATP804,
                            f"sum() over unordered {lb} — float result "
                            f"depends on hash-iteration order",
                            info.path, node.lineno, node.col_offset))
            elif isinstance(node, ast.ListComp) and id(node) not in exempt:
                lb = ta.taint_of(node.generators[0].iter, env, info.path,
                                 info.cls, ta.max_depth)
                if lb:
                    findings.append(Finding(
                        ATP803,
                        f"list built by iterating unordered {lb} — wrap "
                        f"the iterable in sorted()",
                        info.path, node.lineno, node.col_offset))
            elif isinstance(node, ast.For):
                lb = ta.taint_of(node.iter, env, info.path, info.cls,
                                 ta.max_depth)
                if not lb:
                    continue
                how = _loop_order_sensitivity(node)
                if how in ("early-exit", "ordered-build"):
                    findings.append(Finding(
                        ATP803,
                        f"{how} loop over unordered {lb} — iterate "
                        f"sorted({lb}) instead",
                        info.path, node.lineno, node.col_offset))
                elif how == "accumulate":
                    findings.append(Finding(
                        ATP804,
                        f"accumulation (`+=`) while iterating unordered "
                        f"{lb} — float result depends on hash order",
                        info.path, node.lineno, node.col_offset))


# -- the registered pass --------------------------------------------------

@project_pass("determinism", (ATP801, ATP802, ATP803, ATP804),
              needs_index=True)
def determinism_pass(root: str, index: ProjectIndex | None = None):
    """Wall-clock, RNG, and iteration-order hazards across call edges."""
    if index is None:
        index = core.build_index(root)
    findings: list[Finding] = []
    _run_atp801(index, findings)
    _run_atp802(index, findings)
    _run_atp803(index, findings)
    lines_memo: dict[str, list[str]] = {}
    out = []
    for f in findings:
        mod = index.modules.get(f.path)
        if mod is not None:
            if f.path not in lines_memo:
                lines_memo[f.path] = mod.src.splitlines()
            if core.is_suppressed(f, lines_memo[f.path]):
                continue
        out.append(f)
    return out
