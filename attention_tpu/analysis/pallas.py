"""Pallas contract passes: block/grid/out_shape self-consistency.

``pl.pallas_call`` is a contract with the compiler — the grid rank,
each ``BlockSpec``'s block shape, its ``index_map`` arity and return
arity, and the ``out_shape`` dtype all have to agree — but Pallas
reports violations at trace/lowering time with errors that point
nowhere near the offending spec.  Two evidence tiers are checked here:

- **Literal** (ATP201-204): every component is spelled as a literal at
  the call site.  Anything else is skipped rather than guessed at.
- **Symbolic** (ATP902): components bound to *variables* are resolved
  through the ``shapes.ShapeInterp`` scope environment — constant
  propagation through assignments, tuples, and NamedTuple fields
  (``BlockSizes().block_q``).  A finding still requires a provable
  violation: a dim that resolves to a concrete int breaking the rule.
  Dims that stay symbolic are checked against the harvested
  divisibility facts (``assert block_q % 128 == 0`` certifies) and
  stay silent either way — facts certify, absence of a fact is not
  evidence.

Checked (all on one ``pallas_call`` call site):

- ATP201 — ``index_map`` lambda arity != literal ``grid`` rank;
- ATP202 — ``BlockSpec`` literal block-shape rank != the index_map's
  literal return-tuple arity (one coordinate per block dimension);
- ATP203 — kernel's final store into an output ref casts to a literal
  dtype that differs from the matching ``out_shape``
  ``ShapeDtypeStruct`` literal dtype (a silent re-cast on store);
- ATP204 — literal block shapes that break TPU tiling: last dim not a
  multiple of 128 (lane), or second-minor not a multiple of 8
  (sublane) — the assumption every kernel in this tree states in its
  docstring, now enforced where it is spelled out as numbers;
- ATP902 — the same grid-rank / block-rank / tiling contracts, proved
  through the symbolic domain when the call site uses variables.

A block shape that breaks both tiling rules on one spec reports once,
as the strictest (lane, %128) finding — one spec, one tile diagnosis.
"""

from __future__ import annotations

import ast

from attention_tpu.analysis.core import (
    Finding,
    Severity,
    dotted_name,
    file_pass,
    register_code,
    walk_list,
)
from attention_tpu.analysis.shapes import (
    _scope_nodes,
    con,
    interp_for,
)

ATP201 = register_code(
    "ATP201", "index-map-arity-vs-grid", Severity.ERROR,
    "BlockSpec index_map takes a different number of arguments than "
    "the pallas_call grid has dimensions")
ATP202 = register_code(
    "ATP202", "block-shape-rank-vs-index-map", Severity.ERROR,
    "BlockSpec block shape rank differs from its index_map's returned "
    "coordinate count")
ATP203 = register_code(
    "ATP203", "out-shape-dtype-mismatch", Severity.WARNING,
    "kernel stores .astype(X) into an output ref whose out_shape "
    "declares dtype Y — silent re-cast on store")
ATP204 = register_code(
    "ATP204", "tile-misalignment", Severity.WARNING,
    "literal block shape breaks TPU tiling (last dim % 128, "
    "second-minor % 8)")
ATP902 = register_code(
    "ATP902", "symbolic-block-grid-mismatch", Severity.WARNING,
    "pallas_call grid/BlockSpec geometry resolved through the symbolic "
    "shape domain provably breaks a contract (grid rank, block rank, "
    "or TPU tiling)")

_PALLAS_CALL = ("pallas_call", "pl.pallas_call", "pallas.pallas_call")
_DTYPE_NAMES = {
    "float32", "float64", "bfloat16", "float16",
    "int32", "int64", "int16", "int8", "int4", "uint8",
    "uint32", "bool_",
}


def _literal_tuple(node: ast.expr) -> list[ast.expr] | None:
    if isinstance(node, (ast.Tuple, ast.List)):
        return list(node.elts)
    return None


def _grid_node(call: ast.Call) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == "grid":
            return kw.value
    return None


def _grid_rank(call: ast.Call) -> int | None:
    node = _grid_node(call)
    if node is None:
        return None
    elts = _literal_tuple(node)
    if elts is not None:
        return len(elts)
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return 1
    return None


def _sym_grid_rank(call: ast.Call, interp, env) -> int | None:
    """Grid rank through the scope env, for non-literal grids only."""
    node = _grid_node(call)
    if node is None or _literal_tuple(node) is not None \
            or isinstance(node, ast.Constant):
        return None
    line = call.lineno
    tup = interp._tuple_of(node, env, line)
    if tup is not None:
        return len(tup)
    # a bare name of unknown kind could be a tuple — only a provably
    # concrete scalar (e.g. ``g = 4``) counts as a rank-1 grid
    d = interp._dim_of(node, env, line, 0)
    if d is not None and d.concrete:
        return 1
    return None


def _dtype_literal(node: ast.expr) -> str | None:
    """'bfloat16' for ``jnp.bfloat16`` / ``np.bfloat16`` / 'bfloat16'."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in _DTYPE_NAMES else None
    d = dotted_name(node)
    if d is None:
        return None
    leaf = d.split(".")[-1]
    return leaf if leaf in _DTYPE_NAMES else None


def _block_specs(call: ast.Call) -> list[tuple[ast.Call, str]]:
    """(BlockSpec call, which-kwarg) literals in in_specs/out_specs."""
    out = []
    for kw in call.keywords:
        if kw.arg not in ("in_specs", "out_specs"):
            continue
        nodes = _literal_tuple(kw.value) or [kw.value]
        for n in nodes:
            if isinstance(n, ast.Call) and (
                    dotted_name(n.func) or "").endswith("BlockSpec"):
                out.append((n, kw.arg))
    return out


def _spec_parts(spec: ast.Call):
    """(block-shape elements | None, index_map lambda | None)."""
    shape = _literal_tuple(spec.args[0]) if spec.args else None
    index_map = None
    if len(spec.args) > 1 and isinstance(spec.args[1], ast.Lambda):
        index_map = spec.args[1]
    for kw in spec.keywords:
        if kw.arg == "index_map" and isinstance(kw.value, ast.Lambda):
            index_map = kw.value
        if kw.arg == "block_shape":
            shape = _literal_tuple(kw.value)
    return shape, index_map


def _spec_shape_node(spec: ast.Call) -> ast.expr | None:
    """The block-shape expression itself (literal or not)."""
    node = spec.args[0] if spec.args else None
    for kw in spec.keywords:
        if kw.arg == "block_shape":
            node = kw.value
    return node


def _lambda_return_arity(lam: ast.Lambda) -> int | None:
    if isinstance(lam.body, ast.Tuple):
        return len(lam.body.elts)
    return None


def _out_shape_dtypes(call: ast.Call) -> list[tuple[int, str]]:
    """(output index, literal dtype) for ShapeDtypeStruct out_shapes."""
    out: list[tuple[int, str]] = []
    for kw in call.keywords:
        if kw.arg != "out_shape":
            continue
        nodes = _literal_tuple(kw.value) or [kw.value]
        for i, n in enumerate(nodes):
            if not (isinstance(n, ast.Call) and (
                    dotted_name(n.func) or "").endswith("ShapeDtypeStruct")):
                continue
            dt_node = n.args[1] if len(n.args) > 1 else None
            for k in n.keywords:
                if k.arg == "dtype":
                    dt_node = k.value
            dt = _dtype_literal(dt_node) if dt_node is not None else None
            if dt:
                out.append((i, dt))
    return out


def _kernel_def(call: ast.Call, tree: ast.Module):
    """The kernel FunctionDef for this call site, when resolvable."""
    from attention_tpu.analysis.purity import _kernel_arg_name

    if not call.args:
        return None
    name = _kernel_arg_name(call.args[0])
    if not name:
        return None
    for node in walk_list(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == name:
                return node
    return None


def _n_inputs(call: ast.Call) -> int | None:
    for kw in call.keywords:
        if kw.arg == "in_specs":
            elts = _literal_tuple(kw.value)
            return len(elts) if elts is not None else None
    return None


def _check_store_dtypes(call: ast.Call, tree: ast.Module, path: str,
                        findings: list[Finding]) -> None:
    """ATP203: final-store astype vs the declared out_shape dtype.

    Pallas positional convention: kernel params are the input refs (one
    per in_spec), then the output refs (one per out_shape entry), then
    scratch.  Only fires when every link in that chain is literal.
    """
    kernel = _kernel_def(call, tree)
    n_in = _n_inputs(call)
    outs = _out_shape_dtypes(call)
    if kernel is None or n_in is None or not outs:
        return
    params = [p.arg for p in kernel.args.args]
    for idx, declared in outs:
        if n_in + idx >= len(params):
            return
        ref = params[n_in + idx]
        for node in ast.walk(kernel):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            if not (isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == ref):
                continue
            val = node.value
            if (isinstance(val, ast.Call)
                    and isinstance(val.func, ast.Attribute)
                    and val.func.attr == "astype" and val.args):
                stored = _dtype_literal(val.args[0])
                if stored and stored != declared:
                    findings.append(Finding(
                        ATP203,
                        f"kernel stores .astype({stored}) into "
                        f"{ref!r} but out_shape declares {declared} — "
                        "the store silently re-casts",
                        path, node.lineno, node.col_offset))


def _pallas_call_scopes(interp) -> dict[int, ast.AST]:
    """id(pallas_call node) -> the lexical scope it executes in."""
    out: dict[int, ast.AST] = {}
    for scope in interp.scopes():
        for n in _scope_nodes(scope):
            if isinstance(n, ast.Call) \
                    and dotted_name(n.func) in _PALLAS_CALL:
                out[id(n)] = scope
    return out


def _spec_dims(spec: ast.Call, shape, interp, env, line):
    """Per-position ``(Dim | None, is_literal)`` for a block shape,
    with non-literal entries resolved through the scope env.  Returns
    None when even the rank is undecidable."""
    if shape is not None:
        dims = []
        for e in shape:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                dims.append((con(e.value), True))
            elif env is not None:
                dims.append((interp._dim_of(e, env, line, 0), False))
            else:
                dims.append((None, False))
        return dims
    if env is None:
        return None
    node = _spec_shape_node(spec)
    if node is None:
        return None
    tup = interp._tuple_of(node, env, line)
    if tup is None:
        return None
    return [(d, False) for d in tup]


def _check_tiles(dims, which, spec, env, path,
                 findings: list[Finding]) -> None:
    """TPU tiling on resolved block dims, deduped to the strictest.

    Literal dims report ATP204, env-resolved concrete dims ATP902; a
    dim that stays symbolic is checked against the divisibility facts
    (a ``% 128 == 0`` fact certifies it) and never fires either way.
    When both the lane and the sublane rule break on one spec, only
    the lane (%128) finding — the stricter contract — is reported.
    """
    lane: Finding | None = None
    sub: Finding | None = None
    d, lit = dims[-1]
    if d is not None and d.concrete and d.coeff > 0 \
            and d.coeff % 128 != 0:
        lane = Finding(
            ATP204 if lit else ATP902,
            f"{which} block shape last dim "
            f"{'is' if lit else 'resolves to'} {d.coeff}, not a "
            "multiple of 128 (TPU lane tiling)",
            path, spec.lineno, spec.col_offset)
    if len(dims) > 1:
        d, lit = dims[-2]
        if d is not None and d.concrete and d.coeff > 0 \
                and d.coeff % 8 != 0 and d.coeff != 1:
            sub = Finding(
                ATP204 if lit else ATP902,
                f"{which} block shape second-minor dim "
                f"{'is' if lit else 'resolves to'} {d.coeff}, not a "
                "multiple of 8 (TPU sublane tiling)",
                path, spec.lineno, spec.col_offset)
    if lane is not None:
        findings.append(lane)
    elif sub is not None:
        findings.append(sub)


def _check_spec(spec: ast.Call, which: str, call: ast.Call,
                grid_rank, sym_grid, interp, env, path: str,
                findings: list[Finding]) -> None:
    line = call.lineno
    shape, index_map = _spec_parts(spec)
    if index_map is not None:
        arity = len(index_map.args.args)
        if grid_rank is not None:
            if arity != grid_rank:
                findings.append(Finding(
                    ATP201,
                    f"{which} index_map takes {arity} argument(s) "
                    f"but the grid has {grid_rank} dimension(s)",
                    path, spec.lineno, spec.col_offset))
        elif sym_grid is not None and arity != sym_grid:
            findings.append(Finding(
                ATP902,
                f"{which} index_map takes {arity} argument(s) but "
                f"the grid resolves to {sym_grid} dimension(s)",
                path, spec.lineno, spec.col_offset))
    dims = _spec_dims(spec, shape, interp, env, line)
    if index_map is not None and dims is not None:
        ret = _lambda_return_arity(index_map)
        if ret is not None and ret != len(dims):
            findings.append(Finding(
                ATP202 if shape is not None else ATP902,
                f"{which} block shape "
                f"{'has' if shape is not None else 'resolves to'} "
                f"{len(dims)} dimension(s) but index_map returns "
                f"{ret} coordinate(s)",
                path, spec.lineno, spec.col_offset))
    if dims:
        _check_tiles(dims, which, spec, env, path, findings)


@file_pass("pallas", [ATP201, ATP202, ATP203, ATP204, ATP902],
           needs_index=True)
def check_pallas(path: str, tree: ast.Module, src: str, index=None):
    """BlockSpec/grid/out_shape self-consistency at pallas_call sites."""
    findings: list[Finding] = []
    interp = None
    call_scopes: dict[int, ast.AST] = {}
    for call in walk_list(tree):
        if not isinstance(call, ast.Call):
            continue
        if dotted_name(call.func) not in _PALLAS_CALL:
            continue
        if interp is None:
            interp = interp_for(path, tree, index)
            call_scopes = _pallas_call_scopes(interp)
        scope = call_scopes.get(id(call))
        env = interp.env(scope) if scope is not None else None
        grid_rank = _grid_rank(call)
        sym_grid = (_sym_grid_rank(call, interp, env)
                    if env is not None and grid_rank is None else None)
        for spec, which in _block_specs(call):
            _check_spec(spec, which, call, grid_rank, sym_grid,
                        interp, env, path, findings)
        _check_store_dtypes(call, tree, path, findings)
    return findings
