"""Pallas contract passes: block/grid/out_shape self-consistency.

``pl.pallas_call`` is a contract with the compiler — the grid rank,
each ``BlockSpec``'s block shape, its ``index_map`` arity and return
arity, and the ``out_shape`` dtype all have to agree — but Pallas
reports violations at trace/lowering time with errors that point
nowhere near the offending spec.  The decidable subset is checked here
lexically, with literal-only matching: any component that is a
variable (computed grids, shared block-size names) is skipped rather
than guessed at.

Checked (all on one ``pallas_call`` call site):

- ATP201 — ``index_map`` lambda arity != literal ``grid`` rank;
- ATP202 — ``BlockSpec`` literal block-shape rank != the index_map's
  literal return-tuple arity (one coordinate per block dimension);
- ATP203 — kernel's final store into an output ref casts to a literal
  dtype that differs from the matching ``out_shape``
  ``ShapeDtypeStruct`` literal dtype (a silent re-cast on store);
- ATP204 — literal block shapes that break TPU tiling: last dim not a
  multiple of 128 (lane), or second-minor not a multiple of 8
  (sublane) — the assumption every kernel in this tree states in its
  docstring, now enforced where it is spelled out as numbers.
"""

from __future__ import annotations

import ast

from attention_tpu.analysis.core import (
    Finding,
    Severity,
    dotted_name,
    file_pass,
    register_code,
    walk_list,
)

ATP201 = register_code(
    "ATP201", "index-map-arity-vs-grid", Severity.ERROR,
    "BlockSpec index_map takes a different number of arguments than "
    "the pallas_call grid has dimensions")
ATP202 = register_code(
    "ATP202", "block-shape-rank-vs-index-map", Severity.ERROR,
    "BlockSpec block shape rank differs from its index_map's returned "
    "coordinate count")
ATP203 = register_code(
    "ATP203", "out-shape-dtype-mismatch", Severity.WARNING,
    "kernel stores .astype(X) into an output ref whose out_shape "
    "declares dtype Y — silent re-cast on store")
ATP204 = register_code(
    "ATP204", "tile-misalignment", Severity.WARNING,
    "literal block shape breaks TPU tiling (last dim % 128, "
    "second-minor % 8)")

_PALLAS_CALL = ("pallas_call", "pl.pallas_call", "pallas.pallas_call")
_DTYPE_NAMES = {
    "float32", "float64", "bfloat16", "float16",
    "int32", "int64", "int16", "int8", "int4", "uint8",
    "uint32", "bool_",
}


def _literal_tuple(node: ast.expr) -> list[ast.expr] | None:
    if isinstance(node, (ast.Tuple, ast.List)):
        return list(node.elts)
    return None


def _grid_rank(call: ast.Call) -> int | None:
    for kw in call.keywords:
        if kw.arg == "grid":
            elts = _literal_tuple(kw.value)
            if elts is not None:
                return len(elts)
            if isinstance(kw.value, ast.Constant) and isinstance(
                    kw.value.value, int):
                return 1
    return None


def _dtype_literal(node: ast.expr) -> str | None:
    """'bfloat16' for ``jnp.bfloat16`` / ``np.bfloat16`` / 'bfloat16'."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in _DTYPE_NAMES else None
    d = dotted_name(node)
    if d is None:
        return None
    leaf = d.split(".")[-1]
    return leaf if leaf in _DTYPE_NAMES else None


def _block_specs(call: ast.Call) -> list[tuple[ast.Call, str]]:
    """(BlockSpec call, which-kwarg) literals in in_specs/out_specs."""
    out = []
    for kw in call.keywords:
        if kw.arg not in ("in_specs", "out_specs"):
            continue
        nodes = _literal_tuple(kw.value) or [kw.value]
        for n in nodes:
            if isinstance(n, ast.Call) and (
                    dotted_name(n.func) or "").endswith("BlockSpec"):
                out.append((n, kw.arg))
    return out


def _spec_parts(spec: ast.Call):
    """(block-shape elements | None, index_map lambda | None)."""
    shape = _literal_tuple(spec.args[0]) if spec.args else None
    index_map = None
    if len(spec.args) > 1 and isinstance(spec.args[1], ast.Lambda):
        index_map = spec.args[1]
    for kw in spec.keywords:
        if kw.arg == "index_map" and isinstance(kw.value, ast.Lambda):
            index_map = kw.value
        if kw.arg == "block_shape":
            shape = _literal_tuple(kw.value)
    return shape, index_map


def _lambda_return_arity(lam: ast.Lambda) -> int | None:
    if isinstance(lam.body, ast.Tuple):
        return len(lam.body.elts)
    return None


def _out_shape_dtypes(call: ast.Call) -> list[tuple[int, str]]:
    """(output index, literal dtype) for ShapeDtypeStruct out_shapes."""
    out: list[tuple[int, str]] = []
    for kw in call.keywords:
        if kw.arg != "out_shape":
            continue
        nodes = _literal_tuple(kw.value) or [kw.value]
        for i, n in enumerate(nodes):
            if not (isinstance(n, ast.Call) and (
                    dotted_name(n.func) or "").endswith("ShapeDtypeStruct")):
                continue
            dt_node = n.args[1] if len(n.args) > 1 else None
            for k in n.keywords:
                if k.arg == "dtype":
                    dt_node = k.value
            dt = _dtype_literal(dt_node) if dt_node is not None else None
            if dt:
                out.append((i, dt))
    return out


def _kernel_def(call: ast.Call, tree: ast.Module):
    """The kernel FunctionDef for this call site, when resolvable."""
    from attention_tpu.analysis.purity import _kernel_arg_name

    if not call.args:
        return None
    name = _kernel_arg_name(call.args[0])
    if not name:
        return None
    for node in walk_list(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == name:
                return node
    return None


def _n_inputs(call: ast.Call) -> int | None:
    for kw in call.keywords:
        if kw.arg == "in_specs":
            elts = _literal_tuple(kw.value)
            return len(elts) if elts is not None else None
    return None


def _check_store_dtypes(call: ast.Call, tree: ast.Module, path: str,
                        findings: list[Finding]) -> None:
    """ATP203: final-store astype vs the declared out_shape dtype.

    Pallas positional convention: kernel params are the input refs (one
    per in_spec), then the output refs (one per out_shape entry), then
    scratch.  Only fires when every link in that chain is literal.
    """
    kernel = _kernel_def(call, tree)
    n_in = _n_inputs(call)
    outs = _out_shape_dtypes(call)
    if kernel is None or n_in is None or not outs:
        return
    params = [p.arg for p in kernel.args.args]
    for idx, declared in outs:
        if n_in + idx >= len(params):
            return
        ref = params[n_in + idx]
        for node in ast.walk(kernel):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            if not (isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == ref):
                continue
            val = node.value
            if (isinstance(val, ast.Call)
                    and isinstance(val.func, ast.Attribute)
                    and val.func.attr == "astype" and val.args):
                stored = _dtype_literal(val.args[0])
                if stored and stored != declared:
                    findings.append(Finding(
                        ATP203,
                        f"kernel stores .astype({stored}) into "
                        f"{ref!r} but out_shape declares {declared} — "
                        "the store silently re-casts",
                        path, node.lineno, node.col_offset))


@file_pass("pallas", [ATP201, ATP202, ATP203, ATP204])
def check_pallas(path: str, tree: ast.Module, src: str):
    """BlockSpec/grid/out_shape self-consistency at pallas_call sites."""
    findings: list[Finding] = []
    for call in walk_list(tree):
        if not isinstance(call, ast.Call):
            continue
        if dotted_name(call.func) not in _PALLAS_CALL:
            continue
        grid_rank = _grid_rank(call)
        for spec, which in _block_specs(call):
            shape, index_map = _spec_parts(spec)
            if index_map is not None and grid_rank is not None:
                arity = len(index_map.args.args)
                if arity != grid_rank:
                    findings.append(Finding(
                        ATP201,
                        f"{which} index_map takes {arity} argument(s) "
                        f"but the grid has {grid_rank} dimension(s)",
                        path, spec.lineno, spec.col_offset))
            if index_map is not None and shape is not None:
                ret = _lambda_return_arity(index_map)
                if ret is not None and ret != len(shape):
                    findings.append(Finding(
                        ATP202,
                        f"{which} block shape has {len(shape)} "
                        f"dimension(s) but index_map returns {ret} "
                        "coordinate(s)",
                        path, spec.lineno, spec.col_offset))
            if shape is not None and len(shape) >= 1:
                dims = [e.value if isinstance(e, ast.Constant)
                        and isinstance(e.value, int) else None
                        for e in shape]
                last, sub = dims[-1], (dims[-2] if len(dims) > 1 else None)
                if last is not None and last % 128 != 0:
                    findings.append(Finding(
                        ATP204,
                        f"{which} block shape last dim {last} is not a "
                        "multiple of 128 (TPU lane tiling)",
                        path, spec.lineno, spec.col_offset))
                if sub is not None and len(dims) > 1 and sub % 8 != 0 \
                        and sub != 1:
                    findings.append(Finding(
                        ATP204,
                        f"{which} block shape second-minor dim {sub} "
                        "is not a multiple of 8 (TPU sublane tiling)",
                        path, spec.lineno, spec.col_offset))
        _check_store_dtypes(call, tree, path, findings)
    return findings
