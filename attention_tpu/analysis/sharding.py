"""shard_map/PartitionSpec geometry passes (ATP903-906).

The runtime already polices mesh geometry — ``MeshConfigError`` at
call time, chaos campaigns after that.  These passes move the
provable part of that contract to lint time, on top of the
``shapes.py`` symbolic domain:

- **ATP903** — a ``PartitionSpec`` longer than the operand's provable
  rank, or a literal axis name that is not among the lexically
  resolvable mesh axes.
- **ATP904** — a dim that a spec provably shards carries no
  ``dim % shards == 0`` fact (the static twin of ``MeshConfigError``:
  the ``if hkv % n_dev: raise`` guard IS the fact; any divisor with a
  matching dividend accepts, because the mesh size is almost never
  statically known).
- **ATP905** — a contraction (``dot``/``einsum``/``sum(axis=...)``)
  over a dimension the in_specs shard, inside a shard_map body that
  provably contains no collective: each shard silently computes a
  partial result.  Silence here is a proof too — it statically pins
  ``parallel/serving.py``'s "zero collectives per-head math" claim.
- **ATP906** — ``out_specs`` structure vs the returned value: a
  literal out_specs tuple whose length differs from a literal returned
  tuple, a spec longer than the provable return rank, or a literal
  axis name unknown to the mesh.  (A single spec against a tuple
  return is a legal pytree prefix — silent.)

Never-guess discipline throughout: specs are only trusted when they
resolve through single-assignment names to literal ``P(...)`` calls;
only *literal string* axis entries count as provably sharded (a
variable entry could be None); mesh axes are only compared when the
mesh expression resolves to ``Mesh(..., (literal, ...))``,
``default_mesh(<literal>)`` or ``hybrid_mesh(<literals>)``; a body is
only "collective-free" when every call in it resolves to something
provably not a collective.  Anything else stays silent.
"""

from __future__ import annotations

import ast

from attention_tpu.analysis.core import (
    Finding,
    Severity,
    dotted_name,
    file_pass,
    register_code,
    scope_list,
)
from attention_tpu.analysis import shapes as _shapes
from attention_tpu.analysis.shapes import (
    ShapeInterp,
    _scope_nodes,
    interp_for,
)

ATP903 = register_code(
    "ATP903", "partition-spec-geometry", Severity.ERROR,
    "PartitionSpec rank exceeds the operand's provable rank, or a "
    "literal spec axis is not a lexically visible mesh axis")
ATP904 = register_code(
    "ATP904", "sharded-dim-no-divisibility-fact", Severity.WARNING,
    "a dim a spec provably shards carries no `dim % shards == 0` "
    "guard/assert fact — the static twin of MeshConfigError")
ATP905 = register_code(
    "ATP905", "cross-shard-reduction-no-collective", Severity.ERROR,
    "contraction over a spec-sharded dim inside a shard_map body with "
    "provably no collective — each shard computes a silent partial")
ATP906 = register_code(
    "ATP906", "out-specs-return-mismatch", Severity.ERROR,
    "shard_map out_specs structure provably disagrees with the "
    "returned value")

#: cross-shard communication primitives: any of these in a body (or a
#: resolvable callee) makes ATP905 unprovable -> silent
_COLLECTIVES = {
    "psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter", "pbroadcast", "pgather",
}
#: module roots whose non-collective calls provably do no cross-shard
#: communication (collective leaves are checked first)
_SAFE_ROOTS = {"jnp", "np", "numpy", "math", "functools"}
_SAFE_BUILTINS = {
    "int", "float", "bool", "str", "len", "range", "min", "max",
    "abs", "round", "sum", "sorted", "tuple", "list", "dict", "set",
    "zip", "enumerate", "isinstance", "getattr", "hasattr", "print",
    "divmod", "slice", "type", "id", "repr", "any", "all",
}
_REDUCE_LEAVES = {"sum", "mean", "prod", "max", "min", "amax", "amin"}
_COLLECTIVE_DEPTH = 3

#: spec entry markers
_VAR = "?"  # a non-literal entry: could be an axis or None


# -- spec / mesh resolution -----------------------------------------------

def _call_leaf(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _single_assigns(scope: ast.AST) -> dict[str, ast.expr]:
    """name -> value for names assigned exactly once in ``scope`` (any
    second write, aug-assign, loop target or walrus disqualifies)."""
    counts: dict[str, int] = {}
    values: dict[str, ast.expr] = {}
    for n in _scope_nodes(scope):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        counts[sub.id] = counts.get(sub.id, 0) + (
                            1 if t is sub and len(n.targets) == 1
                            else 99)
                        if t is sub and len(n.targets) == 1:
                            values[sub.id] = n.value
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            t = n.target
            if isinstance(t, ast.Name):
                counts[t.id] = counts.get(t.id, 0) + 99
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            for sub in ast.walk(n.target):
                if isinstance(sub, ast.Name):
                    counts[sub.id] = counts.get(sub.id, 0) + 99
        elif isinstance(n, ast.NamedExpr):
            if isinstance(n.target, ast.Name):
                counts[n.target.id] = counts.get(n.target.id, 0) + 99
        elif isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                if item.optional_vars is not None:
                    for sub in ast.walk(item.optional_vars):
                        if isinstance(sub, ast.Name):
                            counts[sub.id] = counts.get(sub.id, 0) + 99
    return {k: v for k, v in values.items() if counts.get(k) == 1}


class _Resolver:
    """Single-assignment name dereferencing along a scope chain."""

    def __init__(self, interp: ShapeInterp, scope: ast.AST):
        self.maps: list[dict[str, ast.expr]] = []
        node = scope
        seen = 0
        while node is not None and seen < 8:
            self.maps.append(_single_assigns(node))
            if isinstance(node, ast.Module):
                break
            node = interp._parents.get(id(node))
            seen += 1

    def deref(self, expr: ast.expr, depth: int = 3) -> ast.expr:
        while depth > 0 and isinstance(expr, ast.Name):
            for m in self.maps:
                got = m.get(expr.id)
                if got is not None:
                    expr = got
                    break
            else:
                return expr
            depth -= 1
        return expr


def _spec_entries(expr: ast.expr,
                  res: _Resolver) -> "tuple | None":
    """A ``P(...)`` call -> tuple of entries: None (replicated), a
    literal axis string, or ``_VAR``.  None when not provably a spec or
    when a star makes positions unreliable past it (the tuple is then
    truncated and flagged open-ended via a trailing ``...``)."""
    expr = res.deref(expr)
    if not (isinstance(expr, ast.Call)
            and _call_leaf(expr) in ("P", "PartitionSpec")):
        return None
    out: list = []
    for a in expr.args:
        if isinstance(a, ast.Starred):
            out.append(Ellipsis)
            break
        if isinstance(a, ast.Constant):
            if a.value is None:
                out.append(None)
            elif isinstance(a.value, str):
                out.append(a.value)
            else:
                out.append(_VAR)
        else:
            out.append(_VAR)
    return tuple(out)


def _specs_list(expr: ast.expr, res: _Resolver) -> "list | None":
    """in_specs/out_specs -> per-operand spec entry tuples (None for an
    operand whose spec is not provable); list truncated at a star."""
    expr = res.deref(expr)
    if isinstance(expr, ast.Call) \
            and _call_leaf(expr) in ("P", "PartitionSpec"):
        return [_spec_entries(expr, res)]
    if not isinstance(expr, (ast.Tuple, ast.List)):
        return None
    out: list = []
    for e in expr.elts:
        if isinstance(e, ast.Starred):
            break  # positions past a star are unknowable
        out.append(_spec_entries(e, res))
    return out


def _mesh_axes(expr: ast.expr, res: _Resolver) -> "tuple | None":
    """The literal axis-name tuple of a mesh expression, or None."""
    expr = res.deref(expr)
    if not isinstance(expr, ast.Call):
        return None
    leaf = _call_leaf(expr)
    if leaf == "Mesh":
        axes = expr.args[1] if len(expr.args) > 1 else None
        for kw in expr.keywords:
            if kw.arg == "axis_names":
                axes = kw.value
        if isinstance(axes, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant)
                and isinstance(e.value, str) for e in axes.elts):
            return tuple(e.value for e in axes.elts)
        if isinstance(axes, ast.Constant) \
                and isinstance(axes.value, str):
            return (axes.value,)
        return None
    if leaf == "default_mesh":
        arg = expr.args[0] if expr.args else None
        for kw in expr.keywords:
            if kw.arg == "axis_name":
                arg = kw.value
        if arg is None:
            return ("kv",)
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return (arg.value,)
        return None
    if leaf == "hybrid_mesh":
        inner, outer = "kv", "dp"
        args = list(expr.args)
        if args:
            if not (isinstance(args[0], ast.Constant)
                    and isinstance(args[0].value, str)):
                return None
            inner = args[0].value
        if len(args) > 1:
            if not (isinstance(args[1], ast.Constant)
                    and isinstance(args[1].value, str)):
                return None
            outer = args[1].value
        for kw in expr.keywords:
            if not (isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)):
                return None
            if kw.arg == "inner_axis":
                inner = kw.value.value
            elif kw.arg == "outer_axis":
                outer = kw.value.value
        return (outer, inner)
    return None


# -- shard_map site discovery ---------------------------------------------

class _Site:
    """One shard_map application: the wrapped def, its spec kwargs, the
    scope holding the shard_map expression, and the wrapped callable's
    visible call sites in that scope."""

    def __init__(self, fn, kwargs, scope, calls):
        self.fn = fn              # ast.FunctionDef being wrapped
        self.kwargs = kwargs      # {mesh, in_specs, out_specs}: exprs
        self.scope = scope        # enclosing scope of the shard_map
        self.calls = calls        # list[ast.Call] invoking the wrapper


def _shard_map_kwargs(call: ast.Call) -> "dict | None":
    kw = {k.arg: k.value for k in call.keywords if k.arg}
    if "in_specs" not in kw and "out_specs" not in kw:
        return None
    return kw


def _partial_shard_map(dec: ast.expr) -> "ast.Call | None":
    """``functools.partial(shard_map, mesh=..., ...)`` decorators."""
    if not isinstance(dec, ast.Call):
        return None
    d = dotted_name(dec.func) or ""
    if d.split(".")[-1] != "partial" or not dec.args:
        return None
    first = dec.args[0]
    if (dotted_name(first) or "").split(".")[-1] != "shard_map":
        return None
    return dec


def _find_sites(interp: ShapeInterp) -> list[_Site]:
    sites: list[_Site] = []
    for scope in interp.scopes():
        if isinstance(scope, ast.Module):
            continue
        for dec in scope.decorator_list:
            pc = _partial_shard_map(dec)
            if pc is None:
                continue
            kwargs = _shard_map_kwargs(pc)
            if kwargs is None:
                continue
            parent = interp._parents.get(id(scope), interp.tree)
            calls = [n for n in _scope_nodes(parent)
                     if isinstance(n, ast.Call)
                     and isinstance(n.func, ast.Name)
                     and n.func.id == scope.name]
            sites.append(_Site(scope, kwargs, parent, calls))
    # direct form: shard_map(f, mesh=..., in_specs=..., out_specs=...)
    for scope in interp.scopes():
        nodes = _scope_nodes(scope)
        local_defs = {n.name: n for n in nodes
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}
        if isinstance(scope, ast.Module):
            local_defs.update(
                {n.name: n for n in scope.body
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))})
        for n in nodes:
            # the compat shim itself defines shard_map; only CALLS with
            # a function first-arg + spec kwargs are applications
            if not (isinstance(n, ast.Call)
                    and _call_leaf(n) == "shard_map" and n.args):
                continue
            fn_arg = n.args[0]
            if not isinstance(fn_arg, ast.Name):
                continue
            fn = local_defs.get(fn_arg.id)
            if fn is None:
                continue
            kwargs = _shard_map_kwargs(n)
            if kwargs is None:
                continue
            calls = [m for m in nodes
                     if isinstance(m, ast.Call) and m.func is n]
            # wrapper bound to a single-assignment name -> its calls
            for m in nodes:
                if isinstance(m, ast.Assign) and len(m.targets) == 1 \
                        and isinstance(m.targets[0], ast.Name) \
                        and m.value is n:
                    wname = m.targets[0].id
                    calls += [c for c in nodes
                              if isinstance(c, ast.Call)
                              and isinstance(c.func, ast.Name)
                              and c.func.id == wname]
            sites.append(_Site(fn, kwargs, scope, calls))
    return sites


# -- collective-freedom proof ----------------------------------------------

def _body_nodes(fn) -> list[ast.AST]:
    """The def's *body* nodes only — decorators and default-arg
    expressions execute outside the shard_map and must not poison (or
    satisfy) the body's collective analysis."""
    out: list[ast.AST] = []
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        out.append(node)
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(node))
    return out


def _collective_free(fn, index, path: str, memo: dict,
                     depth: int = _COLLECTIVE_DEPTH) -> bool:
    """True only when every call reachable from ``fn``'s body (through
    in-tree callees, depth-capped) is provably not a collective."""
    key = id(fn)
    if key in memo:
        return memo[key]
    memo[key] = False  # cycle guard: unresolved recursion stays unproven
    if depth <= 0:
        return False
    ok = True
    for n in _body_nodes(fn):
        if not isinstance(n, ast.Call):
            continue
        leaf = _call_leaf(n)
        if leaf is None:
            ok = False
            break
        if leaf in _COLLECTIVES:
            ok = False
            break
        d = dotted_name(n.func) or leaf
        root = d.split(".")[0]
        if root in _SAFE_ROOTS or root in ("jax", "lax"):
            continue
        if "." not in d and leaf in _SAFE_BUILTINS:
            continue
        if index is None:
            ok = False
            break
        callee, canonical = index.resolve_call(path, None, n)
        if callee is None:
            # external but canonically resolvable (P, Mesh, jnp
            # aliases): safe unless it is a collective leaf (already
            # excluded above)
            if canonical and canonical.split(".")[0] in ("jax",
                                                         "numpy"):
                continue
            ok = False
            break
        info = index.functions.get(callee)
        if info is None:
            ok = False
            break
        if not _collective_free(info.node, index, info.path, memo,
                                depth - 1):
            ok = False
            break
    memo[key] = ok
    return ok


# -- per-site checks -------------------------------------------------------

def _sharded_positions(spec) -> list[tuple[int, str]]:
    """(dim index, literal axis) pairs a spec provably shards."""
    if spec is None:
        return []
    return [(i, e) for i, e in enumerate(spec)
            if isinstance(e, str) and e != _VAR]


def _spec_rank(spec) -> int | None:
    """Declared rank of a spec — only when star-free and non-empty
    (an empty ``P()`` legally prefixes any rank)."""
    if spec is None or not spec or Ellipsis in spec:
        return None
    return len(spec)


def _check_in_specs(site: _Site, interp: ShapeInterp, res: _Resolver,
                    path: str, findings: list[Finding]) -> None:
    specs = _specs_list(site.kwargs["in_specs"], res) \
        if "in_specs" in site.kwargs else None
    if not specs:
        return
    mesh = _mesh_axes(site.kwargs["mesh"], res) \
        if "mesh" in site.kwargs else None
    env = interp.env(site.scope)
    # axis-name validity is call-site independent
    for i, spec in enumerate(specs):
        if mesh is None:
            break
        for (_, axis) in _sharded_positions(spec):
            if axis not in mesh:
                findings.append(Finding(
                    ATP903,
                    f"in_specs[{i}] names axis {axis!r} but the mesh "
                    f"only has axes {mesh}",
                    path, site.fn.lineno, site.fn.col_offset))
    for call in site.calls:
        args = []
        for a in call.args:
            if isinstance(a, ast.Starred):
                break  # positions past a star are unknowable
            args.append(a)
        line = call.lineno + 1
        for i, (arg, spec) in enumerate(zip(args, specs)):
            if spec is None:
                continue
            shape = interp._shape_of(arg, env, line,
                                     _shapes._SUMMARY_DEPTH)
            rank = _spec_rank(spec)
            if shape is not None and rank is not None \
                    and rank > len(shape):
                findings.append(Finding(
                    ATP903,
                    f"in_specs[{i}] has {rank} entries but the operand "
                    f"provably has rank {len(shape)}",
                    path, call.lineno, call.col_offset))
                continue
            for (j, axis) in _sharded_positions(spec):
                if shape is None or j >= len(shape):
                    continue
                dim = shape[j]
                if dim is None:
                    continue
                if env.facts.divisor_facts(dim):
                    continue  # any guard with this dividend certifies
                findings.append(Finding(
                    ATP904,
                    f"operand dim {j} ({dim!r}) is split on axis "
                    f"{axis!r} with no `% shards == 0` guard or "
                    "assert in scope — an uneven split mis-slices "
                    "silently (MeshConfigError's static twin)",
                    path, call.lineno, call.col_offset))


def _check_body_reductions(site: _Site, res: _Resolver, index,
                           path: str, memo: dict,
                           findings: list[Finding]) -> None:
    specs = _specs_list(site.kwargs["in_specs"], res) \
        if "in_specs" in site.kwargs else None
    if not specs:
        return
    a = site.fn.args
    params = [p.arg for p in a.posonlyargs + a.args]
    shard_of: dict[str, dict[int, str]] = {}
    rank_of: dict[str, int] = {}
    for name, spec in zip(params, specs):
        pos = _sharded_positions(spec)
        if pos:
            shard_of[name] = dict(pos)
        r = _spec_rank(spec)
        if r is not None:
            rank_of[name] = r
    if not shard_of:
        return
    hits = list(_body_reduction_hits(site.fn, shard_of, rank_of))
    if not hits:
        return
    if not _collective_free(site.fn, index, path, memo):
        return  # a collective (or anything unprovable) may fix it up
    for (node, pname, j, axis, what) in hits:
        findings.append(Finding(
            ATP905,
            f"{what} contracts dim {j} of {pname!r}, which in_specs "
            f"shards on {axis!r}, and the shard_map body provably "
            "contains no collective — each shard computes a silent "
            "partial result",
            path, node.lineno, node.col_offset))


def _param_name(expr: ast.expr, params) -> str | None:
    if isinstance(expr, ast.Name) and expr.id in params:
        return expr.id
    return None


def _body_reduction_hits(fn, shard_of, rank_of):
    """Yield (node, param, dim, axis, what) for provable contractions
    over sharded dims of shard_map body params."""
    for n in _body_nodes(fn):
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.MatMult):
            lhs = _param_name(n.left, shard_of)
            if lhs is not None and lhs in rank_of:
                j = rank_of[lhs] - 1
                if j in shard_of[lhs]:
                    yield (n, lhs, j, shard_of[lhs][j], "@ (matmul)")
            rhs = _param_name(n.right, shard_of)
            if rhs is not None and rhs in rank_of:
                j = rank_of[rhs] - 2 if rank_of[rhs] >= 2 else 0
                if j in shard_of[rhs]:
                    yield (n, rhs, j, shard_of[rhs][j], "@ (matmul)")
            continue
        if not isinstance(n, ast.Call):
            continue
        leaf = _call_leaf(n)
        if leaf in _REDUCE_LEAVES:
            base = None
            if isinstance(n.func, ast.Attribute):
                base = _param_name(n.func.value, shard_of)
                pos_args = n.args
            if base is None and n.args:
                base = _param_name(n.args[0], shard_of)
                pos_args = n.args[1:]
            if base is None:
                continue
            axis_arg = pos_args[0] if pos_args else None
            for kw in n.keywords:
                if kw.arg == "axis":
                    axis_arg = kw.value
            if axis_arg is None:
                # full reduction: every sharded dim is contracted
                j, axis = next(iter(shard_of[base].items()))
                yield (n, base, j, axis, f"{leaf}() over all axes")
                continue
            if not (isinstance(axis_arg, ast.Constant)
                    and isinstance(axis_arg.value, int)):
                continue
            j = axis_arg.value
            if j < 0:
                if base not in rank_of:
                    continue
                j += rank_of[base]
            if j in shard_of[base]:
                yield (n, base, j, shard_of[base][j],
                       f"{leaf}(axis={axis_arg.value})")
        elif leaf in ("dot", "matmul"):
            if len(n.args) < 2:
                continue
            lhs = _param_name(n.args[0], shard_of)
            if lhs is not None and lhs in rank_of:
                j = rank_of[lhs] - 1
                if j in shard_of[lhs]:
                    yield (n, lhs, j, shard_of[lhs][j], f"{leaf}()")
            rhs = _param_name(n.args[1], shard_of)
            if rhs is not None and rhs in rank_of:
                j = rank_of[rhs] - 2 if rank_of[rhs] >= 2 else 0
                if j in shard_of[rhs]:
                    yield (n, rhs, j, shard_of[rhs][j], f"{leaf}()")
        elif leaf == "einsum":
            if not (n.args and isinstance(n.args[0], ast.Constant)
                    and isinstance(n.args[0].value, str)):
                continue
            spec = n.args[0].value.replace(" ", "")
            if "..." in spec or "->" not in spec:
                continue
            lhs_s, rhs_s = spec.split("->", 1)
            subs = lhs_s.split(",")
            if len(subs) != len(n.args) - 1:
                continue
            contracted = {c for s in subs for c in s if c not in rhs_s}
            for sub, op in zip(subs, n.args[1:]):
                pname = _param_name(op, shard_of)
                if pname is None:
                    continue
                for j, ch in enumerate(sub):
                    if ch in contracted and j in shard_of[pname]:
                        yield (n, pname, j, shard_of[pname][j],
                               f"einsum({spec!r})")


def _check_out_specs(site: _Site, interp: ShapeInterp, res: _Resolver,
                     path: str, findings: list[Finding]) -> None:
    expr = site.kwargs.get("out_specs")
    if expr is None:
        return
    mesh = _mesh_axes(site.kwargs["mesh"], res) \
        if "mesh" in site.kwargs else None
    deref = res.deref(expr)
    returns = [n for n in scope_list(site.fn)
               if isinstance(n, ast.Return) and n.value is not None]
    if isinstance(deref, (ast.Tuple, ast.List)) \
            and not any(isinstance(e, ast.Starred) for e in deref.elts):
        want = len(deref.elts)
        for r in returns:
            if isinstance(r.value, ast.Tuple) \
                    and not any(isinstance(e, ast.Starred)
                                for e in r.value.elts) \
                    and len(r.value.elts) != want:
                findings.append(Finding(
                    ATP906,
                    f"out_specs is a {want}-tuple but the shard_map "
                    f"body returns a {len(r.value.elts)}-tuple",
                    path, r.lineno, r.col_offset))
        specs = _specs_list(expr, res) or []
        for spec in specs:
            if mesh is None:
                break
            for (_, axis) in _sharded_positions(spec):
                if axis not in mesh:
                    findings.append(Finding(
                        ATP906,
                        f"out_specs names axis {axis!r} but the mesh "
                        f"only has axes {mesh}",
                        path, site.fn.lineno, site.fn.col_offset))
        return
    spec = _spec_entries(expr, res)
    if spec is None:
        return
    if mesh is not None:
        for (_, axis) in _sharded_positions(spec):
            if axis not in mesh:
                findings.append(Finding(
                    ATP906,
                    f"out_specs names axis {axis!r} but the mesh only "
                    f"has axes {mesh}",
                    path, site.fn.lineno, site.fn.col_offset))
    rank = _spec_rank(spec)
    if rank is None:
        return
    env = interp.env(site.fn)
    for r in returns:
        if isinstance(r.value, ast.Tuple):
            continue  # single spec against a pytree: legal prefix
        shape = interp._shape_of(r.value, env, r.lineno + 1,
                                 _shapes._SUMMARY_DEPTH)
        if shape is not None and rank > len(shape):
            findings.append(Finding(
                ATP906,
                f"out_specs has {rank} entries but the returned value "
                f"provably has rank {len(shape)}",
                path, r.lineno, r.col_offset))


@file_pass("sharding", [ATP903, ATP904, ATP905, ATP906],
           needs_index=True)
def check_sharding(path: str, tree: ast.Module, src: str, index=None):
    """shard_map spec geometry, shard divisibility, silent partials."""
    if "shard_map" not in src:
        return []
    findings: list[Finding] = []
    interp = interp_for(path, tree, index)
    sites = _find_sites(interp)
    if not sites:
        return findings
    memo: dict = {}
    for site in sites:
        res = _Resolver(interp, site.scope)
        _check_in_specs(site, interp, res, path, findings)
        _check_body_reductions(site, res, index, path, memo, findings)
        _check_out_specs(site, interp, res, path, findings)
    return findings
