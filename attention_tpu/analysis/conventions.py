"""Convention passes: the absorbed ``scripts/check_*`` lints + guards.

The three ad-hoc tree lints that predate this framework —
``check_obs_names.py`` (telemetry naming), ``check_shipped_table.py``
(tuning-table schema), ``check_tolerances.py`` (PARITY.md ledger vs
``chaos/budgets.py``) — are registered here as first-class passes with
stable codes, so one ``cli analyze`` run is the whole gate.  The
scripts survive as thin wrappers over the same functions with their
original stdout/exit-code contracts (CI and muscle memory keep
working); the logic lives here, once.

ATP601 is the guard that keeps the tree source-only by construction:
a committed ``.pyc`` under ``attention_tpu/`` once matched a source
grep during triage — build droppings in the *index* (gitignore only
shields the worktree) now fail the gate.
"""

from __future__ import annotations

import ast
import json
import os
import re
import subprocess

from attention_tpu.analysis.core import (
    Finding,
    Severity,
    file_pass,
    project_pass,
    register_code,
    walk_list,
)

ATP501 = register_code(
    "ATP501", "obs-naming", Severity.ERROR,
    "literal telemetry name violates layer.component.verb "
    "(absorbed scripts/check_obs_names.py)")
ATP504 = register_code(
    "ATP504", "obs-trace-event", Severity.ERROR,
    "literal trace event name outside the closed enum in "
    "obs/naming.py (TRACE_EVENTS)")
ATP502 = register_code(
    "ATP502", "shipped-table-schema", Severity.ERROR,
    "committed tuning table fails schema/key/tile validation "
    "(absorbed scripts/check_shipped_table.py)")
ATP503 = register_code(
    "ATP503", "tolerance-ledger-drift", Severity.ERROR,
    "PARITY.md tolerance ledger disagrees with chaos/budgets.py "
    "(absorbed scripts/check_tolerances.py)")
ATP505 = register_code(
    "ATP505", "frozen-series-pin", Severity.ERROR,
    "FROZEN_SERIES drift: a frozen telemetry series is never created, "
    "created under the wrong instrument kind, or re-typed as a string "
    "literal in a consumer module")
ATP507 = register_code(
    "ATP507", "blackbox-event-enum", Severity.ERROR,
    "literal flight-recorder event kind outside the closed enum in "
    "obs/naming.py (BLACKBOX_EVENTS)")
ATP601 = register_code(
    "ATP601", "non-source-tracked-file", Severity.ERROR,
    "a git-tracked file under attention_tpu/ or tests/ is a build "
    "dropping (.pyc/.so/__pycache__)")


# -- ATP501/ATP504: telemetry + trace-event naming ------------------------

#: call names whose first literal argument must be a telemetry name
INSTRUMENT_CALLS = {"counter", "gauge", "histogram", "digest", "span",
                    "record_event"}

#: call names whose second literal argument must be a trace event type
TRACE_RECORD_CALLS = {"record"}

#: call names whose FIRST literal argument must be a flight-recorder
#: event kind: the module-level `blackbox.note(...)` and the
#: front end's `self._bb_note(...)` wrapper
BLACKBOX_NOTE_CALLS = {"note", "_bb_note"}

_OBS_MSG = ("telemetry name {name!r} violates layer.component.verb "
            "(2-4 lowercase dot-separated [a-z][a-z0-9_]* segments)")


def obs_name_violations(tree: ast.Module) -> list[tuple[int, int, str]]:
    """(line, col, name) for every malformed literal telemetry name."""
    from attention_tpu.obs.naming import check_name

    out = []
    for node in walk_list(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = (func.id if isinstance(func, ast.Name)
                else func.attr if isinstance(func, ast.Attribute)
                else None)
        if name not in INSTRUMENT_CALLS or not node.args:
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue  # non-literal names are runtime-validated
        if not check_name(first.value):
            out.append((node.lineno, node.col_offset, first.value))
    return out


_TRACE_MSG = ("trace event {event!r} is not in the closed enum "
              "obs/naming.py:TRACE_EVENTS")


def trace_event_violations(tree: ast.Module) -> list[tuple[int, int, str]]:
    """(line, col, event) for every unknown literal trace event name.

    Matches calls named ``record`` (``trace.record(rid, "event", ...)``)
    whose SECOND positional argument is a string literal — the event
    type slot.  Dynamic event names are runtime-validated by
    ``require_event`` in the recorder itself."""
    from attention_tpu.obs.naming import check_event

    out = []
    for node in walk_list(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = (func.id if isinstance(func, ast.Name)
                else func.attr if isinstance(func, ast.Attribute)
                else None)
        if name not in TRACE_RECORD_CALLS or len(node.args) < 2:
            continue
        second = node.args[1]
        if not (isinstance(second, ast.Constant)
                and isinstance(second.value, str)):
            continue
        if not check_event(second.value):
            out.append((node.lineno, node.col_offset, second.value))
    return out


_BB_MSG = ("blackbox event {kind!r} is not in the closed enum "
           "obs/naming.py:BLACKBOX_EVENTS")


def blackbox_event_violations(tree: ast.Module) -> list[tuple[int, int, str]]:
    """(line, col, kind) for every unknown literal blackbox event kind.

    Matches calls named ``note`` / ``_bb_note`` (the flight recorder
    and the front end's coordinate-stamping wrapper) whose FIRST
    positional argument is a string literal — the event-kind slot.
    Dynamic kinds are runtime-validated by ``require_blackbox_event``
    in the recorder itself."""
    from attention_tpu.obs.naming import check_blackbox_event

    out = []
    for node in walk_list(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = (func.id if isinstance(func, ast.Name)
                else func.attr if isinstance(func, ast.Attribute)
                else None)
        if name not in BLACKBOX_NOTE_CALLS or not node.args:
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue
        if not check_blackbox_event(first.value):
            out.append((node.lineno, node.col_offset, first.value))
    return out


@file_pass("obs-naming", [ATP501, ATP504, ATP507])
def check_obs_names(path: str, tree: ast.Module, src: str):
    """Literal instrument names, trace event types, and blackbox event
    kinds follow their closed schemes."""
    findings = [
        Finding(ATP501, _OBS_MSG.format(name=name), path, line, col)
        for line, col, name in obs_name_violations(tree)]
    findings += [
        Finding(ATP504, _TRACE_MSG.format(event=event), path, line, col)
        for line, col, event in trace_event_violations(tree)]
    findings += [
        Finding(ATP507, _BB_MSG.format(kind=kind), path, line, col)
        for line, col, kind in blackbox_event_violations(tree)]
    findings.sort(key=lambda f: (f.line, f.col))
    return findings


def legacy_obs_check_file(path: str) -> list[str]:
    """`scripts/check_obs_names.py check_file`: original strings."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [f"{path}: unparsable ({e})"]
    lines = [(line, col, _OBS_MSG.format(name=name))
             for line, col, name in obs_name_violations(tree)]
    lines += [(line, col, _TRACE_MSG.format(event=event))
              for line, col, event in trace_event_violations(tree)]
    lines += [(line, col, _BB_MSG.format(kind=kind))
              for line, col, kind in blackbox_event_violations(tree)]
    return [f"{path}:{line}: {msg}" for line, _col, msg in sorted(lines)]


# -- ATP502: shipped tuning table -----------------------------------------

# which entry fields each family's lookup adapter actually reads; the
# forward/decode/ragged adapters also honor a measured "max_mode"
# rescaling-math variant (ops.flash._tuned_max_mode) — the backward
# families recompute through the forward's own dispatch and carry none
FAMILY_FIELDS = {
    "flash_fwd": {"block_q", "block_k", "max_mode"},
    "flash_bwd": {"block_q", "block_k"},
    "flash_bwd_fused": {"block_q", "block_k"},
    "decode": {"block_k", "max_mode"},
    "paged": {"page_size"},
    "ragged": {"block_q", "max_mode"},
}

META_FIELDS = {"ms", "source", "recorded"}

# fields a family MAY carry but need not (an entry without max_mode
# reads as "no measured opinion": the kernel keeps its call default)
OPTIONAL_FIELDS = {"max_mode"}


def _load_no_duplicates(path: str):
    """json.load that REJECTS duplicate keys instead of last-wins."""

    def hook(pairs):
        seen = set()
        for k, _ in pairs:
            if k in seen:
                raise ValueError(f"duplicate key {k!r}")
            seen.add(k)
        return dict(pairs)

    with open(path) as f:
        return json.load(f, object_pairs_hook=hook)


def shipped_table_problems(path: str) -> list[str]:
    """Schema/key/tile problems in a tuning table (legacy strings)."""
    from attention_tpu.tuning.cache import (
        SCHEMA_VERSION,
        parse_key,
        validate_entry,
    )

    problems = []
    try:
        data = _load_no_duplicates(path)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable ({e})"]
    if data.get("version") != SCHEMA_VERSION:
        problems.append(
            f"version {data.get('version')!r} != {SCHEMA_VERSION}")
    entries = data.get("entries")
    if not isinstance(entries, dict):
        problems.append("'entries' missing or not an object")
        return problems
    for key, entry in entries.items():
        try:
            fields = parse_key(key)
            validate_entry(entry)
        except ValueError as e:
            problems.append(str(e))
            continue
        allowed = FAMILY_FIELDS[fields["kernel"]] | META_FIELDS
        extra = set(entry) - allowed
        missing = (FAMILY_FIELDS[fields["kernel"]] - OPTIONAL_FIELDS
                   - set(entry))
        if extra:
            problems.append(f"{key}: unknown fields {sorted(extra)}")
        if missing:
            problems.append(f"{key}: missing tile fields "
                            f"{sorted(missing)}")
    return problems


@project_pass("shipped-table", [ATP502])
def check_shipped_table(root: str):
    """The committed shipped tuning table passes schema validation."""
    rel = "attention_tpu/tuning/shipped_table.json"
    path = os.path.join(root, rel)
    if not os.path.isfile(path):
        return [Finding(ATP502, f"{rel} is missing", rel)]
    return [Finding(ATP502, p, rel) for p in shipped_table_problems(path)]


# -- ATP503: tolerance ledger ---------------------------------------------

LEDGER_SECTION = "## Tolerance ledger"
#: | `family` | number | basis |
_ROW_RE = re.compile(
    r"^\|\s*`(?P<family>[a-z0-9_]+)`\s*\|\s*(?P<tol>[0-9.eE+-]+)\s*\|"
)


def parse_ledger_table(path: str) -> dict[str, float]:
    """The family -> tolerance rows of PARITY.md's ledger section."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    if LEDGER_SECTION not in text:
        raise ValueError(f"{path}: no '{LEDGER_SECTION}' section")
    body = text.split(LEDGER_SECTION, 1)[1]
    # the section ends at the next heading
    body = re.split(r"^## ", body, maxsplit=1, flags=re.MULTILINE)[0]
    out: dict[str, float] = {}
    for line in body.splitlines():
        m = _ROW_RE.match(line.strip())
        if not m:
            continue
        family = m.group("family")
        if family in out:
            raise ValueError(f"{path}: duplicate ledger row {family!r}")
        out[family] = float(m.group("tol"))
    if not out:
        raise ValueError(f"{path}: ledger section holds no parsable rows")
    return out


def _family_budgets() -> dict[str, float]:
    """``chaos.budgets.FAMILY_BUDGETS`` without importing the chaos
    package: its ``__init__`` pulls the engine (and so jax), which
    would cost the analyzer its seconds-not-minutes contract.  The
    already-imported module is reused when something else paid for it;
    otherwise budgets.py (pure data + numpy) loads by file path."""
    import importlib.util
    import sys

    mod = sys.modules.get("attention_tpu.chaos.budgets")
    if mod is None:
        from attention_tpu.analysis.core import repo_root

        spec = importlib.util.spec_from_file_location(
            "attention_tpu.chaos.budgets",
            os.path.join(repo_root(), "attention_tpu", "chaos",
                         "budgets.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    return mod.FAMILY_BUDGETS


def tolerance_problems(path: str) -> list[str]:
    """Ledger-vs-budgets drift problems (legacy strings)."""
    FAMILY_BUDGETS = _family_budgets()

    try:
        documented = parse_ledger_table(path)
    except (OSError, ValueError) as e:
        return [str(e)]
    problems = []
    for family, tol in sorted(FAMILY_BUDGETS.items()):
        if family not in documented:
            problems.append(
                f"budget {family!r} ({tol:g}) missing from {path}")
        elif documented[family] != tol:
            problems.append(
                f"{family!r}: {path} says {documented[family]:g}, "
                f"chaos/budgets.py says {tol:g}")
    for family in sorted(set(documented) - set(FAMILY_BUDGETS)):
        problems.append(
            f"{path} documents unknown budget {family!r} "
            f"({documented[family]:g})")
    return problems


@project_pass("tolerance-ledger", [ATP503])
def check_tolerances(root: str):
    """PARITY.md's tolerance ledger matches chaos/budgets.py exactly."""
    path = os.path.join(root, "PARITY.md")
    if not os.path.isfile(path):
        return [Finding(ATP503, "PARITY.md is missing", "PARITY.md")]
    return [Finding(ATP503, p, "PARITY.md")
            for p in tolerance_problems(path)]


# -- ATP505: frozen series pin --------------------------------------------

#: instrument call name -> the FROZEN_SERIES kind it creates
_INSTRUMENT_KINDS = {"counter": "counter", "gauge": "gauge",
                     "histogram": "histogram", "digest": "digest"}

#: modules that CONSUME the frozen map (the forecaster stack): they may
#: only reach a frozen series through its SERIES_* constant, never by
#: re-typing the dotted name — so a rename in naming.py is a lint
#: failure, not a silent series fork
FROZEN_CONSUMER_MODULES = (
    "attention_tpu/obs/anomaly.py",
    "attention_tpu/obs/capacity.py",
    "attention_tpu/obs/forecast.py",
    "attention_tpu/obs/slo.py",
)

_FROZEN_DEF_MODULE = "attention_tpu/obs/naming.py"


def _series_arg(node: ast.Call, naming) -> str | None:
    """The telemetry name an instrument call creates, resolving
    ``SERIES_*`` constant references through ``obs.naming`` (the
    engine/frontend creation sites all use the constants, so a
    literal-only scan would see nothing)."""
    if not node.args:
        return None
    first = node.args[0]
    if isinstance(first, ast.Constant) and isinstance(first.value, str):
        return first.value
    ref = (first.id if isinstance(first, ast.Name)
           else first.attr if isinstance(first, ast.Attribute) else None)
    if ref and ref.startswith("SERIES_"):
        val = getattr(naming, ref, None)
        return val if isinstance(val, str) else None
    return None


def _doc_constants(tree: ast.Module) -> set[int]:
    """ids of docstring Constant nodes (exempt from the literal rule:
    prose may cite a series name; code may not)."""
    out = set()
    for node in walk_list(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            if (body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)):
                out.add(id(body[0].value))
    return out


def frozen_series_findings(index) -> list[Finding]:
    """ATP505 findings over an already-built project index."""
    from attention_tpu.obs import naming

    frozen = naming.FROZEN_SERIES
    #: frozen name -> [(path, line, call_name)] creation sites
    created: dict[str, list[tuple[str, int, str]]] = {}
    findings: list[Finding] = []
    for rel in sorted(index.modules):
        mod = index.modules[rel]
        for node in walk_list(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            call = (func.id if isinstance(func, ast.Name)
                    else func.attr if isinstance(func, ast.Attribute)
                    else None)
            if call not in _INSTRUMENT_KINDS:
                continue
            name = _series_arg(node, naming)
            if name in frozen:
                created.setdefault(name, []).append(
                    (rel, node.lineno, call))
    for name in sorted(frozen):
        sites = created.get(name, [])
        if not sites:
            findings.append(Finding(
                ATP505,
                f"frozen series {name!r} ({frozen[name]}) is never "
                f"created by any instrument call in the tree",
                _FROZEN_DEF_MODULE))
            continue
        for rel, line, call in sites:
            kind = _INSTRUMENT_KINDS[call]
            if kind != frozen[name]:
                findings.append(Finding(
                    ATP505,
                    f"frozen series {name!r} is registered as a "
                    f"{frozen[name]} but created here via {call}()",
                    rel, line))
    for rel in FROZEN_CONSUMER_MODULES:
        mod = index.modules.get(rel)
        if mod is None:
            continue
        docs = _doc_constants(mod.tree)
        for node in walk_list(mod.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and node.value in frozen
                    and id(node) not in docs):
                findings.append(Finding(
                    ATP505,
                    f"frozen series name {node.value!r} re-typed as a "
                    f"literal — import its SERIES_* constant from "
                    f"obs/naming.py instead",
                    rel, node.lineno, node.col_offset))
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    return findings


@project_pass("frozen-series", [ATP505], needs_index=True)
def check_frozen_series(root: str, index=None):
    """Every FROZEN_SERIES name is really created (kind-correct), and
    forecaster-stack consumers never re-type one as a literal."""
    from attention_tpu.analysis.core import build_index

    if index is None:
        index = build_index(root)
    return frozen_series_findings(index)


# -- ATP601: source-only tree guard ---------------------------------------

#: extensions/components that mark a tracked file as a build dropping
_NON_SOURCE_SUFFIXES = (".pyc", ".pyo", ".so", ".o", ".a", ".dylib",
                        ".dll", ".egg")
_NON_SOURCE_PARTS = {"__pycache__", ".DS_Store", ".egg-info"}


def non_source_findings(paths) -> list[Finding]:
    """Findings for tracked paths that are not source artifacts."""
    out = []
    for p in paths:
        parts = p.replace(os.sep, "/").split("/")
        if (p.endswith(_NON_SOURCE_SUFFIXES)
                or any(part in _NON_SOURCE_PARTS for part in parts)):
            out.append(Finding(
                ATP601,
                "tracked build dropping — .gitignore only shields the "
                "worktree; remove it from the index (git rm --cached)",
                p))
    return out


@project_pass("source-only-tree", [ATP601])
def check_source_only(root: str):
    """No committed .pyc/.so/__pycache__ under attention_tpu/ or tests/."""
    try:
        raw = subprocess.run(
            ["git", "-C", root, "ls-files", "-z", "--",
             "attention_tpu", "tests"],
            capture_output=True, timeout=30, check=True,
        ).stdout
    except (OSError, subprocess.SubprocessError):
        return []  # not a checkout (e.g. installed wheel): nothing to guard
    paths = [p.decode("utf-8", "replace")
             for p in raw.split(b"\0") if p]
    return non_source_findings(paths)
