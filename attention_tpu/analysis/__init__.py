"""attention_tpu.analysis — AST-based static analysis for this tree.

The static half of the correctness story (the runtime half is
``attention_tpu.obs`` + ``attention_tpu.chaos``): JAX/Pallas-aware
passes that flag, before anything traces or compiles,

- trace-purity violations (ATP1xx, `purity`),
- Pallas block/grid/out_shape contract breaks (ATP2xx, `pallas`),
- silent low-precision accumulation (ATP3xx, `precision`),
- error-taxonomy drift (ATP4xx, `errors`),
- tree conventions — the absorbed ``scripts/check_*`` lints, the
  frozen-series pin, and the source-only guard (ATP5xx/ATP601,
  `conventions`),
- committed benchmark-trajectory regressions (ATP506, `benchtrend`),
- torn-write-prone persistence in the durable modules (ATP701,
  `durability`),
- determinism hazards across call edges — wall-clock into artifacts,
  unseeded randomness, unordered iteration/accumulation (ATP8xx,
  `determinism`, on the `callgraph` + `dataflow` interprocedural
  core),
- provable inconsistencies in the symbolic shape/sharding domain —
  dot/concat/where operand shapes, Pallas grids and block shapes
  bound to variables, PartitionSpec geometry, shard divisibility,
  cross-shard reductions without a collective (ATP9xx, `shapes` +
  `sharding` + the `pallas` upgrade, on the same interprocedural
  core; divisibility facts certify, nothing is guessed).

Entry points: ``cli analyze`` (text/JSON/SARIF/GitHub annotations,
``--changed``),
``scripts/check_all.py`` (the tier-1 gate), and `core.analyze` as a
library.  Inline suppression: ``# atp: disable=ATP###``.  Accepted
legacy findings: ``analysis/baseline.json`` (every entry justified).

Importing this package registers every pass (the submodule imports
below are the registration mechanism, not conveniences).
"""

from attention_tpu.analysis.core import (  # noqa: F401
    CODES,
    PASSES,
    Finding,
    Severity,
    analyze,
    analyze_file,
    iter_source_files,
    repo_root,
)
from attention_tpu.analysis import (  # noqa: F401  (pass registration)
    benchtrend,
    conventions,
    determinism,
    durability,
    errors,
    pallas,
    precision,
    purity,
    shapes,
    sharding,
)
from attention_tpu.analysis.report import (  # noqa: F401
    apply_baseline,
    default_baseline_path,
    load_baseline,
    render_github,
    render_json,
    render_sarif,
    render_text,
)
