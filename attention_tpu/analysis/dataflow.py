"""A small taint lattice over the call graph (sources/sinks/sanitizers).

The determinism passes (ATP8xx) all reduce to one question: *can a
value produced here reach an artifact over there?*  This module answers
it with a deliberately small forward dataflow:

- the lattice is ``None < label`` per tracked name (labels are short
  strings like ``"time.perf_counter"`` naming the originating source —
  they ride into finding messages);
- the environment is **name-level**: plain names, plus dotted
  attribute roots (``self._wall``) so per-object state threads between
  the methods of one class, plus module-level globals as seeds for
  every function in that module;
- propagation is **syntactic and conservative**: assignments,
  augmented assignments, ``for``/``with`` targets, arithmetic,
  containers, f-strings, and — unless a call is a registered
  sanitizer — *through* opaque calls (a tainted argument taints the
  result, so ``round(wall, 4)`` stays tainted and ``sorted(s)`` does
  not);
- calls resolved by the :mod:`callgraph` index propagate **along call
  edges with a depth cap**: a call taints its result when the callee's
  return value is (transitively, up to ``max_depth`` edges) tainted,
  and a tainted argument reaches a sink when the callee (transitively,
  same cap) forwards that parameter into one.

Beyond the cap the analysis assumes *clean* — bounded, never guessing,
matching the callgraph's contract.  Env construction runs the
statement scan twice so loop-carried taint converges, then a third
pass collects sink hits.  Everything is plain ``ast``; no imports of
the analyzed code ever happen.
"""

from __future__ import annotations

import ast
from typing import Callable, Iterator

from attention_tpu.analysis.callgraph import (
    CallSite,
    FunctionInfo,
    ProjectIndex,
    _local_env,
)
from attention_tpu.analysis.core import dotted_name

#: default interprocedural depth cap (call edges followed per query)
MAX_DEPTH = 3


def iter_stmts_ordered(node: ast.AST) -> Iterator[ast.AST]:
    """Source-order traversal of a scope: yields every descendant but
    does not enter nested function/class/lambda bodies (they are their
    own scopes)."""
    for child in ast.iter_child_nodes(node):
        yield child
        if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
            yield from iter_stmts_ordered(child)


def ordered_stmts(index: ProjectIndex, node: ast.AST) -> list[ast.AST]:
    """``iter_stmts_ordered(node)`` flattened once and cached on the
    index — every summary query re-scans the same scopes, and the
    recursive generator dominated the tree-wide budget before this."""
    cache = index._stmt_cache
    got = cache.get(id(node))
    if got is None:
        got = list(iter_stmts_ordered(node))
        cache[id(node)] = got
    return got


def target_key(node: ast.expr) -> str | None:
    """The env key a store binds: a plain name, or the dotted root of
    an attribute/subscript chain (``self._wall[rid] = ...`` stores
    under ``self._wall``)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return dotted_name(node)


def _join(*labels: str | None) -> str | None:
    for lb in labels:
        if lb:
            return lb
    return None


class TaintAnalysis:
    """One spec (source/sink/sanitizer hooks) evaluated over an index.

    Hooks (all optional except ``source``):

    - ``source(site) -> label|None`` — the call is a taint source;
    - ``expr_source(node, taint_of) -> label|None`` — non-call
      expression sources (set displays, comprehensions); ``taint_of``
      lets the hook ask about sub-expressions;
    - ``sink(site) -> kind|None`` — the call consumes its arguments
      into a deterministic artifact;
    - ``sanitizer(site) -> bool`` — the call's result is clean no
      matter its arguments (``sorted``, explicit re-seeding, ...);
    - ``taint_loop_var`` — whether ``for x in tainted:`` taints ``x``
      (True for value taint like wall-clock; False for container
      properties like unorderedness).
    """

    def __init__(self, index: ProjectIndex, *,
                 source: Callable[[CallSite], str | None],
                 sink: Callable[[CallSite], str | None] | None = None,
                 sanitizer: Callable[[CallSite], bool] | None = None,
                 expr_source: Callable | None = None,
                 taint_loop_var: bool = True,
                 decision_sinks: bool = False,
                 max_depth: int = MAX_DEPTH):
        self.index = index
        self.source = source
        self.sink = sink or (lambda site: None)
        self.sanitizer = sanitizer or (lambda site: False)
        self.expr_source = expr_source
        self.taint_loop_var = taint_loop_var
        self.decision_sinks = decision_sinks
        self.max_depth = max_depth
        self._site_by_node: dict[int, CallSite] = {}
        for sites in index.calls.values():
            for s in sites:
                self._site_by_node[id(s.node)] = s
        self._module_env_memo: dict[str, dict[str, str]] = {}
        self._module_sites: dict[str, dict[int, CallSite]] = {}
        self._class_attr_memo: dict[str, dict[str, str]] = {}
        self._returns_memo: dict[tuple[str, int], str | None] = {}
        self._fn_env_memo: dict[tuple, dict[str, str]] = {}
        self._param_sink_memo: dict[tuple[str, int, int], str | None] = {}
        self._param_ret_memo: dict[tuple[str, int, int], bool] = {}
        self._in_progress: set[tuple] = set()

    # -- call-site lookup -------------------------------------------------

    def _site(self, call: ast.Call, path: str,
              cls_qual: str | None) -> CallSite:
        site = self._site_by_node.get(id(call))
        if site is None:
            mod_sites = self._module_sites.get(path)
            if mod_sites is not None:
                site = mod_sites.get(id(call))
        if site is None:
            callee, name = self.index.resolve_call(path, cls_qual, call)
            site = CallSite("<adhoc>", callee, name, call.lineno,
                            call.col_offset, call)
        return site

    # -- environments -----------------------------------------------------

    def module_env(self, path: str) -> dict[str, str]:
        """Taint of module-level globals (seeds every scope in the
        file: a ``_T0 = time.perf_counter()`` at import time taints
        ``_T0`` everywhere)."""
        if path in self._module_env_memo:
            return self._module_env_memo[path]
        self._module_env_memo[path] = {}  # cycle guard
        mod = self.index.modules.get(path)
        if mod is None:
            return {}
        sites: dict[int, CallSite] = {}
        for node in ordered_stmts(self.index, mod.tree):
            if isinstance(node, ast.Call):
                callee, name = self.index.resolve_call(path, None, node)
                sites[id(node)] = CallSite("<module>", callee, name,
                                           node.lineno, node.col_offset,
                                           node)
        self._module_sites[path] = sites
        env: dict[str, str] = {}
        for _ in range(2):
            self._env_pass(mod.tree, env, path, None, self.max_depth)
        self._module_env_memo[path] = env
        return env

    def class_attrs(self, cls_qual: str) -> dict[str, str]:
        """Tainted ``self.<attr>`` roots, unioned over the class's
        methods (one seedless round) — how ``add_request`` stamping
        ``self._wall`` reaches ``_finish_request`` reading it."""
        if cls_qual in self._class_attr_memo:
            return self._class_attr_memo[cls_qual]
        self._class_attr_memo[cls_qual] = {}  # cycle guard
        cls = self.index.classes.get(cls_qual)
        if cls is None:
            return {}
        attrs: dict[str, str] = {}
        for m in cls.methods.values():
            env = dict(self.module_env(m.path))
            for _ in range(2):
                self._env_pass(m.node, env, m.path, cls_qual,
                               self.max_depth)
            for k, v in env.items():
                if k.startswith("self."):
                    attrs.setdefault(k, v)
        self._class_attr_memo[cls_qual] = attrs
        return attrs

    def function_env(self, info: FunctionInfo,
                     seed: dict[str, str] | None = None,
                     depth: int | None = None) -> dict[str, str]:
        depth = self.max_depth if depth is None else depth
        key = (id(info.node), depth,
               tuple(sorted(seed.items())) if seed else None)
        hit = self._fn_env_memo.get(key)
        if hit is not None:
            return dict(hit)  # callers may mutate their copy
        env = dict(self.module_env(info.path))
        if info.cls:
            env.update(self.class_attrs(info.cls))
        if seed:
            env.update(seed)
        for _ in range(2):
            self._env_pass(info.node, env, info.path, info.cls, depth)
        self._fn_env_memo[key] = dict(env)
        return env

    def _env_pass(self, scope: ast.AST, env: dict[str, str], path: str,
                  cls_qual: str | None, depth: int) -> None:
        for node in ordered_stmts(self.index, scope):
            if isinstance(node, ast.Assign):
                lb = self.taint_of(node.value, env, path, cls_qual, depth)
                for tgt in node.targets:
                    for t in (tgt.elts if isinstance(
                            tgt, (ast.Tuple, ast.List)) else [tgt]):
                        key = target_key(t)
                        if key:
                            if lb:
                                env[key] = lb
                            elif isinstance(t, ast.Name):
                                env.pop(key, None)  # clean rebind
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                key = target_key(node.target)
                lb = self.taint_of(node.value, env, path, cls_qual, depth)
                if key and lb:
                    env[key] = lb
            elif isinstance(node, ast.AugAssign):
                key = target_key(node.target)
                lb = self.taint_of(node.value, env, path, cls_qual, depth)
                if key and lb:
                    env.setdefault(key, lb)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                lb = self.taint_of(node.iter, env, path, cls_qual, depth)
                if lb and self.taint_loop_var:
                    key = target_key(node.target)
                    if key:
                        env[key] = lb
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        key = target_key(item.optional_vars)
                        lb = self.taint_of(item.context_expr, env, path,
                                           cls_qual, depth)
                        if key and lb:
                            env[key] = lb

    # -- expression taint -------------------------------------------------

    def taint_of(self, node: ast.expr, env: dict[str, str], path: str,
                 cls_qual: str | None, depth: int) -> str | None:
        if self.expr_source is not None:
            lb = self.expr_source(
                node, lambda e: self.taint_of(e, env, path, cls_qual,
                                              depth))
            if lb:
                return lb
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Attribute):
            d = dotted_name(node)
            if d and d in env:
                return env[d]
            return self.taint_of(node.value, env, path, cls_qual, depth)
        if isinstance(node, ast.Subscript):
            return self.taint_of(node.value, env, path, cls_qual, depth)
        if isinstance(node, ast.Call):
            return self.call_taint(node, env, path, cls_qual, depth)
        if isinstance(node, ast.BinOp):
            return _join(
                self.taint_of(node.left, env, path, cls_qual, depth),
                self.taint_of(node.right, env, path, cls_qual, depth))
        if isinstance(node, ast.UnaryOp):
            return self.taint_of(node.operand, env, path, cls_qual, depth)
        if isinstance(node, ast.BoolOp):
            return _join(*(self.taint_of(v, env, path, cls_qual, depth)
                           for v in node.values))
        if isinstance(node, ast.Compare):
            return _join(
                self.taint_of(node.left, env, path, cls_qual, depth),
                *(self.taint_of(c, env, path, cls_qual, depth)
                  for c in node.comparators))
        if isinstance(node, ast.IfExp):
            return _join(
                self.taint_of(node.body, env, path, cls_qual, depth),
                self.taint_of(node.orelse, env, path, cls_qual, depth))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return _join(*(self.taint_of(e, env, path, cls_qual, depth)
                           for e in node.elts))
        if isinstance(node, ast.Dict):
            return _join(*(self.taint_of(v, env, path, cls_qual, depth)
                           for v in list(node.keys) + list(node.values)
                           if v is not None))
        if isinstance(node, ast.JoinedStr):
            return _join(*(self.taint_of(v.value, env, path, cls_qual,
                                         depth)
                           for v in node.values
                           if isinstance(v, ast.FormattedValue)))
        if isinstance(node, ast.Starred):
            return self.taint_of(node.value, env, path, cls_qual, depth)
        if isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            return self.taint_of(node.generators[0].iter, env, path,
                                 cls_qual, depth)
        if isinstance(node, ast.DictComp):
            return self.taint_of(node.generators[0].iter, env, path,
                                 cls_qual, depth)
        if isinstance(node, ast.NamedExpr):
            return self.taint_of(node.value, env, path, cls_qual, depth)
        return None

    def call_taint(self, call: ast.Call, env: dict[str, str], path: str,
                   cls_qual: str | None, depth: int) -> str | None:
        site = self._site(call, path, cls_qual)
        if self.sanitizer(site):
            return None
        lb = self.source(site)
        if lb:
            return lb
        if site.callee is not None and depth > 0:
            lb = self.returns_taint(site.callee, depth - 1)
            if lb:
                return lb
            # a tainted argument survives a callee that returns it
            # (the `def _r6(x): return round(x, 6)` helper shape)
            for i, a in enumerate(call.args):
                alb = self.taint_of(a, env, path, cls_qual, depth)
                if alb and self.param_returns(site.callee, i, depth - 1):
                    return alb
            return None  # resolved call: trust the summary
        # opaque call: conservative — tainted args/receiver taint the
        # result (round(wall), wall.get("added"), f(t), str(t), ...)
        parts = [self.taint_of(a, env, path, cls_qual, depth)
                 for a in call.args]
        parts += [self.taint_of(kw.value, env, path, cls_qual, depth)
                  for kw in call.keywords]
        if isinstance(call.func, ast.Attribute):
            parts.append(self.taint_of(call.func.value, env, path,
                                       cls_qual, depth))
        return _join(*parts)

    # -- interprocedural summaries ---------------------------------------

    def returns_taint(self, qual: str, depth: int) -> str | None:
        """Does ``qual``'s return value carry taint (within depth)?"""
        key = (qual, depth)
        if key in self._returns_memo:
            return self._returns_memo[key]
        if ("r", qual) in self._in_progress or depth < 0:
            return None
        info = self.index.functions.get(qual)
        if info is None:
            return None
        self._in_progress.add(("r", qual))
        try:
            env = self.function_env(info, depth=depth)
            lb = None
            for node in ordered_stmts(self.index, info.node):
                if isinstance(node, ast.Return) and node.value is not None:
                    lb = _join(lb, self.taint_of(
                        node.value, env, info.path, info.cls, depth))
                elif isinstance(node, (ast.Yield, ast.YieldFrom)) \
                        and node.value is not None:
                    lb = _join(lb, self.taint_of(
                        node.value, env, info.path, info.cls, depth))
        finally:
            self._in_progress.discard(("r", qual))
        self._returns_memo[key] = lb
        return lb

    def param_sink(self, qual: str, param: int,
                   depth: int) -> str | None:
        """Does ``qual`` forward positional param ``param`` into a sink
        (within depth)?  Returns the sink kind."""
        key = (qual, param, depth)
        if key in self._param_sink_memo:
            return self._param_sink_memo[key]
        if ("p", qual, param) in self._in_progress or depth < 0:
            return None
        info = self.index.functions.get(qual)
        if info is None:
            return None
        args = info.node.args
        names = [p.arg for p in args.posonlyargs + args.args]
        if info.cls and names and names[0] in ("self", "cls"):
            names = names[1:]
        if param >= len(names):
            self._param_sink_memo[key] = None
            return None
        self._in_progress.add(("p", qual, param))
        try:
            seed = {names[param]: f"param:{names[param]}"}
            env = self.function_env(info, seed=seed, depth=depth)
            kind = None
            for node in ordered_stmts(self.index, info.node):
                if isinstance(node, ast.Call):
                    k = self.sink_hit(node, env, info.path, info.cls,
                                      depth)
                    kind = _join(kind, k)
                elif self.decision_sinks and isinstance(
                        node, (ast.If, ast.While)):
                    if self.taint_of(node.test, env, info.path, info.cls,
                                     depth):
                        kind = _join(kind, "decision")
                if kind:
                    break
        finally:
            self._in_progress.discard(("p", qual, param))
        self._param_sink_memo[key] = kind
        return kind

    def param_returns(self, qual: str, param: int, depth: int) -> bool:
        """Does ``qual`` return a value derived from positional param
        ``param`` (within depth)?  Evaluated with a sentinel-only env so
        other taint in the callee cannot mask the answer."""
        key = (qual, param, depth)
        if key in self._param_ret_memo:
            return self._param_ret_memo[key]
        if ("pr", qual, param) in self._in_progress or depth < 0:
            return False
        info = self.index.functions.get(qual)
        if info is None:
            return False
        args = info.node.args
        names = [p.arg for p in args.posonlyargs + args.args]
        if info.cls and names and names[0] in ("self", "cls"):
            names = names[1:]
        if param >= len(names):
            self._param_ret_memo[key] = False
            return False
        self._in_progress.add(("pr", qual, param))
        try:
            sentinel = f"param:{names[param]}"
            env = {names[param]: sentinel}
            for _ in range(2):
                self._env_pass(info.node, env, info.path, info.cls, depth)
            hit = False
            for node in ordered_stmts(self.index, info.node):
                if isinstance(node, (ast.Return, ast.Yield)) \
                        and node.value is not None:
                    if self.taint_of(node.value, env, info.path, info.cls,
                                     depth) == sentinel:
                        hit = True
                        break
        finally:
            self._in_progress.discard(("pr", qual, param))
        self._param_ret_memo[key] = hit
        return hit

    def sink_hit(self, call: ast.Call, env: dict[str, str], path: str,
                 cls_qual: str | None, depth: int) -> str | None:
        """The sink kind this call realizes with the given env: a
        registered sink consuming a tainted argument, or a resolved
        callee that forwards a tainted positional argument into one
        (depth-capped)."""
        site = self._site(call, path, cls_qual)
        if self.sanitizer(site):
            return None
        arg_taints = [self.taint_of(a, env, path, cls_qual, depth)
                      for a in call.args]
        kw_taints = [self.taint_of(kw.value, env, path, cls_qual, depth)
                     for kw in call.keywords]
        any_tainted = _join(*arg_taints, *kw_taints)
        kind = self.sink(site)
        if kind and any_tainted:
            return kind
        if site.callee is not None and depth > 0:
            for i, lb in enumerate(arg_taints):
                if lb is None:
                    continue
                k = self.param_sink(site.callee, i, depth - 1)
                if k:
                    return k
        return None
