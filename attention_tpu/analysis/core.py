"""Pass registry, visitor plumbing, and the finding model.

The runtime half of the correctness story (obs counters, chaos
campaigns) catches defects after a kernel traces and produces a wrong
number; this package is the static half — hazards that are decidable
from source (impure Python under trace, Pallas contract violations,
silent low-precision matmuls, error-taxonomy drift) are flagged before
anything compiles.  Every rule carries a stable ``ATP###`` code:

- findings can be suppressed inline with ``# atp: disable=ATP###``
  (same physical line; bare ``# atp: disable`` suppresses every code);
- accepted legacy findings live in ``analysis/baseline.json`` — every
  entry carries a human justification (see `report.load_baseline`);
- codes never get renumbered, only retired.

Two pass shapes cover everything:

- **file passes** run per Python file on its parsed AST
  (``fn(path, tree, src) -> Iterable[Finding]``);
- **project passes** run once per tree (``fn(root) -> ...``) — the
  absorbed ``scripts/check_*`` lints and the tracked-file guard.

Deliberately jax-free: the analyzer imports nothing that imports jax,
so a tree-wide run is parse + walk, seconds not minutes.
"""

from __future__ import annotations

import ast
import dataclasses
import enum
import os
import re
from typing import Callable, Iterable, Iterator

#: sub-trees (and single files) scanned by default, repo-root-relative —
#: the same surface scripts/check_obs_names.py always linted
SCAN = ("attention_tpu", "scripts", "tests", "bench.py")


class Severity(enum.Enum):
    ERROR = "error"
    WARNING = "warning"


@dataclasses.dataclass(frozen=True)
class Code:
    """One stable rule id: ``ATP###`` + title + default severity."""

    code: str
    title: str
    severity: Severity
    summary: str

    _RE = re.compile(r"^ATP\d{3}$")

    def __post_init__(self):
        if not self._RE.match(self.code):
            raise ValueError(f"rule id {self.code!r} is not ATP###")


#: code -> Code, insertion-ordered (the README table is generated
#: from this registry so docs cannot drift from the enforcing set)
CODES: dict[str, Code] = {}


def register_code(code: str, title: str, severity: Severity,
                  summary: str) -> str:
    if code in CODES:
        raise ValueError(f"duplicate rule id {code}")
    CODES[code] = Code(code, title, severity, summary)
    return code


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one location.

    ``line`` is 1-based; 0 means the finding is about the whole file
    (or, for project passes, about a non-Python artifact).
    """

    code: str
    message: str
    path: str
    line: int = 0
    col: int = 0

    @property
    def severity(self) -> Severity:
        return CODES[self.code].severity

    def location(self) -> str:
        if self.line:
            return f"{self.path}:{self.line}:{self.col}"
        return self.path

    def to_dict(self) -> dict:
        return {
            "code": self.code,
            "severity": self.severity.value,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }


@dataclasses.dataclass(frozen=True)
class Pass:
    name: str
    codes: tuple[str, ...]
    scope: str  # "file" | "project"
    fn: Callable
    doc: str
    #: interprocedural passes get the tree-wide `callgraph.ProjectIndex`
    #: as an extra ``index=`` argument (built once per analyze() run)
    needs_index: bool = False


#: pass name -> Pass, insertion-ordered
PASSES: dict[str, Pass] = {}


def _register(name: str, codes: Iterable[str], scope: str, fn: Callable,
              needs_index: bool = False):
    if name in PASSES:
        raise ValueError(f"duplicate pass {name!r}")
    codes = tuple(codes)
    for c in codes:
        if c not in CODES:
            raise ValueError(f"pass {name!r} emits unregistered code {c}")
    PASSES[name] = Pass(name, codes, scope, fn,
                        (fn.__doc__ or "").strip().splitlines()[0]
                        if fn.__doc__ else "",
                        needs_index)
    return fn


def file_pass(name: str, codes: Iterable[str], needs_index: bool = False):
    """Register ``fn(path, tree, src) -> Iterable[Finding]`` to run on
    every scanned Python file (``path`` is repo-root-relative).  With
    ``needs_index`` the signature grows an ``index=None`` 4th param."""

    def deco(fn):
        return _register(name, codes, "file", fn, needs_index)

    return deco


def project_pass(name: str, codes: Iterable[str],
                 needs_index: bool = False):
    """Register ``fn(root) -> Iterable[Finding]`` to run once per tree
    (with ``needs_index``: ``fn(root, index=None)``)."""

    def deco(fn):
        return _register(name, codes, "project", fn, needs_index)

    return deco


# -- shared AST helpers ---------------------------------------------------

def dotted_name(node: ast.expr) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


#: id(tree) -> (tree, flattened walk).  The tree is held strongly so
#: its id cannot be recycled under us; capped so a long-lived process
#: feeding synthetic trees (tests) cannot grow it without bound.
_WALK_CACHE: dict[int, tuple[ast.AST, list[ast.AST]]] = {}
_WALK_CACHE_MAX = 4096


def walk_list(tree: ast.AST) -> list[ast.AST]:
    """``list(ast.walk(tree))`` memoized by tree identity.

    Several passes walk the same parsed module top to bottom (purity
    twice, precision via traced_functions, obs-naming, pallas, the
    index build): flattening once and sharing the list is the single
    biggest win in the tree-wide time budget.  Callers must not mutate
    the returned list.
    """
    hit = _WALK_CACHE.get(id(tree))
    if hit is not None and hit[0] is tree:
        return hit[1]
    # inlined ast.walk (same BFS order): the generator-over-generator
    # cost of iter_child_nodes dominated the tree-wide flatten
    AST = ast.AST
    nodes: list[ast.AST] = [tree]
    i = 0
    while i < len(nodes):
        node = nodes[i]
        i += 1
        d = node.__dict__
        for field in node._fields:
            value = d.get(field)
            if isinstance(value, AST):
                nodes.append(value)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, AST):
                        nodes.append(v)
    if len(_WALK_CACHE) >= _WALK_CACHE_MAX:
        _WALK_CACHE.clear()
    _WALK_CACHE[id(tree)] = (tree, nodes)
    return nodes


def iter_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Walk ``node``'s subtree but do NOT descend into nested
    function/class scopes (their bodies are separate scopes)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(child))


#: id(scope node) -> (node, flattened iter_scope) — same contract as
#: ``_WALK_CACHE`` above, but its own (larger) cap: the tree has a few
#: thousand distinct scopes and precision + the shape interpreter
#: flatten every one, so a shared 1024 cap thrashed
_SCOPE_CACHE: dict[int, tuple[ast.AST, list[ast.AST]]] = {}
_SCOPE_CACHE_MAX = 8192


def scope_list(node: ast.AST) -> list[ast.AST]:
    """``list(iter_scope(node))`` memoized by node identity.  Callers
    must not mutate the returned list."""
    hit = _SCOPE_CACHE.get(id(node))
    if hit is not None and hit[0] is node:
        return hit[1]
    # inlined iter_scope (identical stack-pop order), generator-free
    AST = ast.AST
    scope_kinds = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef)
    stack: list[ast.AST] = []
    d = node.__dict__
    for field in node._fields:
        value = d.get(field)
        if isinstance(value, AST):
            stack.append(value)
        elif isinstance(value, list):
            for v in value:
                if isinstance(v, AST):
                    stack.append(v)
    nodes: list[ast.AST] = []
    while stack:
        child = stack.pop()
        nodes.append(child)
        if not isinstance(child, scope_kinds):
            d = child.__dict__
            for field in child._fields:
                value = d.get(field)
                if isinstance(value, AST):
                    stack.append(value)
                elif isinstance(value, list):
                    for v in value:
                        if isinstance(v, AST):
                            stack.append(v)
    if len(_SCOPE_CACHE) >= _SCOPE_CACHE_MAX:
        _SCOPE_CACHE.clear()
    _SCOPE_CACHE[id(node)] = (node, nodes)
    return nodes


# -- suppression ----------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*atp:\s*disable(?:=(?P<codes>[A-Z0-9_,\s]+?))?\s*(?:#|$)"
)


def suppressions(line_text: str) -> set[str] | None:
    """The codes an ``# atp: disable[=...]`` comment on this physical
    line suppresses: None when there is no directive, an empty set for
    a bare ``disable`` (suppress everything), else the listed codes."""
    m = _SUPPRESS_RE.search(line_text)
    if not m:
        return None
    codes = m.group("codes")
    if not codes:
        return set()
    return {c.strip() for c in codes.split(",") if c.strip()}


def is_suppressed(finding: Finding, src_lines: list[str]) -> bool:
    if not finding.line or finding.line > len(src_lines):
        return False
    sup = suppressions(src_lines[finding.line - 1])
    if sup is None:
        return False
    return not sup or finding.code in sup


# -- file discovery + the runner ------------------------------------------

ATP001 = register_code(
    "ATP001", "unparsable-source", Severity.ERROR,
    "a scanned .py file fails to parse (syntax error)")


def repo_root() -> str:
    """The checkout root: the directory holding ``attention_tpu/``."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def iter_source_files(root: str) -> Iterator[str]:
    """Repo-root-relative paths of every scanned ``.py`` file."""
    for rel in SCAN:
        top = os.path.join(root, rel)
        if os.path.isfile(top):
            yield rel
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.relpath(
                        os.path.join(dirpath, fn), root
                    ).replace(os.sep, "/")


def analyze_file(root: str, rel: str,
                 passes: Iterable[Pass] | None = None,
                 index=None,
                 timings: dict[str, float] | None = None) -> list[Finding]:
    """Run the file passes on one file; suppressions already applied."""
    passes = [p for p in (PASSES.values() if passes is None else passes)
              if p.scope == "file"]
    mod = index.modules.get(rel) if index is not None else None
    if mod is not None:  # reuse the index's parse
        src, tree = mod.src, mod.tree
    else:
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError as e:
            return [Finding(ATP001, f"syntax error: {e.msg}", rel,
                            e.lineno or 0, (e.offset or 1) - 1)]
    findings: list[Finding] = []
    for p in passes:
        t0 = _clock()
        if p.needs_index:
            findings.extend(p.fn(rel, tree, src, index=index))
        else:
            findings.extend(p.fn(rel, tree, src))
        if timings is not None:
            timings[p.name] = timings.get(p.name, 0.0) + _clock() - t0
    lines = src.splitlines()
    return [f for f in findings if not is_suppressed(f, lines)]


def _clock() -> float:
    import time
    return time.perf_counter()


def build_index(root: str, rel_paths: Iterable[str] | None = None):
    """The tree-wide ``callgraph.ProjectIndex`` (imported lazily so
    plain file-pass runs never pay for it)."""
    from attention_tpu.analysis import callgraph
    return callgraph.ProjectIndex.build(root, rel_paths)


def analyze(root: str | None = None,
            rel_paths: Iterable[str] | None = None,
            passes: Iterable[str] | None = None,
            include_project: bool = True,
            timings: dict[str, float] | None = None,
            index=None) -> list[Finding]:
    """Run registered passes over the tree (or just ``rel_paths``).

    Project passes always see the whole tree — they check committed
    artifacts (tables, ledgers, the git index), not individual files —
    so a ``--changed`` run still enforces them.  When any selected pass
    is interprocedural the project index is built once (over the WHOLE
    tree, even for a ``rel_paths`` run: call edges cross files) and
    threaded through.  ``timings`` (when given) collects cumulative
    per-pass wall seconds plus the index build under ``"<index>"``.
    """
    root = root or repo_root()
    selected = ([PASSES[name] for name in passes] if passes
                else list(PASSES.values()))
    if rel_paths is None:
        rel_paths = list(iter_source_files(root))
    if index is None and any(p.needs_index for p in selected):
        t0 = _clock()
        index = build_index(root)
        if timings is not None:
            timings["<index>"] = _clock() - t0
    findings: list[Finding] = []
    file_passes = [p for p in selected if p.scope == "file"]
    for rel in rel_paths if file_passes else ():
        if not rel.endswith(".py"):
            continue
        if not os.path.isfile(os.path.join(root, rel)):
            continue  # e.g. --changed listing a deleted file
        findings.extend(analyze_file(root, rel, file_passes, index=index,
                                     timings=timings))
    if include_project:
        for p in selected:
            if p.scope == "project":
                t0 = _clock()
                if p.needs_index:
                    findings.extend(p.fn(root, index=index))
                else:
                    findings.extend(p.fn(root))
                if timings is not None:
                    timings[p.name] = (timings.get(p.name, 0.0)
                                       + _clock() - t0)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings
