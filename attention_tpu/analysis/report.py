"""Reporting: text/JSON/SARIF renderers + the justified baseline.

The baseline (``attention_tpu/analysis/baseline.json``) is the list of
*accepted* findings: real rule hits that are deliberate and stay in
the tree.  Every entry MUST carry a non-empty ``justification`` — a
silent baseline is just a second way to ignore the linter.  Entries
match findings by code + path plus either:

- ``match``: a substring of the finding message (pin one specific
  finding), and/or
- ``count``: exactly how many findings of that code live in that path
  (pin a family, e.g. "7 ValueError validations in request.py") — a
  new finding of the same shape changes the count and fails the gate.

An entry that matches nothing (or whose count drifts) is *stale* and
fails the run too: the baseline can only shrink honestly.
"""

from __future__ import annotations

import dataclasses
import json
import os

from attention_tpu.analysis.core import CODES, Finding, Severity

BASELINE_REL = "attention_tpu/analysis/baseline.json"
BASELINE_VERSION = 1


@dataclasses.dataclass(frozen=True)
class BaselineEntry:
    code: str
    path: str
    justification: str
    match: str | None = None
    count: int | None = None


def load_baseline(path: str) -> list[BaselineEntry]:
    """Parse + validate a baseline file (every entry justified)."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"{path}: baseline version {data.get('version')!r} != "
            f"{BASELINE_VERSION}")
    entries = []
    for i, raw in enumerate(data.get("entries", [])):
        just = (raw.get("justification") or "").strip()
        if not just:
            raise ValueError(
                f"{path}: entry {i} ({raw.get('code')} "
                f"{raw.get('path')}) has no justification — silent "
                "baseline entries are not allowed")
        if raw.get("code") not in CODES:
            raise ValueError(
                f"{path}: entry {i} names unknown code "
                f"{raw.get('code')!r}")
        if not raw.get("path"):
            raise ValueError(f"{path}: entry {i} has no path")
        entries.append(BaselineEntry(
            code=raw["code"], path=raw["path"], justification=just,
            match=raw.get("match"), count=raw.get("count")))
    return entries


def save_baseline(path: str, entries: list[BaselineEntry]) -> None:
    data = {
        "version": BASELINE_VERSION,
        "entries": [
            {k: v for k, v in dataclasses.asdict(e).items()
             if v is not None}
            for e in entries
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=False)
        f.write("\n")


def apply_baseline(
    findings: list[Finding], entries: list[BaselineEntry],
) -> tuple[list[Finding], list[str]]:
    """(unbaselined findings, baseline problems).

    Matched findings are filtered out; an entry matching nothing, or a
    ``count`` entry whose actual count drifted, is reported as a
    problem (stale/drifted baselines fail the gate both ways).
    """
    remaining = list(findings)
    problems: list[str] = []
    for e in entries:
        matched = [f for f in remaining
                   if f.code == e.code and f.path == e.path
                   and (e.match is None or e.match in f.message)]
        if not matched:
            problems.append(
                f"stale baseline entry: {e.code} {e.path}"
                + (f" (match={e.match!r})" if e.match else "")
                + " no longer matches any finding — delete it")
        elif e.count is not None and len(matched) != e.count:
            problems.append(
                f"baseline count drift: {e.code} {e.path} pins "
                f"{e.count} finding(s) but the tree has "
                f"{len(matched)} — re-justify or fix")
        for f in matched:
            remaining.remove(f)
    return remaining, problems


def default_baseline_path(root: str) -> str:
    return os.path.join(root, BASELINE_REL)


# -- renderers ------------------------------------------------------------

def render_text(findings: list[Finding],
                baseline_problems: list[str] = ()) -> str:
    lines = []
    for f in findings:
        lines.append(f"{f.location()}: {f.severity.value} "
                     f"{f.code} {f.message}")
    for p in baseline_problems:
        lines.append(f"baseline: error {p}")
    n_err = sum(1 for f in findings if f.severity is Severity.ERROR)
    n_warn = len(findings) - n_err
    if findings or baseline_problems:
        lines.append(
            f"{len(findings)} finding(s): {n_err} error(s), "
            f"{n_warn} warning(s)"
            + (f"; {len(baseline_problems)} baseline problem(s)"
               if baseline_problems else ""))
    else:
        lines.append("analysis OK")
    return "\n".join(lines) + "\n"


def render_json(findings: list[Finding],
                baseline_problems: list[str] = ()) -> str:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.code] = counts.get(f.code, 0) + 1
    return json.dumps({
        "version": 1,
        "findings": [f.to_dict() for f in findings],
        "baseline_problems": list(baseline_problems),
        "counts": counts,
    }, sort_keys=True) + "\n"


def _gh_escape_data(s: str) -> str:
    """Escape workflow-command message data (GitHub's own rules)."""
    return s.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")


def _gh_escape_prop(s: str) -> str:
    """Escape a workflow-command property value (adds , and :)."""
    return _gh_escape_data(s).replace(",", "%2C").replace(":", "%3A")


def render_github(findings: list[Finding],
                  baseline_problems: list[str] = ()) -> str:
    """GitHub Actions workflow commands, one per finding.

    ``::error file=...,line=...,col=...,title=ATP###::message`` lines
    annotate the diff inline when the gate runs inside a workflow —
    same findings as the text renderer, no separate CI glue needed.
    Column is 1-based (the UI convention); whole-file findings
    (``line == 0``) carry only ``file=``.
    """
    lines = []
    for f in findings:
        kind = "error" if f.severity is Severity.ERROR else "warning"
        props = [f"file={_gh_escape_prop(f.path)}"]
        if f.line:
            props.append(f"line={f.line}")
            props.append(f"col={f.col + 1}")
        props.append(f"title={_gh_escape_prop(f.code)}")
        lines.append(f"::{kind} " + ",".join(props)
                     + f"::{_gh_escape_data(f.message)}")
    for p in baseline_problems:
        lines.append(f"::error file={BASELINE_REL},title=baseline"
                     f"::{_gh_escape_data(p)}")
    return "\n".join(lines) + "\n" if lines else ""


def render_sarif(findings: list[Finding],
                 baseline_problems: list[str] = ()) -> str:
    """Minimal SARIF 2.1.0 — one run, one rule per registered code."""
    used = sorted({f.code for f in findings})
    rules = [{
        "id": code,
        "name": CODES[code].title,
        "shortDescription": {"text": CODES[code].summary},
        "defaultConfiguration": {
            "level": CODES[code].severity.value},
    } for code in used]
    results = [{
        "ruleId": f.code,
        "level": f.severity.value,
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {"startLine": max(f.line, 1),
                           "startColumn": f.col + 1},
            },
        }],
    } for f in findings]
    for p in baseline_problems:
        results.append({
            "ruleId": "ATP000",
            "level": "error",
            "message": {"text": f"baseline: {p}"},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": BASELINE_REL},
                    "region": {"startLine": 1, "startColumn": 1},
                },
            }],
        })
    return json.dumps({
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "attention-tpu-analysis",
                "informationUri":
                    "https://github.com/attention-tpu",
                "rules": rules,
            }},
            "results": results,
        }],
    }, sort_keys=True) + "\n"
