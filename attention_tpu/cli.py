"""CLI harness: the reference's frozen main() contract, generalized.

The reference binary is ``./attention <testcase.bin>`` → load, compute,
verify, print "Correct!/Wrong!" + elapsed µs (`attention.c:164-196`,
`attention-mpi.c:497-541`).  This CLI preserves that exact interaction —
same output lines, same exit semantics — and adds what the course grader
provided externally: testcase generation and backend/precision selection
(the serial-vs-MPI binary split becomes ``--backend``).

Usage:
  python -m attention_tpu.cli run <testcase.bin> [--backend flash]
      [--dtype bf16|f32|f64] [--repeats 1] [--no-verify]
  python -m attention_tpu.cli generate <out.bin> --m 1024 --n 1024
      --dk 128 --dv 128 [--seed 0]
  python -m attention_tpu.cli suite <out_dir>     # simple..scale5 ladder
  python -m attention_tpu.cli backends
  python -m attention_tpu.cli tune --kernel flash --seq 32768 --dim 128
      # timed on-device tile search; winners persist in the per-device
      # cache (~/.cache/attention_tpu/) and future calls pick them up
  python -m attention_tpu.cli serve-sim [--trace trace.json]
      [--num-requests 8 --shared-prefix-len 129 --shared-count 4 ...]
      [--replicas 3 --deadline-ms 40 --tick-ms 1 --max-retries 3
       --chaos-plan plan.json --bursty --tenants 2]
      [--standbys 1 --suspect-after 2 --gray-plan gray.json]
      [--obs --obs-out run_dir [--obs-profile]]
      # continuous-batching engine over a request trace; prints
      # per-step (--per-step) and summary metrics JSON; --obs-out
      # persists the telemetry dump for `cli obs`; --replicas N serves
      # through the resilient multi-replica front end
      # (attention_tpu.frontend: deadlines, retry-with-backoff, load
      # shedding, graceful degradation) and --chaos-plan attaches a
      # replica-kill storm; --gray-plan attaches a gray-failure storm
      # (slow/flaky/stall/NaN windows) against the replica supervisor,
      # --standbys keeps warm spares for DEAD-verdict promotion, and
      # --trace-out embeds the gray plan so the run replays
      # byte-identically from the trace file alone
  python -m attention_tpu.cli analyze [paths ...] [--changed]
      [--format text|json|sarif] [--baseline FILE | --no-baseline]
      [--list-codes]
      # static analysis (attention_tpu.analysis): AST passes with
      # stable ATP### codes — trace purity, Pallas contracts,
      # precision, error taxonomy, tree conventions; exit 0 iff clean
      # modulo analysis/baseline.json; --changed lints only files
      # touched since `git merge-base HEAD --base`
  python -m attention_tpu.cli obs report --run run_dir
  python -m attention_tpu.cli obs export --run run_dir
      --format chrome|prom|jsonl [--out timeline.json]
      # unified telemetry (attention_tpu.obs): counters/spans summary,
      # or export — chrome merges host spans with the XLA device lane
  python -m attention_tpu.cli chaos fuzz --seed 0 --cases 16
      [--families flash,decode,...] [--inject-failure] [--repro-dir DIR]
  python -m attention_tpu.cli chaos replay <repro.json|repro.bin>
  python -m attention_tpu.cli chaos shrink repro.json [--bin repro.bin]
  python -m attention_tpu.cli chaos faults --seed 0 --plans 5
      [--replicas 3]
      # differential fuzzing + engine fault injection
      # (attention_tpu.chaos): sampled kernel configs vs the fp64
      # oracle under the tolerance ledger; failing configs shrink to
      # minimal repros (plain ones to the reference .bin format `run`
      # replays); seeded fault plans storm the serving engine under
      # invariant checkers

Diagnostics (progress notes, warnings) go through the shared
``attention_tpu`` stdlib logger, stderr at INFO — the frozen
reference-contract lines (Correct!/Wrong!/Elapsed time) stay on
stdout, exactly as `attention.c` printed them.
"""

from __future__ import annotations

import argparse
import logging
import sys
import time

import numpy as np

_logger = logging.getLogger("attention_tpu.cli")


def _setup_logging(level: int = logging.INFO) -> None:
    """Attach one stderr handler to the shared ``attention_tpu`` logger
    (idempotent).  Library modules log under ``attention_tpu.*``; the
    CLI is the place that decides those records are user-visible."""
    root = logging.getLogger("attention_tpu")
    if not root.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter("%(name)s: %(message)s"))
        root.addHandler(h)
    # our handler is the single sink: without this, a root logger that
    # jax/absl already configured would print every record twice
    root.propagate = False
    root.setLevel(level)


def _cmd_run(args: argparse.Namespace) -> int:
    from attention_tpu import attention
    from attention_tpu.core.testcase import read_testcase, verify

    try:
        case = read_testcase(args.testcase)
    except FileNotFoundError:
        # reference diagnostic (attention.c:103-106)
        print(f"Cannot open file: {args.testcase}", file=sys.stderr)
        return 1
    except ValueError:
        print("Invalid testing data.", file=sys.stderr)  # attention.c:112
        return 1
    m, n, dk, dv = case.dims

    dtype = {"bf16": "bfloat16", "f32": "float32", "f64": "float64"}[args.dtype]
    if dtype == "bfloat16":
        import jax.numpy as jnp

        q, k, v = (jnp.asarray(x, dtype=jnp.bfloat16) for x in (case.q, case.k, case.v))
    else:
        q, k, v = (x.astype(dtype) for x in (case.q, case.k, case.v))

    from attention_tpu.utils.timing import benchmark, benchmark_attention

    # One untimed run produces the result and doubles as warmup, keeping
    # one-time costs (jit compilation; the native backend's first-use C
    # build) out of the timed region — the reference's timed region is
    # pure compute (attention.c:180-182), its compile happened at build
    # time.  Timing then follows the shared min-over-repeats discipline.
    # Host backends (numpy/C) get plain fence timing — it is honest for
    # them; device backends go through the tunnel-aware clock.
    result = attention(q, k, v, backend=args.backend)
    if args.backend in ("oracle", "native"):
        timing = benchmark(
            attention, q, k, v, backend=args.backend,
            repeats=max(1, args.repeats), warmup=0,
        )
    else:
        timing = benchmark_attention(
            attention, q, k, v, backend=args.backend,
            repeats=max(1, args.repeats), warmup=0,
        )
    best_us = timing.best_us
    result = np.asarray(result, dtype=np.float64)

    if args.no_verify or case.expected is None:
        print(f"Elapsed time: {best_us:.2f} us")
        return 0
    # Exact frozen output contract (attention.c:150-151,184-189): success
    # is "Correct!" + elapsed; failure is the first-mismatch diagnostic on
    # stdout then ONLY "Wrong!", and the exit status is 0 either way.
    # --stats appends one opt-in full-scan line AFTER the frozen lines
    # (max-abs-error / mismatch count — `core.testcase.verify_scan`).
    ok, msg = verify(case.expected, result)
    if ok:
        print("Correct!")
        print(f"Elapsed time: {best_us:.2f} us")
    else:
        print(msg)
        print("Wrong!")
    if args.stats:
        from attention_tpu.core.testcase import verify_scan

        print(verify_scan(case.expected, result).stats_line())
    return 0


def _cmd_generate(args: argparse.Namespace) -> int:
    from attention_tpu.core.testcase import generate_testcase, write_testcase

    case = generate_testcase(args.m, args.n, args.dk, args.dv, seed=args.seed)
    write_testcase(args.out, case)
    print(f"wrote {args.out}: m={args.m} n={args.n} dk={args.dk} dv={args.dv} "
          f"({case.nbytes()} bytes)")
    return 0


def _cmd_suite(args: argparse.Namespace) -> int:
    from attention_tpu.core.testcase import generate_suite

    for path in generate_suite(args.out_dir, seed=args.seed):
        print(f"wrote {path}")
    return 0


def _cmd_backends(_args: argparse.Namespace) -> int:
    from attention_tpu import available_backends

    for name in available_backends():
        print(name)
    return 0


def _build_sim_model(args: argparse.Namespace):
    """Deterministic tiny decoder for serving simulation: params come
    from PRNGKey(--model-seed), so a trace replays bit-identically."""
    import jax
    import jax.numpy as jnp

    from attention_tpu.models import TinyDecoder

    dtype = {"f32": jnp.float32, "bf16": jnp.bfloat16}[args.dtype]
    model = TinyDecoder(
        vocab=args.vocab, dim=args.dim, depth=args.depth,
        num_q_heads=args.q_heads, num_kv_heads=args.kv_heads,
        impl="flash", dtype=dtype,
    )
    probe = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(args.model_seed), probe)["params"]
    return model, params


def _cmd_serve_sim(args: argparse.Namespace) -> int:
    """Run the continuous-batching engine on a request trace (from
    --trace JSON, else synthetic) and print metrics JSON."""
    import json

    from attention_tpu.engine import (
        EngineConfig,
        ServingEngine,
        load_trace,
        replay,
        synthetic_trace,
    )

    obs_on = args.obs or args.obs_out or args.obs_profile
    if obs_on:
        from attention_tpu import obs

        obs.enable()
        obs.reset()

    model, params = _build_sim_model(args)
    if args.trace:
        trace = load_trace(args.trace)
    elif args.diurnal:
        from attention_tpu.engine import diurnal_trace

        trace = diurnal_trace(
            args.num_requests, vocab=args.vocab, seed=args.seed,
            period=args.diurnal_period, base_rate=args.base_rate,
            peak_rate=args.peak_rate, tenants=args.tenants,
            rag_every=args.rag_every,
            rag_prefill_len=args.rag_prefill_len,
            prompt_len_min=args.prompt_len_min,
            prompt_len_max=args.prompt_len_max,
            max_tokens=args.max_tokens,
            temperature=args.temperature,
        )
    elif args.disagg:
        from attention_tpu.engine.sim import disagg_trace

        trace = disagg_trace(
            args.num_requests, vocab=args.vocab, seed=args.seed,
            rate=args.base_rate, tenants=args.tenants,
            burst_every=args.burst_every, burst_size=args.burst_size,
            rag_prefill_len=args.rag_prefill_len,
            prompt_len_min=args.prompt_len_min,
            prompt_len_max=args.prompt_len_max,
            max_tokens=args.max_tokens,
            temperature=args.temperature,
        )
    elif args.bursty:
        from attention_tpu.engine import bursty_trace

        trace = bursty_trace(
            args.num_requests, vocab=args.vocab, seed=args.seed,
            tenants=args.tenants, burst_every=args.burst_every,
            burst_size=args.burst_size,
            shared_prefix_len=args.shared_prefix_len,
            prompt_len_min=args.prompt_len_min,
            prompt_len_max=args.prompt_len_max,
            max_tokens=args.max_tokens,
            temperature=args.temperature,
        )
    else:
        trace = synthetic_trace(
            args.num_requests, vocab=args.vocab, seed=args.seed,
            prompt_len_min=args.prompt_len_min,
            prompt_len_max=args.prompt_len_max,
            max_tokens=args.max_tokens, arrival_every=args.arrival_every,
            shared_prefix_len=args.shared_prefix_len,
            shared_count=args.shared_count,
            temperature=args.temperature,
        )
    # resolve the gray plan early: an explicit --gray-plan wins, else a
    # --trace file's embedded annotation attaches automatically (the
    # gray storm replays from the trace file alone)
    gray_plan_doc = None
    if args.gray_plan:
        with open(args.gray_plan) as f:
            gray_plan_doc = json.load(f)
    elif args.trace:
        from attention_tpu.engine.sim import load_gray_plan

        gray_plan_doc = load_gray_plan(args.trace)
    if args.trace_out:
        from attention_tpu.engine import save_trace

        save_trace(args.trace_out, trace, gray_plan=gray_plan_doc)
        _logger.info("wrote trace: %s", args.trace_out)

    config = EngineConfig(
        num_pages=args.num_pages, page_size=args.page_size,
        max_seq_len=args.max_seq_len,
        max_decode_batch=args.max_decode_batch,
        max_prefill_rows=args.max_prefill_rows,
        prefill_chunk=args.prefill_chunk,
        token_budget=args.token_budget,
        watermark_pages=args.watermark_pages,
        mesh_shards=args.mesh_shards,
    )
    if (args.snapshot_dir is None) != (args.snapshot_every is None):
        print("--snapshot-dir and --snapshot-every must be set "
              "together", file=sys.stderr)
        return 2
    if args.disagg and args.replicas < 2:
        print("--disagg needs at least two replicas (--replicas >= 2): "
              "one prefill pool member and one decode pool member",
              file=sys.stderr)
        return 2
    if args.autoscale and not args.disagg:
        print("--autoscale acts on the disaggregated fleet's pools; "
              "set --disagg too", file=sys.stderr)
        return 2
    if args.autoscale and not args.standbys:
        print("--autoscale needs warm spares to promote "
              "(--standbys > 0)", file=sys.stderr)
        return 2
    if args.prefix_store and not args.replicas:
        print("--prefix-store needs the multi-replica front end "
              "(--replicas > 0): fleet-wide reuse has no meaning on "
              "one engine", file=sys.stderr)
        return 2
    if args.replicas:
        return _serve_sim_frontend(args, model, params, config, trace,
                                   gray_plan=gray_plan_doc)
    if gray_plan_doc is not None:
        _logger.info("gray plan ignored on the single-engine path "
                     "(gray failures need --replicas)")
    if args.anomaly or args.incident_dir:
        _logger.info("--anomaly/--incident-dir ignored on the "
                     "single-engine path (the incident layer runs in "
                     "the front-end tick loop; needs --replicas)")

    engine = ServingEngine(model, params, config)
    if args.snapshot_dir is not None:
        from attention_tpu.engine import SnapshotManager

        SnapshotManager(engine, args.snapshot_dir,
                        every=args.snapshot_every)
        _logger.info("snapshotting every %d steps to %s",
                     args.snapshot_every, args.snapshot_dir)
    import contextlib

    profile_cm = contextlib.nullcontext()
    if args.obs_profile:
        import os

        from attention_tpu.obs.export import DUMP_DEVICE
        from attention_tpu.utils import profiling

        if not args.obs_out:
            print("--obs-profile requires --obs-out", file=sys.stderr)
            return 2
        profile_cm = profiling.trace(
            os.path.join(args.obs_out, DUMP_DEVICE))
    with profile_cm:
        summary, outputs = replay(engine, trace, max_steps=args.max_steps)
    if args.per_step:
        for m in engine.metrics.steps:
            print(m.to_json())
    record = engine.metrics.to_run_record(
        config="engine-serve-sim",
        extra={"num_pages": config.num_pages,
               "page_size": config.page_size,
               "prefill_chunk": config.prefill_chunk,
               "max_decode_batch": config.max_decode_batch,
               "token_budget": config.token_budget},
    )
    out = {"summary": summary, "run_record": json.loads(record.to_json())}
    if args.outputs:
        out["outputs"] = outputs
    if args.obs_out:
        from attention_tpu import obs

        obs.dump(args.obs_out)
        _logger.info("wrote telemetry dump: %s", args.obs_out)
    print(json.dumps(out))
    return 0


def _serve_sim_frontend(args: argparse.Namespace, model, params,
                        config, trace, *,
                        gray_plan: dict | None = None) -> int:
    """serve-sim through the resilient multi-replica front end
    (attention_tpu.frontend): N engine replicas, deadlines, retry,
    shedding, optional chaos storm and gray-failure plans."""
    import json

    from attention_tpu.frontend import (
        FrontendConfig,
        RetryPolicy,
        ServingFrontend,
        SupervisorPolicy,
        replay_frontend,
    )

    ttl = None
    if args.deadline_ms is not None:
        ttl = max(1, int(round(args.deadline_ms / args.tick_ms)))
    supervisor = (SupervisorPolicy(suspect_after=args.suspect_after)
                  if args.suspect_after is not None
                  else SupervisorPolicy())
    forecast_policy = None
    if args.forecast or args.forecast_advisory:
        from attention_tpu.frontend import ForecastPolicy

        season = args.forecast_season
        if season is None and args.diurnal:
            season = args.diurnal_period
        forecast_policy = ForecastPolicy(
            season_ticks=season, horizon=args.forecast_horizon,
            advisory=args.forecast_advisory)
    prefix_store = None
    if args.prefix_store:
        from attention_tpu.prefixstore import PrefixStoreConfig

        prefix_store = PrefixStoreConfig(
            max_bytes=args.prefix_store_bytes)
    anomaly_policy = None
    if args.anomaly:
        from attention_tpu.obs.anomaly import AnomalyPolicy

        anomaly_policy = AnomalyPolicy()
    fleet_topology = None
    autoscaler_policy = None
    if args.disagg:
        from attention_tpu.fleet import AutoscalerPolicy, FleetTopology

        # roughly 1:2 prefill:decode — prompts are bursty, streams are
        # steady — with the autoscaler free to rebalance at runtime
        prefill = max(1, args.replicas // 3)
        fleet_topology = FleetTopology(
            prefill_replicas=prefill,
            decode_replicas=args.replicas - prefill)
        if args.autoscale:
            autoscaler_policy = AutoscalerPolicy()
    frontend = ServingFrontend(
        model, params, config,
        FrontendConfig(
            num_replicas=args.replicas, seed=args.seed,
            retry=RetryPolicy(max_retries=args.max_retries),
            default_ttl_ticks=ttl,
            snapshot_dir=args.snapshot_dir,
            snapshot_every=args.snapshot_every,
            supervisor=supervisor,
            standbys=args.standbys,
            forecast=forecast_policy,
            prefix_store=prefix_store,
            anomaly=anomaly_policy,
            incident_dir=args.incident_dir,
            fleet=fleet_topology,
            autoscaler=autoscaler_policy,
        ),
    )
    if args.chaos_plan or gray_plan is not None:
        from attention_tpu.chaos.faults import (
            FaultPlan,
            FrontendFaultInjector,
        )

        if args.chaos_plan:
            with open(args.chaos_plan) as f:
                plan = FaultPlan.from_json(f.read())
            FrontendFaultInjector(frontend, plan)
            _logger.info("attached chaos plan: %s (%d events)",
                         args.chaos_plan, len(plan.events))
        if gray_plan is not None:
            plan = FaultPlan.from_json(json.dumps(gray_plan))
            FrontendFaultInjector(frontend, plan)
            _logger.info("attached gray plan (%d events)",
                         len(plan.events))
    summary, outputs = replay_frontend(frontend, trace,
                                       max_ticks=args.max_steps)
    record = frontend.to_run_record(
        config="frontend-serve-sim",
        extra={"num_pages": config.num_pages,
               "page_size": config.page_size,
               "deadline_ms": args.deadline_ms,
               "tick_ms": args.tick_ms},
    )
    # SLO observatory (obs.slo): deterministic error-budget accounting
    # over the run's latency rows, mirrored onto the frozen registry
    # series and persisted next to the telemetry dump for `cli obs slo`
    from attention_tpu.obs import slo as slo_mod

    slo_report = slo_mod.slo_report(frontend.latency_rows(),
                                    horizon_tick=summary["ticks"])
    slo_mod.publish(slo_report)
    out = {"summary": summary,
           "run_record": json.loads(record.to_json()),
           "slo": {"fleet": {ob["objective"]:
                             {"burn_rate": ob["burn_rate"],
                              "budget_remaining": ob["budget_remaining"],
                              "violations": ob["violations"]}
                             for ob in slo_report["fleet"]["slo"]}}}
    # forecast + capacity observatory (obs.forecast/capacity): a
    # deterministic document over the tracker's per-tick series,
    # persisted as forecast.json for `cli obs forecast`
    forecast_doc = None
    if frontend.forecast is not None:
        from attention_tpu.obs import capacity as capacity_mod
        from attention_tpu.obs import forecast as forecast_mod

        forecast_doc = frontend.forecast_report()
        forecast_mod.publish(forecast_doc)
        capacity_mod.publish(forecast_doc)
        pblk = next((b for b in forecast_doc["series"]
                     if b["name"] == forecast_mod.PRESSURE_SERIES), None)
        fleet = forecast_doc["capacity"]["fleet"]
        out["forecast"] = {
            "pressure_next": (pblk["forecast"][0]["mean"]
                              if pblk and pblk["forecast"] else None),
            "one_step_mape": (pblk["backtest"]["one_step_mape"]
                              if pblk else None),
            "headroom": fleet["headroom"],
            "cost_per_token": fleet["cost_per_token"],
            "time_to_saturation":
                forecast_doc["capacity"]["time_to_saturation"],
        }
    # incident layer: anomaly detector report + flight-recorder block.
    # The blackbox block lives at the CLI level (not in the frontend's
    # summary) so the off-path token streams stay byte-identical.
    anomaly_doc = None
    if frontend.anomaly is not None:
        anomaly_doc = frontend.anomaly.report()
        out["anomaly"] = {"firings": len(anomaly_doc["firings"]),
                          "active": anomaly_doc["active"]}
    if args.obs or args.obs_out or args.obs_profile or args.incident_dir:
        from attention_tpu.obs import blackbox as blackbox_mod

        out["blackbox"] = {
            "ring_depth": blackbox_mod.depth(),
            "events_total": blackbox_mod.total(),
            "incidents": (len(frontend.postmortem.written)
                          if frontend.postmortem is not None else 0),
        }
    if args.outputs:
        out["outputs"] = outputs
    if args.obs_out:
        from attention_tpu import obs

        obs.dump(args.obs_out)
        obs.write_slo(args.obs_out, slo_report)
        if forecast_doc is not None:
            obs.write_forecast(args.obs_out, forecast_doc)
        if anomaly_doc is not None:
            obs.write_anomaly(args.obs_out, anomaly_doc)
        _logger.info("wrote telemetry dump: %s", args.obs_out)
    print(json.dumps(out))
    return 0


def _snapshot_paths(path: str) -> list[str]:
    """A snapshot file as-is; a directory expands to its snapshots,
    newest first (the order recovery would consider them)."""
    import os

    if os.path.isdir(path):
        from attention_tpu.engine.snapshot import list_snapshots

        return [p for _, p in reversed(list_snapshots(path))]
    return [path]


def _cmd_snapshot_inspect(args: argparse.Namespace) -> int:
    """Print one JSON line per snapshot: manifest + reconstruction
    metadata, without loading pool payloads into an engine."""
    import json

    from attention_tpu.engine.errors import SnapshotError
    from attention_tpu.engine.snapshot import inspect
    from attention_tpu.fleet.handoff import inspect_handoff, is_handoff

    paths = _snapshot_paths(args.path)
    if not paths:
        print(f"no snapshots under {args.path}", file=sys.stderr)
        return 1
    rc = 0
    for p in paths:
        try:
            # handoff blobs (fleet.handoff) share the directory with
            # engine snapshots; sniff the manifest line and report the
            # per-section CRC verdicts instead of engine metadata
            with open(p, "rb") as f:
                blob = f.read()
            if is_handoff(blob):
                doc = inspect_handoff(blob)
                doc["path"] = p
                print(json.dumps(doc, sort_keys=True))
                if not doc["valid"]:
                    rc = 1
                continue
            print(json.dumps(inspect(p), sort_keys=True))
        except SnapshotError as e:
            print(json.dumps({"path": p, "error": str(e)},
                             sort_keys=True))
            rc = 1
    return rc


def _cmd_snapshot_verify(args: argparse.Namespace) -> int:
    """Validate snapshot integrity (magic, version, section table,
    per-section checksums); exit 0 iff every snapshot is restorable."""
    paths = _snapshot_paths(args.path)
    if not paths:
        print(f"no snapshots under {args.path}", file=sys.stderr)
        return 1
    from attention_tpu.engine.snapshot import verify

    rc = 0
    for p in paths:
        problems = verify(p)
        if problems:
            rc = 1
            for problem in problems:
                print(f"{p}: {problem}")
        else:
            print(f"{p}: ok")
    return rc


def _add_serve_sim_args(ss) -> None:
    """serve-sim's flag set, shared with scripts/engine_trace.py."""
    ss.add_argument("--trace", default=None,
                    help="JSON request trace to replay (default: "
                         "synthesize one from the --num-requests knobs)")
    ss.add_argument("--trace-out", default=None,
                    help="write the (possibly synthetic) trace here")
    ss.add_argument("--per-step", action="store_true",
                    help="emit one JSON line per engine step")
    ss.add_argument("--outputs", action="store_true",
                    help="include generated token ids in the summary")
    ss.add_argument("--max-steps", type=int, default=10000)
    # synthetic-trace knobs
    ss.add_argument("--num-requests", type=int, default=8)
    ss.add_argument("--seed", type=int, default=0)
    ss.add_argument("--prompt-len-min", type=int, default=4)
    ss.add_argument("--prompt-len-max", type=int, default=24)
    ss.add_argument("--max-tokens", type=int, default=8)
    ss.add_argument("--arrival-every", type=int, default=1)
    ss.add_argument("--shared-prefix-len", type=int, default=0)
    ss.add_argument("--shared-count", type=int, default=0)
    ss.add_argument("--temperature", type=float, default=0.0)
    # bursty multi-tenant trace knobs (engine.sim.bursty_trace)
    ss.add_argument("--bursty", action="store_true",
                    help="synthesize a multi-tenant bursty trace "
                         "(sessions, priorities, per-tenant shared "
                         "prefixes) instead of the plain one")
    ss.add_argument("--tenants", type=int, default=2)
    ss.add_argument("--burst-every", type=int, default=6)
    ss.add_argument("--burst-size", type=int, default=3)
    # diurnal trace knobs (engine.sim.diurnal_trace)
    ss.add_argument("--diurnal", action="store_true",
                    help="synthesize a sinusoidal diurnal trace (one "
                         "day of --diurnal-period ticks between "
                         "--base-rate and --peak-rate req/tick, with "
                         "periodic RAG prefill bursts) instead of the "
                         "plain one")
    ss.add_argument("--diurnal-period", type=int, default=48,
                    help="ticks per simulated day")
    ss.add_argument("--base-rate", type=float, default=1.0,
                    help="trough arrival rate, requests/tick")
    ss.add_argument("--peak-rate", type=float, default=4.0,
                    help="peak arrival rate, requests/tick")
    ss.add_argument("--rag-every", type=int, default=7,
                    help="every Nth diurnal request is a long-prefill "
                         "RAG burst")
    ss.add_argument("--rag-prefill-len", type=int, default=64,
                    help="shared retrieval-header length for RAG "
                         "bursts (0 disables them)")
    # load forecasting + capacity observatory (obs.forecast/capacity;
    # front-end path only)
    ss.add_argument("--forecast", action="store_true",
                    help="track per-tick fleet series and emit the "
                         "forecast + capacity report (front-end path "
                         "only; never changes scheduling)")
    ss.add_argument("--forecast-horizon", type=int, default=8,
                    help="forecast horizon in ticks")
    ss.add_argument("--forecast-season", type=int, default=None,
                    help="seasonal period in ticks (default: "
                         "--diurnal-period when --diurnal, else no "
                         "seasonal term)")
    ss.add_argument("--forecast-advisory", action="store_true",
                    help="log would-have-acted forecast events into "
                         "the event log (still never acts); implies "
                         "--forecast")
    # resilient multi-replica front end (attention_tpu.frontend)
    # disaggregated serving (attention_tpu.fleet)
    ss.add_argument("--disagg", action="store_true",
                    help="disaggregated prefill/decode fleet: fresh "
                         "admissions route to a prefill pool and hand "
                         "off to the decode pool at prompt commit, "
                         "shipping committed KV pages instead of "
                         "re-prefilling (needs --replicas >= 2); "
                         "without --trace, synthesizes the disagg "
                         "mixed workload (steady decode sessions + "
                         "RAG prefill bursts)")
    ss.add_argument("--autoscale", action="store_true",
                    help="closed-loop elastic autoscaler over the "
                         "fleet pools: promotes warm standbys on "
                         "forecast watermark crossings, drains + "
                         "demotes on sustained slack (needs --disagg "
                         "and --standbys > 0)")
    ss.add_argument("--replicas", type=int, default=0,
                    help="serve through the resilient front end with "
                         "N engine replicas (0 = single engine, the "
                         "legacy path)")
    ss.add_argument("--deadline-ms", type=float, default=None,
                    help="default per-request TTL in virtual ms "
                         "(converted to ticks via --tick-ms; "
                         "front-end path only)")
    ss.add_argument("--tick-ms", type=float, default=1.0,
                    help="virtual milliseconds per front-end tick")
    ss.add_argument("--max-retries", type=int, default=3,
                    help="front-end retry budget per request")
    ss.add_argument("--chaos-plan", default=None,
                    help="frontend fault-plan JSON (chaos.faults."
                         "FaultPlan) to attach to the run")
    # gray-failure supervision (attention_tpu.frontend.supervisor)
    ss.add_argument("--standbys", type=int, default=0,
                    help="warm spare replicas promoted on a DEAD "
                         "supervisor verdict (front-end path only)")
    ss.add_argument("--suspect-after", type=int, default=None,
                    help="supervisor hysteresis: consecutive bad ticks "
                         "before HEALTHY -> SUSPECT (default: policy "
                         "default)")
    ss.add_argument("--gray-plan", default=None,
                    help="gray-failure fault-plan JSON (slow_step/"
                         "flaky_step/stall/nan windows) to attach; a "
                         "--trace file's embedded gray_plan annotation "
                         "attaches automatically, and --trace-out "
                         "embeds the active plan")
    # crash-consistent durability (attention_tpu.engine.snapshot)
    ss.add_argument("--snapshot-dir", default=None,
                    help="persist checksummed engine snapshots + "
                         "journals here (per-replica subdirs on the "
                         "front-end path); requires --snapshot-every")
    ss.add_argument("--snapshot-every", type=int, default=None,
                    help="snapshot period in engine steps / front-end "
                         "ticks; requires --snapshot-dir")
    # global prefix tier (attention_tpu.prefixstore)
    ss.add_argument("--prefix-store", action="store_true",
                    help="attach the fleet-wide prefix store to the "
                         "multi-replica front end (--replicas > 0): "
                         "committed prompt pages export as CRC'd "
                         "records any replica imports on a miss, and "
                         "identical prompt storms prefill exactly "
                         "once fleet-wide (single-flight leases); "
                         "with --snapshot-dir the store persists as "
                         "its own checksummed section file")
    ss.add_argument("--prefix-store-bytes", type=int, default=1 << 22,
                    help="prefix-store byte budget (LRU-evicted)")
    # model knobs (deterministic from --model-seed)
    ss.add_argument("--vocab", type=int, default=64)
    ss.add_argument("--dim", type=int, default=64)
    ss.add_argument("--depth", type=int, default=2)
    ss.add_argument("--q-heads", type=int, default=4)
    ss.add_argument("--kv-heads", type=int, default=2)
    ss.add_argument("--dtype", choices=["f32", "bf16"], default="f32")
    ss.add_argument("--model-seed", type=int, default=0)
    # engine knobs
    ss.add_argument("--num-pages", type=int, default=64)
    ss.add_argument("--page-size", type=int, default=128)
    ss.add_argument("--max-seq-len", type=int, default=512)
    ss.add_argument("--max-decode-batch", type=int, default=8)
    ss.add_argument("--max-prefill-rows", type=int, default=2)
    ss.add_argument("--prefill-chunk", type=int, default=32)
    ss.add_argument("--token-budget", type=int, default=128)
    ss.add_argument("--watermark-pages", type=int, default=1)
    ss.add_argument("--mesh-shards", type=int, default=0,
                    help="serve through KV-head-sharded kernels on a "
                         "1D 'tp' mesh of N local devices (0 = "
                         "single-device; tokens are identical either "
                         "way; --kv-heads must divide by N; on CPU "
                         "set XLA_FLAGS=--xla_force_host_platform_"
                         "device_count=N)")
    # incident layer (obs.anomaly / obs.blackbox / obs.postmortem;
    # front-end path only)
    ss.add_argument("--anomaly", action="store_true",
                    help="run the deterministic anomaly detectors "
                         "(residual band, burn slope, gray failure) "
                         "in the tick loop; advisory-only, never "
                         "changes scheduling (front-end path only)")
    ss.add_argument("--incident-dir", default=None,
                    help="dump an incident-<tick>/ postmortem bundle "
                         "here on every typed error or detector "
                         "firing (front-end path only); read back "
                         "with `cli obs postmortem --run DIR`")
    # telemetry (attention_tpu.obs)
    ss.add_argument("--obs", action="store_true",
                    help="enable the unified telemetry subsystem for "
                         "this run (default off, zero overhead)")
    ss.add_argument("--obs-out", default=None,
                    help="write the telemetry dump (metrics.json + "
                         "events.jsonl) here; implies --obs")
    ss.add_argument("--obs-profile", action="store_true",
                    help="also capture a jax.profiler device trace "
                         "under <obs-out>/device for the merged "
                         "chrome timeline; implies --obs")


def _cmd_tune(args: argparse.Namespace) -> int:
    import json

    from attention_tpu.tuning.search import CLI_KERNELS, tune

    kernels = (list(CLI_KERNELS) if args.kernel == "all"
               else [args.kernel])
    rc = 0
    for name in kernels:
        _logger.info("tuning %s (seq=%d, dim=%d)...",
                     name, args.seq, args.dim)
        try:
            rec = tune(
                CLI_KERNELS[name],
                seq=args.seq, dim=args.dim, heads=args.heads,
                kv_heads=args.kv_heads, batch=args.batch,
                dtype=args.dtype, causal=args.causal,
                window=args.window, sinks=args.sinks, stats=args.stats,
                max_mode=args.max_mode,
                repeats=args.repeats, cache_path=args.cache,
                write=not args.dry_run,
                log=_logger.info,
            )
        except Exception as e:  # noqa: BLE001 - report and keep sweeping
            print(json.dumps({"kernel": name,
                              "error": f"{type(e).__name__}: "
                                       f"{str(e)[:200]}"}))
            rc = 1
            continue
        print(json.dumps(rec))
    return rc


def _chaos_defect(args: argparse.Namespace):
    """The synthetic-failure hook shared by the chaos subcommands."""
    if not getattr(args, "inject_failure", False):
        return None
    from attention_tpu.chaos.fuzzer import synthetic_defect

    return synthetic_defect


def _cmd_chaos_fuzz(args: argparse.Namespace) -> int:
    """Seeded differential fuzz campaign: sampled kernel configs vs the
    fp64 oracle, judged by the tolerance ledger.  Deterministic: same
    seed -> same cases -> same report."""
    import json

    from attention_tpu.chaos.configs import FAMILIES
    from attention_tpu.chaos.fuzzer import run_campaign
    from attention_tpu.chaos.shrink import write_repro_json

    families = (args.families.split(",") if args.families
                else list(FAMILIES))
    for fam in families:
        if fam not in FAMILIES:
            print(f"unknown family {fam!r}; known: {list(FAMILIES)}",
                  file=sys.stderr)
            return 2
    report = run_campaign(args.seed, args.cases, families=families,
                          max_mode=args.max_mode,
                          defect=_chaos_defect(args), log=_logger.info)
    if args.repro_dir and report.failures:
        import os

        os.makedirs(args.repro_dir, exist_ok=True)
        for i, r in enumerate(report.failures):
            path = os.path.join(args.repro_dir, f"repro-{i}.json")
            write_repro_json(path, r.config)
            _logger.info("wrote failing-config repro: %s", path)
    print(json.dumps(report.to_dict(), sort_keys=True))
    return 0 if report.ok else 1


def _cmd_chaos_replay(args: argparse.Namespace) -> int:
    """Re-run one repro: a `.bin` replays through the frozen run
    harness semantics (backend result vs embedded expected), a `.json`
    re-runs the exact fuzz case.  Exit 0 iff the case passes."""
    import json

    if args.repro.endswith(".bin"):
        from attention_tpu import attention
        from attention_tpu.core.testcase import read_testcase, verify_scan

        case = read_testcase(args.repro)
        if case.expected is None:
            print(f"no expected output in {args.repro}", file=sys.stderr)
            return 2
        result = np.asarray(
            attention(case.q, case.k, case.v, backend=args.backend),
            dtype=np.float64,
        )
        scan = verify_scan(case.expected, result)
        print("Correct!" if scan.ok else f"{scan.message}\nWrong!")
        print(scan.stats_line())
        return 0 if scan.ok else 1
    from attention_tpu.chaos.fuzzer import run_case
    from attention_tpu.chaos.shrink import read_repro_json

    result = run_case(read_repro_json(args.repro),
                      defect=_chaos_defect(args))
    print(json.dumps(result.to_dict(), sort_keys=True))
    return 0 if result.ok else 1


def _cmd_chaos_shrink(args: argparse.Namespace) -> int:
    """Minimize a failing repro config; write the minimal `.json` and,
    when the minimum is plain single-head attention, the reference
    `.bin` testcase that `cli run` replays."""
    import json

    from attention_tpu.chaos.shrink import (
        read_repro_json,
        shrink,
        write_repro_bin,
        write_repro_json,
    )

    config = read_repro_json(args.repro)
    try:
        res = shrink(config, defect=_chaos_defect(args),
                     log=_logger.info)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.out:
        write_repro_json(args.out, res.minimal)
        _logger.info("wrote minimal repro: %s", args.out)
    wrote_bin = None
    if args.bin:
        if res.minimal.is_plain:
            write_repro_bin(args.bin, res.minimal)
            wrote_bin = args.bin
            _logger.info("wrote .bin repro: %s", args.bin)
        else:
            _logger.info(
                ".bin skipped: minimal config is not plain (%s)",
                res.minimal.to_json())
    print(json.dumps({
        "original": json.loads(res.original.to_json()),
        "minimal": json.loads(res.minimal.to_json()),
        "steps": res.steps,
        "attempts": res.attempts,
        "max_abs_err": res.final.max_abs_err,
        "tolerance": res.final.tolerance,
        "bin": wrote_bin,
    }, sort_keys=True))
    return 0


def _cmd_chaos_faults(args: argparse.Namespace) -> int:
    """Seeded fault-injection campaign against the serving engine
    (--replicas 1, default) or the multi-replica front end
    (--replicas N > 1: replica-kill/restart storms on top of the
    OOM/preempt/cancel kinds).  Every plan must hold the engine
    invariants — plus, for storms, no-request-lost and surviving-
    replica conservation.  Exit 0 iff no violations."""
    import json

    if args.replicas > 1:
        from attention_tpu.chaos.faults import run_frontend_campaign

        report = run_frontend_campaign(
            args.seed, num_plans=args.plans,
            num_requests=args.requests, num_replicas=args.replicas,
            temperature=args.temperature,
            events_per_plan=args.events, log=_logger.info,
        )
    else:
        from attention_tpu.chaos.faults import run_campaign

        report = run_campaign(
            args.seed, num_plans=args.plans,
            num_requests=args.requests,
            temperature=args.temperature,
            events_per_plan=args.events, log=_logger.info,
        )
    out = report.to_dict()
    if not args.outputs:
        for r in out["reports"]:
            r.pop("outputs", None)
    print(json.dumps(out, sort_keys=True))
    return 0 if report.ok else 1


def _changed_files(root: str, base: str) -> list[str]:
    """Repo-root-relative paths touched since ``merge-base HEAD base``
    (committed, staged, unstaged, and untracked).  On ``base``'s own
    branch the merge-base IS HEAD, so only working-tree changes show —
    exactly what a builder mid-PR wants to lint."""
    import subprocess

    def git(*argv: str) -> list[str]:
        out = subprocess.run(["git", "-C", root, *argv],
                             capture_output=True, text=True, check=True)
        return [line for line in out.stdout.splitlines() if line]

    try:
        mb = git("merge-base", "HEAD", base)[0]
        changed = set(git("diff", "--name-only", mb, "--"))
        changed |= set(git("ls-files", "--others", "--exclude-standard"))
    except (OSError, subprocess.SubprocessError, IndexError) as e:
        raise SystemExit(f"--changed needs a git checkout with ref "
                         f"{base!r}: {e}") from e
    return sorted(changed)


def _cmd_analyze(args: argparse.Namespace) -> int:
    """Run the static-analysis passes (attention_tpu.analysis): exit 0
    iff the selected files are clean modulo the committed baseline."""
    import os

    from attention_tpu import analysis
    from attention_tpu.analysis import report as areport

    root = analysis.repo_root()
    if args.list_codes:
        for code in sorted(analysis.CODES.values(),
                           key=lambda c: c.code):
            print(f"{code.code}  {code.severity.value:7s} "
                  f"{code.title}: {code.summary}")
        return 0

    rel_paths = None
    analyzer_changed = False
    if args.changed:
        rel_paths = _changed_files(root, args.base)
        # an edit under analysis/ changes what every pass would say
        # about every file — the call-graph closure below can't model
        # that (passes aren't callees), so escalate to a full run
        if any(p.startswith("attention_tpu/analysis/")
               for p in rel_paths):
            rel_paths = None
            analyzer_changed = True
    if args.paths and not analyzer_changed:
        rel_paths = (rel_paths or []) + [
            os.path.relpath(os.path.abspath(p), root).replace(os.sep, "/")
            for p in args.paths
        ]
    index = None
    if rel_paths is not None and any(
            p.needs_index for p in analysis.PASSES.values()):
        # interprocedural passes see hazards across call edges, so a
        # helper edit must re-lint the files that CALL the helper — the
        # call-graph reverse closure (--changed can't silently pass a
        # hazard introduced one level away)
        from attention_tpu.analysis import core as acore

        index = acore.build_index(root)
        closure = index.files_calling(
            [p for p in rel_paths if p.endswith(".py")])
        if closure:
            rel_paths = sorted(set(rel_paths) | closure)
    timings: dict[str, float] | None = {} if args.timings else None
    findings = analysis.analyze(root, rel_paths=rel_paths,
                                timings=timings, index=index)
    if timings is not None:
        total = sum(timings.values())
        for name, secs in sorted(timings.items(), key=lambda kv: -kv[1]):
            print(f"{secs * 1e3:9.1f} ms  {name}", file=sys.stderr)
        print(f"{total * 1e3:9.1f} ms  total", file=sys.stderr)

    problems: list[str] = []
    if not args.no_baseline:
        bpath = args.baseline or areport.default_baseline_path(root)
        if os.path.isfile(bpath):
            try:
                entries = areport.load_baseline(bpath)
            except ValueError as e:
                print(str(e), file=sys.stderr)
                return 2
            # a partial run can't tell a stale entry from an unscanned
            # file, so only full runs police baseline staleness
            findings, problems = areport.apply_baseline(findings, entries)
            if rel_paths is not None:
                problems = []
        elif args.baseline:
            print(f"no such baseline: {bpath}", file=sys.stderr)
            return 2

    render = {"text": areport.render_text, "json": areport.render_json,
              "sarif": areport.render_sarif,
              "github": areport.render_github}[args.format]
    text = render(findings, problems)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text)
        _logger.info("wrote %s report: %s", args.format, args.out)
    else:
        sys.stdout.write(text)
    return 1 if (findings or problems) else 0


def _obs_load(args: argparse.Namespace):
    """(snapshot, events, device_dir) for an ``obs`` subcommand: from a
    --run dump directory, else the live in-process state (useful when a
    caller invokes cli.main() programmatically after a run)."""
    from attention_tpu import obs

    if args.run:
        snapshot, events = obs.load_dump(args.run)
        device = args.device_trace or obs.device_dir_of(args.run)
    else:
        snapshot, events = obs.REGISTRY.snapshot(), obs.events()
        device = args.device_trace
    return snapshot, events, device


def _cmd_obs_report(args: argparse.Namespace) -> int:
    """Human-oriented run picture: instrument families first (every
    layer that recorded anything, frontend.* through engine.step.*),
    then counters, gauges, histogram/digest and span aggregates, and
    per-module device seconds when a capture exists."""
    snapshot, events, device = _obs_load(args)

    def _lbl(labels):
        return ("{" + ",".join(f"{k}={v}" for k, v in
                               sorted(labels.items())) + "}"
                if labels else "")

    # grouped family view: series counts per layer.component, so the
    # PR 6-11 families (frontend.*, engine.snapshot.*, engine.step.*)
    # and the new digest/SLO series are visible at a glance
    fams: dict[str, dict[str, int]] = {}
    for kind in ("counters", "gauges", "histograms", "digests"):
        for s in snapshot.get(kind, []):
            fam = ".".join(s["name"].split(".")[:2])
            fams.setdefault(fam, {}).setdefault(kind, 0)
            fams[fam][kind] += 1
    print("== families ==")
    for fam in sorted(fams):
        parts = ", ".join(f"{n} {k}" for k, n in
                          sorted(fams[fam].items()))
        print(f"  {fam}: {parts}")
    print("== counters ==")
    for s in snapshot.get("counters", []):
        print(f"  {s['name']}{_lbl(s['labels'])} = {s['value']:g}")
    print("== gauges ==")
    for s in snapshot.get("gauges", []):
        print(f"  {s['name']}{_lbl(s['labels'])} = {s['value']:g}")
    print("== histograms ==")
    for s in snapshot.get("histograms", []):
        mean = s["sum"] / s["count"] if s["count"] else 0.0
        print(f"  {s['name']}{_lbl(s['labels'])}: count={s['count']} "
              f"mean={mean:.3f} sum={s['sum']:.3f}")
    print("== digests ==")
    for s in snapshot.get("digests", []):
        p = s["percentiles"]
        print(f"  {s['name']}{_lbl(s['labels'])}: count={s['count']} "
              f"p50={p['p50']:.3f} p90={p['p90']:.3f} "
              f"p99={p['p99']:.3f} p999={p['p999']:.3f}")
    # forecast + capacity observatory, when the run dumped one
    fdoc = None
    if args.run:
        from attention_tpu import obs as obs_mod

        fdoc = obs_mod.load_forecast(args.run)
    if fdoc is not None:
        from attention_tpu.obs.forecast import PRESSURE_SERIES

        print("== forecast ==")
        cap = fdoc["capacity"]
        print(f"  horizon={fdoc['horizon']} "
              f"ticks={cap['fleet']['ticks']} "
              f"headroom={cap['fleet']['headroom']:g} "
              f"cost_per_token={cap['fleet']['cost_per_token']}")
        for blk in fdoc["series"]:
            st = blk["state"]
            season = (f" season[{len(st['seasonal'])}]"
                      if st["seasonal"] else "")
            print(f"  {blk['name']}: level={st['level']:g} "
                  f"trend={st['trend']:g}{season} "
                  f"mape={blk['backtest']['one_step_mape']:g} "
                  f"coverage={blk['backtest']['coverage']:g}")
            if blk["name"] == PRESSURE_SERIES:
                for row in blk["forecast"]:
                    print(f"    h={row['h']} tick={row['tick']} "
                          f"mean={row['mean']:g} "
                          f"[{row['lo']:g}, {row['hi']:g}]")
        for name, tts in sorted(cap["time_to_saturation"].items()):
            when = (f"tick {tts['tick']} (h={tts['h']}, "
                    f"pressure {tts['pressure']:g})"
                    if tts["tick"] is not None
                    else "beyond horizon")
            print(f"  saturation[{name}] @ {tts['watermark']:g}: {when}")
    # anomaly observatory (obs.anomaly), when the run dumped one
    adoc = None
    if args.run:
        from attention_tpu import obs as obs_mod

        adoc = obs_mod.load_anomaly(args.run)
    if adoc is not None:
        print("== anomalies ==")
        det = adoc["detectors"]
        rb = det["residual_band"]
        print(f"  residual_band: residual={rb['residual']:g} "
              f"band_p90={rb['band_p90']:g} "
              f"ticks={rb['observed_ticks']}")
        for obj, slope in sorted(det["burn_slope"].items()):
            print(f"  burn_slope[{obj}]: slope={slope:g}")
        for rep, score in sorted(det["gray_failure"].items()):
            print(f"  gray_failure[{rep}]: score={score:g}")
        if adoc["firings"]:
            for f in adoc["firings"]:
                print(f"  fired @ tick {f['tick']}: {f['detector']}"
                      f"[{f['key']}] value={f['value']:g} "
                      f"bound={f['bound']:g}")
        else:
            print("  (no firings)")
    print("== spans ==")
    agg: dict[str, list[float]] = {}
    for e in events:
        agg.setdefault(e["name"], []).append(e["dur_us"])
    for name in sorted(agg):
        durs = agg[name]
        print(f"  {name}: n={len(durs)} total_ms="
              f"{sum(durs) / 1e3:.3f} mean_us={sum(durs) / len(durs):.1f}")
    if device:
        from attention_tpu.utils.profiling import device_module_seconds

        mods = device_module_seconds(device)
        print("== device modules ==")
        if mods:
            for name, sec in sorted(mods.items(), key=lambda kv: -kv[1]):
                print(f"  {name}: {sec * 1e3:.3f} ms")
        else:
            print("  (no parsable device lane)")
    return 0


def _cmd_obs_export(args: argparse.Namespace) -> int:
    import json

    from attention_tpu import obs

    snapshot, events, device = _obs_load(args)
    if args.format == "prom":
        text = obs.prom_text(snapshot)
    elif args.format == "jsonl":
        text = "\n".join(obs.jsonl_lines(events, snapshot))
        text += "\n" if text else ""
    else:  # chrome
        text = json.dumps(obs.chrome_trace(events, device_dir=device))
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        _logger.info("wrote %s export: %s", args.format, args.out)
    else:
        sys.stdout.write(text)
    return 0


def _cmd_obs_trace(args: argparse.Namespace) -> int:
    """Per-request journey report (obs.trace): ``--request ID`` prints
    one chain event by event; without it, one summary line per chain.
    Reads ``<run>/traces.jsonl`` from a dump, else the live store."""
    from attention_tpu import obs
    from attention_tpu.obs import trace as trace_mod

    chains = (obs.load_traces(args.run) if args.run
              else trace_mod.all_traces())
    if args.request is not None:
        evs = chains.get(args.request)
        if not evs:
            print(f"no trace recorded for request {args.request!r}",
                  file=sys.stderr)
            return 1
        for line in trace_mod.journey_lines(args.request, evs):
            print(line)
        return 0
    for rid in sorted(chains):
        evs = chains[rid]
        term = trace_mod.terminal_of(evs)
        print(f"{rid}: {len(evs)} events, "
              f"terminal={term or 'none (in flight)'}")
    return 0


def _cmd_obs_slo(args: argparse.Namespace) -> int:
    """Print a run's SLO report (obs.slo) in its canonical JSON form —
    byte-identical across same-seed runs, which is the property the
    acceptance test pins."""
    import json

    from attention_tpu import obs

    if not args.run:
        print("obs slo requires --run "
              "(a `serve-sim --obs-out` directory)", file=sys.stderr)
        return 1
    report = obs.load_slo(args.run)
    if report is None:
        print(f"no slo.json under {args.run} (was serve-sim run "
              "with --replicas and --obs-out?)", file=sys.stderr)
        return 1
    print(json.dumps(report, indent=1, sort_keys=True))
    return 0


def _cmd_obs_forecast(args: argparse.Namespace) -> int:
    """Print a run's forecast + capacity report (obs.forecast /
    obs.capacity) in its canonical JSON form.  Without ``--horizon``
    this is byte-identical to the committed forecast.json (same-seed
    determinism, the pinned property); with it, the report is rebuilt
    from the dump's embedded samples at the requested horizon."""
    import json

    from attention_tpu import obs
    from attention_tpu.obs import capacity as capacity_mod

    if not args.run:
        print("obs forecast requires --run "
              "(a `serve-sim --obs-out` directory)", file=sys.stderr)
        return 1
    doc = obs.load_forecast(args.run)
    if doc is None:
        print(f"no forecast.json under {args.run} (was serve-sim run "
              "with --replicas and --forecast and --obs-out?)",
              file=sys.stderr)
        return 1
    if args.horizon is not None:
        doc = capacity_mod.rebuild_report(doc, horizon=args.horizon)
    print(json.dumps(doc, indent=1, sort_keys=True))
    return 0


def _cmd_obs_postmortem(args: argparse.Namespace) -> int:
    """Reconstruct every incident bundle under ``--run`` into a
    cross-replica causal timeline: alarm, correlated trigger events,
    then the ring slice in coordinate order.  Byte-deterministic from
    the bundles alone — same-seed runs print identical reports.  With
    ``--chrome OUT`` also writes a chrome trace whose incident lane
    (pid 4) sits beside the request lanes."""
    import json

    from attention_tpu.obs import postmortem as pm_mod

    if not args.run:
        print("obs postmortem requires --run (an incident directory "
              "written via --incident-dir or a chaos campaign)",
              file=sys.stderr)
        return 1
    bundles = pm_mod.list_incidents(args.run)
    if not bundles:
        print(f"no incident bundles under {args.run}", file=sys.stderr)
        return 1
    print("\n".join(pm_mod.report_lines(args.run)))
    if args.chrome:
        from attention_tpu import obs

        loaded = [pm_mod.load_incident(b) for b in bundles]
        with open(args.chrome, "w") as f:
            json.dump(obs.chrome_trace([], incidents=loaded), f)
        _logger.info("wrote incident chrome trace: %s", args.chrome)
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="attention-tpu", description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser("run", help="run a testcase and verify (reference main())")
    run.add_argument("testcase")
    run.add_argument("--backend", default="flash")
    run.add_argument("--dtype", choices=["bf16", "f32", "f64"], default="f32")
    run.add_argument("--repeats", type=int, default=1,
                     help="min-over-repeats timing (reference methodology)")
    run.add_argument("--no-verify", action="store_true")
    run.add_argument("--stats", action="store_true",
                     help="append a full-scan statistics line "
                          "(max-abs-error, mismatch count) after the "
                          "frozen verdict lines")
    run.set_defaults(fn=_cmd_run)

    gen = sub.add_parser("generate", help="write a random testcase + oracle output")
    gen.add_argument("out")
    gen.add_argument("--m", type=int, required=True)
    gen.add_argument("--n", type=int, required=True)
    gen.add_argument("--dk", type=int, required=True)
    gen.add_argument("--dv", type=int, required=True)
    gen.add_argument("--seed", type=int, default=0)
    gen.set_defaults(fn=_cmd_generate)

    suite = sub.add_parser("suite", help="write the simple..scale5 ladder")
    suite.add_argument("out_dir")
    suite.add_argument("--seed", type=int, default=0)
    suite.set_defaults(fn=_cmd_suite)

    be = sub.add_parser("backends", help="list available backends")
    be.set_defaults(fn=_cmd_backends)

    ss = sub.add_parser(
        "serve-sim",
        help="continuous-batching engine on a synthetic or JSON request "
             "trace (attention_tpu.engine); prints metrics JSON",
    )
    _add_serve_sim_args(ss)
    ss.set_defaults(fn=_cmd_serve_sim)

    tn = sub.add_parser(
        "tune",
        help="timed on-device kernel tile search; winners persist in "
             "the per-device tuning cache (see attention_tpu.tuning)",
    )
    tn.add_argument("--kernel", default="flash",
                    choices=["flash", "flash-bwd", "flash-bwd-fused",
                             "decode", "paged", "all"])
    tn.add_argument("--seq", type=int, default=32768,
                    help="sequence length (cache capacity for "
                         "decode/paged)")
    tn.add_argument("--dim", type=int, default=128)
    tn.add_argument("--heads", type=int, default=1)
    tn.add_argument("--kv-heads", type=int, default=None,
                    help="GQA KV heads (default: = --heads)")
    tn.add_argument("--batch", type=int, default=8,
                    help="batch size (decode/paged families)")
    tn.add_argument("--dtype", default="bfloat16")
    tn.add_argument("--causal", action="store_true")
    tn.add_argument("--stats", action="store_true",
                    help="tune the partials (stats-emitting) forward")
    tn.add_argument("--window", type=int, default=None)
    tn.add_argument("--sinks", type=int, default=None)
    tn.add_argument("--max-mode", default="bound",
                    choices=["online", "bound", "flashd", "amla", "auto"],
                    help="rescaling-math variant to measure; 'auto' "
                         "races every variant the family can lower and "
                         "records the winner in the cache entry")
    tn.add_argument("--repeats", type=int, default=3,
                    help="median-of-k timing repeats per candidate")
    tn.add_argument("--cache", default=None,
                    help="cache file to write (default: "
                         "~/.cache/attention_tpu/tuning_cache.json)")
    tn.add_argument("--dry-run", action="store_true",
                    help="search and report but write nothing")
    tn.set_defaults(fn=_cmd_tune)

    ch = sub.add_parser(
        "chaos",
        help="differential fuzzing + fault injection "
             "(attention_tpu.chaos): fuzz kernel configs against the "
             "fp64 oracle, shrink failures to .bin repros, storm the "
             "serving engine with seeded fault plans",
    )
    chsub = ch.add_subparsers(dest="chaos_cmd", required=True)

    cf = chsub.add_parser("fuzz", help="seeded differential fuzz "
                                       "campaign vs the tolerance ledger")
    cf.add_argument("--seed", type=int, default=0)
    cf.add_argument("--cases", type=int, default=16)
    cf.add_argument("--families", default=None,
                    help="comma-separated subset of "
                         "flash,decode,paged,int8,int4 (default: all)")
    cf.add_argument("--max-mode", default="online",
                    choices=["online", "bound", "flashd", "amla"],
                    help="pin the rescaling-math variant for families "
                         "that can lower it (per-variant oracle "
                         "campaigns; others keep online)")
    cf.add_argument("--inject-failure", action="store_true",
                    help="apply the synthetic defect to every kernel "
                         "output (pipeline self-test: forces failures)")
    cf.add_argument("--repro-dir", default=None,
                    help="write each failing config here as "
                         "repro-<i>.json")
    cf.set_defaults(fn=_cmd_chaos_fuzz)

    cr = chsub.add_parser("replay", help="re-run one repro "
                                         "(.json fuzz config or .bin "
                                         "testcase)")
    cr.add_argument("repro")
    cr.add_argument("--backend", default="flash",
                    help=".bin replay backend (any `cli backends` "
                         "name, e.g. chaos-broken)")
    cr.add_argument("--inject-failure", action="store_true")
    cr.set_defaults(fn=_cmd_chaos_replay)

    cs = chsub.add_parser("shrink", help="minimize a failing fuzz "
                                         "config; emit .json/.bin repro")
    cs.add_argument("repro", help="failing-config repro.json")
    cs.add_argument("--out", default=None,
                    help="write the minimal config JSON here")
    cs.add_argument("--bin", default=None,
                    help="write a .bin testcase here when the minimal "
                         "config is plain single-head attention")
    cs.add_argument("--inject-failure", action="store_true")
    cs.set_defaults(fn=_cmd_chaos_shrink)

    cfa = chsub.add_parser("faults", help="seeded fault-injection "
                                          "campaign against the "
                                          "serving engine")
    cfa.add_argument("--seed", type=int, default=0)
    cfa.add_argument("--plans", type=int, default=5)
    cfa.add_argument("--requests", type=int, default=5)
    cfa.add_argument("--events", type=int, default=4)
    cfa.add_argument("--replicas", type=int, default=1,
                     help="storm a --replicas N multi-replica front "
                          "end instead of a single engine (adds "
                          "replica_kill/restart fault kinds and the "
                          "no-request-lost invariant)")
    cfa.add_argument("--temperature", type=float, default=0.0)
    cfa.add_argument("--outputs", action="store_true",
                     help="include per-request token streams in the "
                          "report JSON")
    cfa.set_defaults(fn=_cmd_chaos_faults)

    sn = sub.add_parser(
        "snapshot",
        help="crash-consistency tooling (attention_tpu.engine."
             "snapshot): inspect / verify serve-sim snapshot files",
    )
    snsub = sn.add_subparsers(dest="snapshot_cmd", required=True)
    si = snsub.add_parser("inspect", help="print manifest + metadata "
                                          "JSON per snapshot")
    si.add_argument("path", help=".atpsnap file or a --snapshot-dir")
    si.set_defaults(fn=_cmd_snapshot_inspect)
    sv = snsub.add_parser("verify", help="check integrity (checksums, "
                                         "version, section table); "
                                         "exit 0 iff restorable")
    sv.add_argument("path", help=".atpsnap file or a --snapshot-dir")
    sv.set_defaults(fn=_cmd_snapshot_verify)

    an = sub.add_parser(
        "analyze",
        help="static analysis (attention_tpu.analysis): AST passes "
             "with stable ATP### codes over the whole tree; exit 0 "
             "iff clean modulo analysis/baseline.json",
    )
    an.add_argument("paths", nargs="*",
                    help="specific files to lint (default: the whole "
                         "scanned tree)")
    an.add_argument("--changed", action="store_true",
                    help="lint only files touched since "
                         "`git merge-base HEAD --base` (plus "
                         "staged/unstaged/untracked changes, plus the "
                         "call-graph reverse closure: files whose "
                         "callers changed); an edit under "
                         "attention_tpu/analysis/ escalates to a "
                         "full tree run")
    an.add_argument("--timings", action="store_true",
                    help="print per-pass wall time to stderr (the "
                         "tree-wide budget is <= 5 s)")
    an.add_argument("--base", default="main",
                    help="merge-base ref for --changed (default: main)")
    an.add_argument("--format",
                    choices=["text", "json", "sarif", "github"],
                    default="text",
                    help="report renderer; 'github' emits workflow-"
                         "command annotations (::error file=...)")
    an.add_argument("--baseline", default=None,
                    help="baseline file (default: "
                         "attention_tpu/analysis/baseline.json)")
    an.add_argument("--no-baseline", action="store_true",
                    help="report every finding, accepted or not")
    an.add_argument("--out", default=None,
                    help="write the report here instead of stdout")
    an.add_argument("--list-codes", action="store_true",
                    help="print the ATP### rule table and exit")
    an.set_defaults(fn=_cmd_analyze)

    ob = sub.add_parser(
        "obs",
        help="unified telemetry (attention_tpu.obs): report / export a "
             "run's counters, spans, and merged host/device timeline",
    )
    obsub = ob.add_subparsers(dest="obs_cmd", required=True)
    for name, fn in (("report", _cmd_obs_report),
                     ("export", _cmd_obs_export),
                     ("trace", _cmd_obs_trace),
                     ("slo", _cmd_obs_slo),
                     ("forecast", _cmd_obs_forecast),
                     ("postmortem", _cmd_obs_postmortem)):
        sp = obsub.add_parser(name)
        sp.add_argument("--run", default=None,
                        help="telemetry dump directory written by "
                             "`serve-sim --obs-out` (default: the live "
                             "in-process registry); for postmortem, "
                             "the incident directory")
        sp.add_argument("--device-trace", default=None,
                        help="jax.profiler trace dir for the device "
                             "lane (default: <run>/device if present)")
        if name == "export":
            sp.add_argument("--format",
                            choices=["chrome", "prom", "jsonl"],
                            default="chrome")
            sp.add_argument("--out", default=None,
                            help="write here instead of stdout")
        if name == "trace":
            sp.add_argument("--request", default=None,
                            help="print the full journey of one "
                                 "request id (default: list every "
                                 "chain, one line each)")
        if name == "forecast":
            sp.add_argument("--horizon", type=int, default=None,
                            help="rebuild the report from the dump's "
                                 "embedded samples at this horizon "
                                 "(default: print the dump verbatim)")
        if name == "postmortem":
            sp.add_argument("--chrome", default=None,
                            help="also write a chrome trace with the "
                                 "incident lane (pid 4) here")
        sp.set_defaults(fn=fn)

    _setup_logging()
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
