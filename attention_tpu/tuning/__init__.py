"""Measured kernel autotuning with a persistent per-device cache.

The reference project's performance story is an empirical sweep: it
measured batch sizes, placement, and tile shapes on its target cluster
and baked the winners in (README benchmark tables, report Q1-Q8).  This
package turns that one-time sweep into a subsystem:

- ``space``  — the tunable-parameter space per kernel family (flash
  forward, flash backward two-kernel + fused, decode, paged).
- ``cache``  — the persistent JSON result table: a user cache under
  ``~/.cache/attention_tpu/`` plus an in-repo shipped table seeded from
  the measured heuristics, both keyed by (device kind, kernel, shape
  bucket, dtype, flags).
- ``lookup`` — the read path the kernels consult: user cache first,
  shipped table second, ``None`` third (the caller's heuristic remains
  the final fallback, so CPU/interpret runs with no cache are
  byte-for-byte unaffected).
- ``search`` — the timed on-device search (compile-failure tolerant:
  VMEM-overflow candidates are skipped, not fatal), run by
  ``python -m attention_tpu.cli tune`` and ``bench.py --autotune``.

Kernel integration stays thin: `BlockSizes.for_shape`
(`ops/flash.py`), `default_bwd_block_sizes` /
`default_fused_bwd_block_sizes` (`ops/flash_bwd.py`), the decode
``block_k`` default (`ops/decode.py`), and
`recommended_page_size` (`ops/paged.py`) each try `lookup` and fall
back to their existing measured heuristics.
"""

from attention_tpu.tuning.cache import (  # noqa: F401
    TuningTable,
    bucket_pow2,
    default_cache_path,
    device_key,
    make_key,
    parse_key,
    shipped_table_path,
)

# NOTE: the lookup FUNCTION deliberately stays under
# attention_tpu.tuning.lookup.lookup — re-exporting it here would
# shadow the submodule attribute of the same name (a classic
# package-namespace collision that breaks `import
# attention_tpu.tuning.lookup as m` and monkeypatching).
