"""Persistent tuning tables: key schema, JSON I/O, device identity.

Two tables share one schema:

- the **user cache** (``~/.cache/attention_tpu/tuning_cache.json``,
  overridable via ``ATTN_TPU_TUNING_CACHE``): written by
  ``cli tune`` / ``bench.py --autotune`` runs on the machine at hand;
- the **shipped table** (``attention_tpu/tuning/shipped_table.json``,
  committed): seeded from the measured heuristics by
  ``scripts/make_shipped_table.py`` so a fresh host starts from the
  swept defaults instead of nothing.

Schema (version 1)::

    {"version": 1,
     "entries": {"<key>": {"block_q": 4096, "block_k": 2048,
                           "ms": 2.87, "source": "measured",
                           "recorded": "2026-08-04"}, ...}}

Keys are 5 pipe-separated fields::

    <device>|<kernel>|g<G>-m<M>-n<N>-d<D>|<dtype>|<flags>

- ``device``: normalized device kind (``tpu-v5e``, ``cpu``, ...);
- ``kernel``: one of :data:`KERNELS`;
- shape bucket: ``G`` = heads bucket (GQA group for decode), ``M``/``N``
  = floor-power-of-two sequence buckets (``M`` = batch bucket for
  decode/paged), ``D`` = exact head dim — floor bucketing means an
  entry measured at 32k serves every m in [32768, 65535], and the
  kernel adapters re-clamp tiles to the call's real padding;
- ``dtype``: canonical dtype name, or ``any``;
- ``flags``: comma-joined sorted ``k=v`` pairs, ``-`` when empty
  (window flags carry the window's own pow2 bucket).

Entry values carry any of ``block_q``/``block_k``/``page_size`` (all
must be positive multiples of 128 — ``validate_entry`` and the
``scripts/check_shipped_table.py`` lint enforce it), optionally a
``max_mode`` rescaling-math variant (one of :data:`MAX_MODE_VALUES`;
the forward/decode/ragged kernels' ``max_mode="auto"`` dispatch reads
it), plus provenance fields the kernels ignore.
"""

from __future__ import annotations

import json
import os
import re
import tempfile

SCHEMA_VERSION = 1

KERNELS = ("flash_fwd", "flash_bwd", "flash_bwd_fused", "decode", "paged",
           "ragged")

_TILE_FIELDS = ("block_q", "block_k", "page_size")

#: legal values for an entry's optional ``max_mode`` field — the
#: rescaling-math variants ops.flash/decode/ragged_paged can lower
#: (ops.flash.MAX_MODES; spelled out here so a corrupt cache cannot
#: import ops at validation time)
MAX_MODE_VALUES = ("online", "bound", "flashd", "amla")

_BUCKET_RE = re.compile(r"^g(\d+)-m(\d+)-n(\d+)-d(\d+)$")
_FLAG_RE = re.compile(r"^[a-z_]+=\d+$")


def bucket_pow2(x: int) -> int:
    """Floor power-of-two bucket (4864 -> 4096; exact powers map to
    themselves, so tuned shapes hit their own bucket)."""
    if x < 1:
        raise ValueError(f"bucket_pow2 needs x >= 1, got {x}")
    return 1 << (int(x).bit_length() - 1)


def make_key(device: str, kernel: str, *, g: int, m: int, n: int, d: int,
             dtype: str = "any", flags: dict | None = None) -> str:
    """Cache key for a concrete call shape (buckets applied here, so
    callers pass real shapes)."""
    if kernel not in KERNELS:
        raise ValueError(f"unknown kernel family {kernel!r}")
    bucket = (f"g{bucket_pow2(g)}-m{bucket_pow2(m)}"
              f"-n{bucket_pow2(n)}-d{d}")
    items = sorted((flags or {}).items())
    flag_s = ",".join(f"{k}={int(v)}" for k, v in items) or "-"
    return f"{device}|{kernel}|{bucket}|{dtype}|{flag_s}"


def parse_key(key: str) -> dict:
    """Split a key back into fields; raises ValueError on malformed keys
    (the shipped-table lint runs every committed key through this)."""
    parts = key.split("|")
    if len(parts) != 5:
        raise ValueError(f"key must have 5 '|' fields: {key!r}")
    device, kernel, bucket, dtype, flag_s = parts
    if not device:
        raise ValueError(f"empty device field: {key!r}")
    if kernel not in KERNELS:
        raise ValueError(f"unknown kernel family {kernel!r} in {key!r}")
    mb = _BUCKET_RE.match(bucket)
    if not mb:
        raise ValueError(f"malformed shape bucket {bucket!r} in {key!r}")
    g, m, n, d = (int(x) for x in mb.groups())
    for dim, name in ((g, "g"), (m, "m"), (n, "n")):
        if dim != bucket_pow2(dim):
            raise ValueError(
                f"bucket field {name}={dim} is not a power of two: {key!r}"
            )
    flags = {}
    if flag_s != "-":
        for pair in flag_s.split(","):
            if not _FLAG_RE.match(pair):
                raise ValueError(f"malformed flag {pair!r} in {key!r}")
            fk, fv = pair.split("=")
            if fk in flags:
                raise ValueError(f"duplicate flag {fk!r} in {key!r}")
            flags[fk] = int(fv)
    if list(flags) != sorted(flags):
        raise ValueError(f"flags not sorted in {key!r}")
    return {"device": device, "kernel": kernel, "g": g, "m": m, "n": n,
            "d": d, "dtype": dtype, "flags": flags}


def validate_entry(entry: dict) -> None:
    """Raise ValueError unless the entry carries at least one tile field,
    every tile field is a positive multiple of 128, and ``max_mode``
    (when present) names a known rescaling-math variant."""
    if not isinstance(entry, dict):
        raise ValueError(f"entry must be a dict, got {type(entry).__name__}")
    tiles = [f for f in _TILE_FIELDS if f in entry]
    if not tiles:
        raise ValueError(f"entry has no tile field {_TILE_FIELDS}: {entry}")
    for f in tiles:
        v = entry[f]
        if not isinstance(v, int) or v <= 0 or v % 128:
            raise ValueError(
                f"{f}={v!r} must be a positive multiple of 128"
            )
    if "max_mode" in entry and entry["max_mode"] not in MAX_MODE_VALUES:
        raise ValueError(
            f"max_mode={entry['max_mode']!r} must be one of "
            f"{MAX_MODE_VALUES}"
        )


def default_cache_path() -> str:
    """User cache location: ``ATTN_TPU_TUNING_CACHE`` env override, else
    ``$XDG_CACHE_HOME/attention_tpu/tuning_cache.json`` (XDG default
    ``~/.cache``)."""
    env = os.environ.get("ATTN_TPU_TUNING_CACHE")
    if env:
        return env
    base = os.environ.get("XDG_CACHE_HOME") or os.path.expanduser("~/.cache")
    return os.path.join(base, "attention_tpu", "tuning_cache.json")


def shipped_table_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "shipped_table.json")


def device_key() -> str:
    """Normalized identity of the default device, the key's first field.

    TPU kinds normalize to ``tpu-v<gen><variant>`` (``TPU v5 lite`` and
    ``TPU v5e`` both -> ``tpu-v5e``) so shipped entries survive PJRT
    spelling drift; non-TPU backends use the platform name, which is
    what keeps CPU/interpret lookups off the TPU-measured shipped
    entries (they miss and fall to the heuristics).
    """
    try:
        import jax

        dev = jax.devices()[0]
    except Exception:  # noqa: BLE001 - no backend at all
        return "unknown"
    if dev.platform != "tpu":
        return str(dev.platform).lower()
    return normalize_device_kind(getattr(dev, "device_kind", "tpu"))


def normalize_device_kind(kind: str) -> str:
    k = (kind or "tpu").lower()
    mg = re.search(r"v(\d+)\s*(p|e|x|lite)?", k)
    if not mg:
        # newer spellings drop the 'v' ("TPU7x")
        mg = re.search(r"tpu\s*(\d+)\s*(p|e|x|lite)?", k)
    if not mg:
        return "tpu-" + re.sub(r"\s+", "-", k.strip())
    variant = mg.group(2) or ""
    if variant == "lite":
        variant = "e"
    return f"tpu-v{mg.group(1)}{variant}"


class TuningTable:
    """One schema-versioned key->entry table with atomic JSON persistence."""

    def __init__(self, entries: dict | None = None, path: str | None = None):
        self.entries: dict = dict(entries or {})
        self.path = path

    @classmethod
    def load(cls, path: str) -> "TuningTable":
        """Load ``path``; missing/corrupt/version-mismatched files load
        as empty (a bad cache must never break kernel dispatch)."""
        try:
            with open(path) as f:
                data = json.load(f)
            if (isinstance(data, dict)
                    and data.get("version") == SCHEMA_VERSION
                    and isinstance(data.get("entries"), dict)):
                return cls(data["entries"], path=path)
        except (OSError, ValueError):
            pass
        return cls({}, path=path)

    def get(self, key: str) -> dict | None:
        e = self.entries.get(key)
        return dict(e) if isinstance(e, dict) else None

    def put(self, key: str, entry: dict) -> None:
        parse_key(key)
        validate_entry(entry)
        self.entries[key] = dict(entry)

    def save(self, path: str | None = None) -> str:
        """Atomic write (tmp + replace): a concurrent reader never sees a
        torn table."""
        path = path or self.path
        if not path:
            raise ValueError("no path to save to")
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        data = {"version": SCHEMA_VERSION,
                "entries": dict(sorted(self.entries.items()))}
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(os.path.abspath(path)), suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(data, f, indent=1, sort_keys=True)
                f.write("\n")
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self.path = path
        return path


# (path, mtime_ns, size) -> TuningTable: lookups happen at jit-trace
# time, so repeated loads must cost one os.stat, not one json parse —
# while a post-``tune`` write (new mtime) still invalidates in-process.
_TABLE_MEMO: dict = {}


def load_table_cached(path: str) -> TuningTable:
    try:
        st = os.stat(path)
        stamp = (path, st.st_mtime_ns, st.st_size)
    except OSError:
        return TuningTable({}, path=path)
    hit = _TABLE_MEMO.get(path)
    if hit is not None and hit[0] == stamp:
        return hit[1]
    table = TuningTable.load(path)
    _TABLE_MEMO[path] = (stamp, table)
    return table
