"""The read path kernels consult for tuned parameters.

Resolution order is fixed: **user cache -> shipped table -> None**.
``None`` sends the caller to its own measured heuristic, which is what
keeps an empty-cache CPU run byte-for-byte identical to the
pre-autotuner library (the shipped table only carries ``tpu-*`` device
keys, and CPU lookups key as ``cpu``).

``ATTN_TPU_NO_TUNING=1`` disables both tables (heuristics only) — the
triage switch for suspect cache entries.

This module deliberately imports nothing from ``attention_tpu.ops`` so
the ops modules can import it without a cycle; it returns plain dict
entries and lets each kernel adapt them (clamping to the call's real
padding stays the kernel's business).
"""

from __future__ import annotations

import os

from attention_tpu.tuning.cache import (
    bucket_pow2,
    default_cache_path,
    device_key,
    load_table_cached,
    make_key,
    shipped_table_path,
    validate_entry,
)


def window_bucket(window: int | None) -> int:
    """Windows bucket like sequence dims (pow2 floor), 0 = unwindowed."""
    return 0 if window is None else bucket_pow2(window)


def dtype_name(dtype) -> str:
    if dtype is None:
        return "any"
    import numpy as np

    return np.dtype(dtype).name


def key_fields(kernel: str, *, heads=1, kv_heads=None, seq=0, dim=0,
               batch=1, causal=False, window=None, sinks=None,
               stats=False) -> dict:
    """The (g, m, n, d, flags) key fields for one family — the SINGLE
    definition shared by the tuner's write side (`search.tune`) and the
    kernels' read side, so the two can never drift.

    Field mapping per family: flash forward keys on (heads bucket,
    m=n=seq, d, causal/stats/window-bucket); the backward families are
    head- and causal-generic (measured: the defaults hold across h and
    the causal band, RESULTS.md r2/r4) and key on (m=n=seq, d,
    window-bucket); decode/paged/ragged key on (GQA group, m=batch
    (ragged: active slots), n=cache capacity, d, sinks/window-bucket).
    """
    wb = window_bucket(window)
    if kernel == "flash_fwd":
        return dict(g=heads, m=seq, n=seq, d=dim,
                    flags={"causal": int(bool(causal)),
                           "stats": int(bool(stats)), "window": wb})
    if kernel in ("flash_bwd", "flash_bwd_fused"):
        return dict(g=1, m=seq, n=seq, d=dim, flags={"window": wb})
    if kernel in ("decode", "paged", "ragged"):
        group = heads // (kv_heads or heads)
        return dict(g=group, m=batch, n=seq, d=dim,
                    flags={"sinks": int(bool(sinks)), "window": wb})
    raise ValueError(f"unknown kernel family {kernel!r}")


def lookup(kernel: str, *, g: int, m: int, n: int, d: int,
           dtype=None, flags: dict | None = None,
           cache_path: str | None = None) -> dict | None:
    """Tuned entry for a call shape, or None (caller falls back).

    Tries the exact dtype key first, then the ``any``-dtype key, in the
    user cache, then the shipped table.  Never raises: tuning is an
    accelerant, not a dependency — any I/O or schema problem reads as a
    miss.
    """
    if os.environ.get("ATTN_TPU_NO_TUNING"):
        return None
    try:
        dev = device_key()
        names = [dtype_name(dtype)]
        if names[0] != "any":
            names.append("any")
        keys = [
            make_key(dev, kernel, g=g, m=m, n=n, d=d, dtype=nm, flags=flags)
            for nm in names
        ]
        for path in (cache_path or default_cache_path(),
                     shipped_table_path()):
            table = load_table_cached(path)
            for key in keys:
                entry = table.get(key)
                if entry is not None:
                    validate_entry(entry)
                    return entry
    except Exception:  # noqa: BLE001 - a broken table must read as a miss
        return None
    return None
