"""Timed on-device tile search for the four kernel families.

The measurement contract mirrors bench.py: same input recipe (seeded
normal bf16 tensors), same chained-scan clock
(`utils.timing.benchmark_candidate` — honest under the axon tunnel,
median-of-k), shorter chains because a sweep times many candidates.
Candidates that fail to COMPILE (scoped-VMEM overflow on oversized
tiles) are recorded and skipped, not fatal — the space deliberately
overshoots every chip's budget so a roomier future generation can move
the optimum without a code change.

``timer`` is injectable (``timer(step, x, operands, repeats) ->
seconds``) so the search loop itself is unit-testable on CPU without
timing real kernels.
"""

from __future__ import annotations

import time

from attention_tpu import obs
from attention_tpu.tuning import space
from attention_tpu.tuning.cache import (
    default_cache_path,
    device_key,
    load_table_cached,
    make_key,
)
from attention_tpu.tuning.lookup import dtype_name, key_fields

# Tuning-search progress telemetry (attention_tpu.obs, off by
# default): candidates tried / skipped (compile failures et al.) per
# kernel family, plus one tick per completed search.
_CANDIDATES = obs.counter("tuning.search.candidates",
                          "candidates timed, by kernel family")
_SKIPPED = obs.counter("tuning.search.skipped",
                       "candidates skipped, by kernel family and error")
_SEARCHES = obs.counter("tuning.search.completed",
                        "tune() calls that produced a winner")

#: CLI spelling -> internal kernel family name.
CLI_KERNELS = {
    "flash": "flash_fwd",
    "flash-bwd": "flash_bwd",
    "flash-bwd-fused": "flash_bwd_fused",
    "decode": "decode",
    "paged": "paged",
}


def _default_timer(step, x, operands, repeats):
    from attention_tpu.utils.timing import benchmark_candidate

    return benchmark_candidate(step, x, operands=operands, repeats=repeats)


def _measure_factory(kernel: str, cand, *, heads, kv_heads, seq, dim,
                     batch, dtype, causal, window, sinks, stats,
                     max_mode, interpret):
    """(step, x, operands) for timing one candidate of one family."""
    import jax
    import jax.numpy as jnp

    jdt = jnp.dtype(dtype)
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    hkv = kv_heads or heads

    if kernel in ("flash_fwd", "flash_bwd", "flash_bwd_fused"):
        from attention_tpu.ops.flash import BlockSizes

        bs = BlockSizes(*cand)
        q = jax.random.normal(kq, (heads, seq, dim), jdt)
        k = jax.random.normal(kk, (hkv, seq, dim), jdt)
        v = jax.random.normal(kv, (hkv, seq, dim), jdt)
        if kernel == "flash_fwd":
            if stats:
                from attention_tpu.ops.flash import flash_attention_partials

                def step(x, kk_, vv_):
                    o, _, _ = flash_attention_partials(
                        x, kk_, vv_, block_sizes=bs, causal=causal,
                        window=window, sinks=sinks, max_mode=max_mode,
                        interpret=interpret)
                    return o
            else:
                from attention_tpu.ops.flash import flash_attention

                def step(x, kk_, vv_):
                    return flash_attention(
                        x, kk_, vv_, block_sizes=bs, causal=causal,
                        window=window, sinks=sinks, max_mode=max_mode,
                        interpret=interpret)
            return step, q, (k, v)

        # backward families: a full value_and_grad step with every
        # gradient folded into the timed value (bench.py's grad_step
        # discipline — returning only dQ lets XLA dead-code the dK/dV
        # kernel) and a distribution-stationary carry.
        from attention_tpu.ops.flash_vjp import flash_attention_diff

        def grad_step(x, kk_, vv_):
            def loss(args):
                # (no interpret kwarg: flash_attention_diff resolves
                # interpret mode from the backend itself)
                o = flash_attention_diff(
                    *args, block_sizes=bs, causal=causal, window=window,
                    sinks=sinks, max_mode=max_mode)
                return jnp.sum(o.astype(jnp.float32))

            _, grads = jax.value_and_grad(loss)((x, kk_, vv_))
            combined = (grads[0].astype(jnp.float32)
                        + jnp.sum(grads[1]).astype(jnp.float32)
                        + jnp.sum(grads[2]).astype(jnp.float32))
            return (x.astype(jnp.float32) + 1e-12 * combined).astype(jdt)

        return grad_step, q, (k, v)

    if kernel == "decode":
        from attention_tpu.ops.decode import flash_decode

        q = jax.random.normal(kq, (batch, heads, dim), jdt)
        kc = jax.random.normal(kk, (batch, hkv, seq, dim), jdt)
        vc = jax.random.normal(kv, (batch, hkv, seq, dim), jdt)
        lens = jnp.full((batch,), seq, jnp.int32)

        def dstep(x, kcc, vcc, ll):
            return flash_decode(x, kcc, vcc, ll, block_k=cand,
                                window=window, sinks=sinks,
                                max_mode=max_mode,
                                interpret=interpret)

        return dstep, q, (kc, vc, lens)

    if kernel == "paged":
        import random as _random

        from attention_tpu.ops.paged import (
            PagePool,
            paged_flash_decode,
            paged_from_dense,
        )

        q = jax.random.normal(kq, (batch, heads, dim), jdt)
        kc = jax.random.normal(kk, (batch, hkv, seq, dim), jdt)
        vc = jax.random.normal(kv, (batch, hkv, seq, dim), jdt)
        num_pages = batch * (seq // cand)
        pool = PagePool(num_pages)
        # scrambled physical pages, bench.py's fragmentation recipe
        ids = pool.alloc(num_pages)
        _random.Random(0).shuffle(ids)
        pool.free(ids)
        cache = paged_from_dense(
            kc, vc, jnp.full((batch,), seq, jnp.int32), pool,
            num_pages=num_pages, page_size=cand)

        def pstep(x, c):
            return paged_flash_decode(x, c, window=window, sinks=sinks,
                                      interpret=interpret).astype(x.dtype)

        return pstep, q, (cache,)

    raise ValueError(f"unknown kernel family {kernel!r}")


def tune(kernel: str, *, seq: int, dim: int, heads: int = 1,
         kv_heads: int | None = None, batch: int = 8,
         dtype="bfloat16", causal: bool = False,
         window: int | None = None, sinks: int | None = None,
         stats: bool = False, max_mode: str = "bound",
         repeats: int = 3, timer=None, cache_path: str | None = None,
         write: bool = True, interpret: bool | None = None,
         log=None) -> dict:
    """Search one kernel family's space at one shape; persist the winner.

    ``max_mode="auto"`` widens the race to the cross product of tiles
    and the family's rescaling-math variants
    (:func:`space.max_mode_candidates`) and records the winning variant
    in the entry's ``max_mode`` field — the value the kernels'
    ``max_mode="auto"`` dispatch later reads back.  An explicit
    ``max_mode`` pins the variant (and is recorded likewise for
    mode-capable families); the default ``"bound"`` measures each
    family's historical forward default (decode/ragged cannot lower
    bound and fall to ``"online"``).

    Returns a record: per-candidate ``ms`` (or ``error`` for candidates
    that failed to compile/run), the winning entry, the cache key it was
    stored under, and whether it was written.  Raises RuntimeError only
    when EVERY candidate fails.
    """
    if kernel not in CLI_KERNELS.values():
        raise ValueError(f"unknown kernel family {kernel!r}; "
                         f"one of {sorted(CLI_KERNELS.values())}")
    timer = timer or _default_timer
    fields = key_fields(kernel, heads=heads, kv_heads=kv_heads, seq=seq,
                        dim=dim, batch=batch, causal=causal,
                        window=window, sinks=sinks, stats=stats)
    cands = space.candidates(kernel, m=seq, n=seq, d=dim, window=window)
    if not cands:
        raise RuntimeError(
            f"no shape-legal candidates for {kernel} at seq={seq}")
    mode_cands = space.max_mode_candidates(kernel)
    if max_mode == "auto":
        # joint (tile, mode) race; families without a mode field keep
        # the forward's historical default
        mode_list = list(mode_cands) or ["bound"]
    else:
        mode_list = [max_mode]
        if mode_cands and max_mode not in mode_cands:
            if max_mode != "bound":
                raise ValueError(
                    f"{kernel} cannot lower max_mode {max_mode!r}; one "
                    f"of {mode_cands + ('auto',)}")
            # decode/ragged cannot lower "bound" (the tune() default,
            # kept for CLI compatibility): measure their online default
            mode_list = ["online"]
    results: dict = {}
    best_cand = None
    best_mode = None
    best_s = None
    force_two_kernel = kernel == "flash_bwd"
    if force_two_kernel:
        # the two-kernel family's entry feeds default_bwd_block_sizes,
        # which only governs the NON-fused dispatch — measure that path
        import attention_tpu.ops.flash_bwd as _bwd

        prev_force = _bwd._FORCE_TWO_KERNEL
        _bwd._FORCE_TWO_KERNEL = True
    try:
        for cand in cands:
            base = (f"{cand[0]}x{cand[1]}" if isinstance(cand, tuple)
                    else str(cand))
            for mode in mode_list:
                label = f"{base}@{mode}" if len(mode_list) > 1 else base
                try:
                    with obs.span("tuning.search.measure"):
                        step, x, operands = _measure_factory(
                            kernel, cand, heads=heads, kv_heads=kv_heads,
                            seq=seq, dim=dim, batch=batch, dtype=dtype,
                            causal=causal, window=window, sinks=sinks,
                            stats=stats, max_mode=mode,
                            interpret=interpret)
                        sec = float(timer(step, x, operands, repeats))
                    _CANDIDATES.inc(kernel=kernel)
                except Exception as e:  # noqa: BLE001 - VMEM overflow
                    results[label] = {"error": f"{type(e).__name__}: "
                                               f"{str(e)[:160]}"}
                    _SKIPPED.inc(kernel=kernel, error=type(e).__name__)
                    if log:
                        log(f"  {label}: SKIP ({type(e).__name__})")
                    continue
                results[label] = {"ms": round(sec * 1e3, 4)}
                if log:
                    log(f"  {label}: {sec * 1e3:.3f} ms")
                if best_s is None or sec < best_s:
                    best_s, best_cand, best_mode = sec, cand, mode
    finally:
        if force_two_kernel:
            _bwd._FORCE_TWO_KERNEL = prev_force
    if best_cand is None:
        raise RuntimeError(
            f"every candidate failed for {kernel} at seq={seq}: {results}")
    _SEARCHES.inc(kernel=kernel)

    if kernel == "decode":
        entry = {"block_k": int(best_cand)}
    elif kernel == "paged":
        entry = {"page_size": int(best_cand)}
    else:
        entry = {"block_q": int(best_cand[0]), "block_k": int(best_cand[1])}
    if mode_cands:
        entry["max_mode"] = best_mode
    entry.update({
        "ms": round(best_s * 1e3, 4),
        "source": "measured",
        "recorded": time.strftime("%Y-%m-%d"),
    })
    key = make_key(device_key(), kernel, dtype=dtype_name(dtype),
                   **fields)
    path = cache_path or default_cache_path()
    written = False
    if write:
        table = load_table_cached(path)
        table.put(key, entry)
        table.save(path)
        written = True
    return {
        "kernel": kernel,
        "key": key,
        "candidates": results,
        "entry": entry,
        "cache_path": path,
        "written": written,
    }
