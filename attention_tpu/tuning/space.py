"""Tunable-parameter spaces per kernel family.

Candidate lists cover every tile regime the measured history has ever
picked (RESULTS.md rounds 1-5: 256x1024 seed default, 512x512 windowed,
1024x1024 stats-capped, 2048x1024/2048 causal, 4096x2048 VMEM-unlocked)
plus one step past each boundary so a new device generation can move
the optimum without a code change.  Candidates that cannot compile on a
given chip (VMEM overflow) are skipped by the search's failure
tolerance, so the lists may safely overshoot.
"""

from __future__ import annotations

# (block_q, block_k) for the flash forward kernel.
FLASH_FWD_TILES = (
    (256, 512), (256, 1024),
    (512, 512), (512, 1024), (512, 2048),
    (1024, 1024), (1024, 2048),
    (2048, 1024), (2048, 2048),
    (4096, 1024), (4096, 2048), (4096, 4096),
)

# (block_q, block_k) for the two-kernel backward (dQ + dK/dV).
FLASH_BWD_TILES = (
    (256, 256),
    (512, 512), (512, 1024),
    (1024, 512), (1024, 1024), (1024, 2048),
    (2048, 1024),
)

# (block_q, block_k) for the fused single-pass backward (resident dQ
# makes its VMEM budget tighter -> wide-k candidates).
FLASH_BWD_FUSED_TILES = (
    (256, 256),
    (512, 512), (512, 1024), (512, 2048), (512, 4096),
    (1024, 1024), (1024, 2048), (1024, 4096),
)

# KV block row counts for the dense decode kernel.
DECODE_BLOCK_K = (256, 512, 1024, 2048, 4096, 8192)

# Physical page sizes for the paged decode kernel.
PAGED_PAGE_SIZES = (128, 256, 512, 1024, 2048, 4096)

# Query-tile ROW counts (q_tile tokens x GQA group) for the ragged
# packed-step kernel; the engine divides by the group to get tokens.
RAGGED_BLOCK_Q = (128, 256, 512)

# Rescaling-math variants per family (the max_mode dispatch dimension).
# "bound" leads for the forward because the r05 key-norm-bound skip won
# the device clock there; decode/ragged cannot lower it (no key-norm
# prefetch on the cache read path), so their lists start at online.
FLASH_FWD_MAX_MODES = ("bound", "online", "flashd", "amla")
DECODE_MAX_MODES = ("online", "flashd", "amla")
RAGGED_MAX_MODES = ("online", "flashd", "amla")


def max_mode_candidates(kernel: str) -> tuple:
    """Rescaling-math variants ``tune(max_mode="auto")`` races for one
    family; empty for families whose entries carry no max_mode (the
    backward kernels recompute through the forward's own dispatch, and
    paged/quantized decode take no max_mode at all)."""
    if kernel == "flash_fwd":
        return FLASH_FWD_MAX_MODES
    if kernel == "decode":
        return DECODE_MAX_MODES
    if kernel == "ragged":
        return RAGGED_MAX_MODES
    return ()


def _ceil_to(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def candidates(kernel: str, *, m: int, n: int, d: int,
               window: int | None = None) -> list:
    """Shape-legal candidates for one kernel family.

    Tiles are clipped to the padded problem (a 4096-row block on a 2k
    sequence is the 2k block in disguise) and de-duplicated; decode and
    paged blocks must divide the cache capacity (the kernels' own
    `_pick_block_k`-style constraint).
    """
    if kernel == "flash_fwd":
        tiles = FLASH_FWD_TILES
    elif kernel == "flash_bwd":
        tiles = FLASH_BWD_TILES
    elif kernel == "flash_bwd_fused":
        tiles = FLASH_BWD_FUSED_TILES
    elif kernel == "decode":
        return [bk for bk in dict.fromkeys(
            min(bk, _ceil_to(n, 128)) for bk in DECODE_BLOCK_K)
            if n % bk == 0]
    elif kernel == "paged":
        return [p for p in PAGED_PAGE_SIZES if n % p == 0]
    elif kernel == "ragged":
        return [bq for bq in dict.fromkeys(
            min(bq, _ceil_to(m, 128)) for bq in RAGGED_BLOCK_Q)]
    else:
        raise ValueError(f"unknown kernel family {kernel!r}")
    m_pad = _ceil_to(m, 128)
    n_pad = _ceil_to(n, 128)
    out = []
    for bq, bk in tiles:
        cand = (min(bq, m_pad), min(bk, n_pad))
        if window is not None and cand[1] > _ceil_to(window, 128) * 4:
            # a KV block much wider than the band is all masked columns
            continue
        if cand not in out:
            out.append(cand)
    return out
