"""Continuous-batching serving engine over the paged KV kernels.

The bridge from "fast kernel" to "high-throughput server": many
concurrent requests in, batched `paged_append(_chunk)` +
`paged_flash_decode` steps out.

    requests ──> Scheduler ────────> ServingEngine.step()
                   │  FCFS admission,     │  fixed-shape decode +
                   │  chunked prefill ⊕   │  chunked-prefill calls
                   │  decode batching,    ▼
                   │  preemption      paged kernels (ops.paged)
                   ▼                      │
               BlockAllocator <───────────┘
                   watermark-guarded pages + hash-keyed
                   prefix cache (incref'd shared pages, LRU eviction)

Modules: `request` (lifecycle + sampling params), `allocator` (pages +
prefix cache), `scheduler` (iteration-level batch composition),
`engine` (the step loop), `metrics` (TTFT/TPOT/page-utilization
records), `sim` (JSON traces + replay — `cli serve-sim`'s core),
`snapshot` + `journal` (crash-consistent durability: checksummed
atomic snapshots, write-ahead log, warm recovery).
"""

from attention_tpu.engine.allocator import (  # noqa: F401
    BlockAllocator,
    pages_for_tokens,
)
from attention_tpu.engine.engine import (  # noqa: F401
    EngineConfig,
    ServingEngine,
    StepLimitExceededError,
)
from attention_tpu.engine.errors import (  # noqa: F401
    DeadlineExceededError,
    PrefixLeaseError,
    PrefixStoreCorruptError,
    ReplicaDeadError,
    ReplicaStateError,
    RequestShedError,
    SnapshotCorruptError,
    SnapshotError,
    StepInterruptedError,
)
from attention_tpu.engine.journal import (  # noqa: F401
    Journal,
    apply_journal,
)
from attention_tpu.engine.metrics import (  # noqa: F401
    EngineMetrics,
    RequestMetrics,
    StepMetrics,
)
from attention_tpu.engine.request import (  # noqa: F401
    TERMINAL_STATES,
    Request,
    RequestState,
    SamplingParams,
)
from attention_tpu.engine.scheduler import (  # noqa: F401
    ScheduledStep,
    Scheduler,
)
from attention_tpu.engine.sim import (  # noqa: F401
    bursty_trace,
    diurnal_trace,
    load_trace,
    replay,
    sampling_of,
    save_trace,
    synthetic_trace,
)
from attention_tpu.engine.snapshot import (  # noqa: F401
    SnapshotManager,
    recover_engine,
    state_fingerprint,
)
