"""Block allocator with a hash-keyed prefix cache over `PagePool`.

The engine's memory layer.  One logical `PagePool` serves every model
layer: the engine keeps per-layer physical pools (same page geometry),
so a single page-id allocation is valid in all of them and one table
row per request drives the whole stack — exactly the id discipline
`generate_paged` already uses (its per-layer pools replay identical
allocation sequences).

Prefix cache (vLLM-style, page granularity): committed prompt pages
are published under a content key — ``tuple(tokens[:i * page_size])``
for the i-th page, i.e. the exact token prefix the page's KV encodes —
and a later request whose prompt starts with the same tokens adopts
the pages by reference (`PagePool.incref`) instead of recomputing
them.  Exact-tuple keys rather than a digest: collisions would silently
serve another prompt's KV, and at serving-trace scale the dict is
small.  The cache holds its own reference on every published page, so
pages survive their computing request; eviction is LRU over *leaf*
entries nobody else references (refcount 1 = cache-only), which keeps
chains consistent — a parent page is only evictable after every longer
prefix built on it is gone.

Watermark: admission-path allocations must leave ``watermark_pages``
free (a reserve so already-running requests can keep appending decode
tokens); decode-path allocations may drain the reserve, then the
cache, and only then fail — the scheduler turns that failure into
preemption-by-recompute.
"""

from __future__ import annotations

import dataclasses

from attention_tpu import obs
from attention_tpu.ops.paged import OutOfPagesError, PagePool

_ALLOC_PAGES = obs.counter("engine.allocator.pages_allocated",
                           "pages handed out, by path")
_OOM = obs.counter("engine.allocator.oom",
                   "OutOfPagesError raises, by path")
_WATERMARK = obs.counter("engine.allocator.watermark_trips",
                         "admission allocations refused by the reserve")
_PREFIX_HITS = obs.counter("engine.allocator.prefix_hits")
_PREFIX_MISSES = obs.counter("engine.allocator.prefix_misses")
_PREFIX_HIT_TOKENS = obs.counter("engine.allocator.prefix_hit_tokens")
_PREFIX_EVICTIONS = obs.counter("engine.allocator.prefix_evictions")


def pages_for_tokens(n_tokens: int, page_size: int) -> int:
    """Pages needed to hold ``n_tokens`` KV rows (>= 1 row per page)."""
    return -(-n_tokens // page_size)


@dataclasses.dataclass
class _PrefixEntry:
    key: tuple[int, ...]          # the token prefix this page completes
    page: int                     # physical page holding its last page's KV
    parent: tuple[int, ...] | None
    children: set = dataclasses.field(default_factory=set)
    last_use: int = 0


class BlockAllocator:
    """Watermark-guarded page allocation + prefix cache for one pool."""

    def __init__(self, pool: PagePool, page_size: int, *,
                 watermark_pages: int = 0):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if not (0 <= watermark_pages < pool.num_pages):
            raise ValueError(
                f"watermark_pages {watermark_pages} outside "
                f"[0, {pool.num_pages})"
            )
        self.pool = pool
        self.page_size = page_size
        self.watermark_pages = watermark_pages
        self._prefix: dict[tuple[int, ...], _PrefixEntry] = {}
        # counters the metrics layer reports
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_hit_tokens = 0
        self.prefix_evictions = 0

    # -- capacity ---------------------------------------------------------

    @property
    def cached_pages(self) -> int:
        return len(self._prefix)

    def _evictable(self) -> list[_PrefixEntry]:
        """Leaf entries whose page only the cache references."""
        return [
            e for e in self._prefix.values()
            if not e.children and self.pool.refcount(e.page) == 1
        ]

    def evict_lru(self) -> int | None:
        """Evict the least-recently-used evictable prefix page; returns
        the freed page id, or None when nothing is evictable."""
        victims = self._evictable()
        if not victims:
            return None
        victim = min(victims, key=lambda e: (e.last_use, e.key))
        del self._prefix[victim.key]
        if victim.parent is not None and victim.parent in self._prefix:
            self._prefix[victim.parent].children.discard(victim.key)
        self.pool.free([victim.page])
        self.prefix_evictions += 1
        _PREFIX_EVICTIONS.inc()
        return victim.page

    def allocate(self, n: int, *, for_decode: bool = False) -> list[int]:
        """Allocate ``n`` pages, evicting LRU prefix pages as needed.

        Admission/prefill calls (``for_decode=False``) must leave the
        watermark reserve free *after* the allocation; decode appends
        may drain it.  Raises `OutOfPagesError` when even full eviction
        cannot satisfy the request — the scheduler's preemption signal.
        """
        if n == 0:
            return []
        path = "decode" if for_decode else "admit"
        with obs.span("allocator.alloc"):
            reserve = 0 if for_decode else self.watermark_pages
            # evict until the allocation fits above the reserve;
            # evicting a leaf can expose its parent, so the loop
            # re-scans each round
            while self.pool.free_pages < n + reserve:
                if self.evict_lru() is None:
                    _OOM.inc(path=path)
                    if not for_decode:
                        _WATERMARK.inc()
                    raise OutOfPagesError(
                        f"allocation of {n} page(s) would breach the "
                        f"{'decode floor' if for_decode else 'watermark'}"
                        f": free {self.pool.free_pages}, nothing "
                        f"evictable, reserve {reserve}"
                    )
            _ALLOC_PAGES.inc(n, path=path)
            return self.pool.alloc(n)

    def free(self, pages) -> None:
        """Drop the caller's reference on ``pages`` (cache references,
        if any, keep prefix pages alive for future hits)."""
        self.pool.free(pages)

    # -- prefix cache -----------------------------------------------------

    def peek_prefix(self, tokens) -> int:
        """Pages of the longest cached page-aligned prefix of
        ``tokens`` WITHOUT taking references or touching hit stats /
        LRU clocks — the multi-replica router's side-effect-free probe
        (routing by cache contents must not perturb the cache, or the
        probe of a replica that loses the routing race would still
        refresh its entries)."""
        toks = tuple(tokens)
        limit = (len(toks) - 1) // self.page_size
        n = 0
        for i in range(1, limit + 1):
            if toks[: i * self.page_size] not in self._prefix:
                break
            n += 1
        return n

    def cached_chain(self, tokens) -> list[int]:
        """Physical pages of the longest cached page-aligned prefix of
        ``tokens`` — `peek_prefix`'s page-id twin, equally
        side-effect-free (no increfs, no hit stats, no LRU touches).
        The fleet prefix-store import path reads it to splice
        store-imported pages onto the end of the locally cached chain
        before committing the extended prefix."""
        toks = tuple(tokens)
        limit = (len(toks) - 1) // self.page_size
        pages: list[int] = []
        for i in range(1, limit + 1):
            entry = self._prefix.get(toks[: i * self.page_size])
            if entry is None:
                break
            pages.append(entry.page)
        return pages

    def lookup_prefix(self, tokens, *, now: int) -> list[int]:
        """Longest cached page-aligned prefix of ``tokens``; increfs and
        returns the matched pages (caller owns one reference each).

        At least one token is always left uncached — the last prompt
        token must run through the model to produce the logits the
        first sampled token comes from.
        """
        toks = tuple(tokens)
        limit = (len(toks) - 1) // self.page_size
        pages: list[int] = []
        for i in range(1, limit + 1):
            entry = self._prefix.get(toks[: i * self.page_size])
            if entry is None:
                break
            entry.last_use = now
            pages.append(entry.page)
        if pages:
            self.pool.incref(pages)
            self.prefix_hits += 1
            self.prefix_hit_tokens += len(pages) * self.page_size
            _PREFIX_HITS.inc()
            _PREFIX_HIT_TOKENS.inc(len(pages) * self.page_size)
        else:
            self.prefix_misses += 1
            _PREFIX_MISSES.inc()
        return pages

    def commit_prefix(self, tokens, pages, *, now: int) -> int:
        """Publish every full page of ``tokens`` (whose KV now lives in
        ``pages``, logical order) into the cache; returns how many new
        entries were inserted.  Already-published prefixes are just
        touched — a concurrent identical prompt that missed keeps its
        private pages and the first publisher's copy stays canonical
        (content-identical, so reads through either id agree)."""
        toks = tuple(tokens)
        if len(pages) < len(toks) // self.page_size:
            raise ValueError(
                f"commit_prefix: {len(pages)} pages cannot cover "
                f"{len(toks) // self.page_size} full page(s) of tokens"
            )
        inserted = 0
        parent: tuple[int, ...] | None = None
        for i in range(1, len(toks) // self.page_size + 1):
            key = toks[: i * self.page_size]
            entry = self._prefix.get(key)
            if entry is None:
                page = pages[i - 1]
                self.pool.incref([page])  # the cache's own reference
                entry = _PrefixEntry(key=key, page=page, parent=parent,
                                     last_use=now)
                self._prefix[key] = entry
                if parent is not None and parent in self._prefix:
                    self._prefix[parent].children.add(key)
                inserted += 1
            else:
                entry.last_use = now
            parent = key
        return inserted
