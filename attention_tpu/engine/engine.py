"""The continuous-batching serving engine: step loop over paged kernels.

`ServingEngine` turns many concurrent requests into batched kernel
steps.  Memory is ONE page-id space across all model layers (per-layer
physical pools share the geometry, so a single `PagePool`/
`BlockAllocator` and one table row per request drive the whole stack);
compute is the model's own paged cache paths — `paged_append` +
`paged_flash_decode` for decode rows, `paged_append_chunk` + the
chunk-mode kernel for prefill slices — exactly the kernels
`generate_paged` steps, which is what makes the engine's output
token-for-token comparable to per-request sequential generation.

Shape discipline (the TPU way): in the default ``step_mode="ragged"``
every step lowers onto exactly ONE jitted call over a PACKED token
axis — decode tokens and prefill chunks ride the same axis, delimited
by ``cu_q_lens`` + a decode/prefill ``distribution`` split
(`ops.ragged_paged`).  The packed width and per-request query tile are
power-of-two bucketed, so a serving life compiles O(log max_tokens)
executables and pad waste per step is just the bucket remainder — not
the ``(max_decode_batch - d) + (max_prefill_rows*chunk - real)``
poison rows of the legacy path.  ``step_mode="two_call"`` keeps that
legacy lowering — a ``(max_decode_batch, 1)`` decode call plus a
``(max_prefill_rows, prefill_chunk)`` prefill call padded with the
inactive sentinel (empty table, length -1) — as the parity oracle;
both modes consume logits through the same post-processing helpers,
so their token streams are identical by construction.

``async_steps=True`` double-buffers the loop: after the launch is
dispatched, next step's page-table rows are staged on host
(``engine.step.overlap`` span) BEFORE `jax.block_until_ready` forces
the logits sync — host staging hides behind device compute, the source
paper's ping-pong trick.  Staging is pure pre-rendering (no
allocation, no RNG), so the async loop is token-identical to the sync
loop; snapshot cuts call `quiesce` to settle it.

Tokens stream out through callbacks (``on_token``/``on_finish``) the
moment they are sampled — iteration-level, not request-level, latency.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from attention_tpu import obs
from attention_tpu.obs import trace as _trace
from attention_tpu.engine.allocator import BlockAllocator
from attention_tpu.engine.errors import DeadlineExceededError
from attention_tpu.engine.metrics import (
    EngineMetrics,
    RequestMetrics,
    StepMetrics,
)
from attention_tpu.engine.request import Request, RequestState, SamplingParams
from attention_tpu.engine.scheduler import ScheduledStep, Scheduler
from attention_tpu.ops.paged import OutOfPagesError, PagedKV, PagePool
from attention_tpu.ops.ragged_paged import (
    RaggedPagedStep,
    packed_bucket,
    recommended_q_tile,
)

_CANCELLED = obs.counter("engine.requests.cancelled",
                         "requests cancelled mid-flight")
_TIMED_OUT = obs.counter("engine.requests.timed_out",
                         "requests expired by the deadline sweep")
# host-side dispatches of jitted attention work, labelled by step mode:
# ticks once per LAUNCH (the ragged loop's single-launch property is
# asserted against this; the ops.*.calls counters tick per jit trace)
_LAUNCHES = obs.counter("engine.step.launches",
                        "jitted model launches dispatched by the step loop")
# mesh-serving surface: how many KV-head shards the per-step launches
# lower onto (1 = single-device), and what the shard fan-in costs.  In
# the zero-collective head-sharded design the kernels exchange nothing;
# the only cross-shard cost is reassembling the replicated logits at
# the step's single host sync, which is exactly what the histogram
# times.
_MESH_SHARDS = obs.gauge("engine.mesh.shards",
                         "KV-head shards the engine's jitted launches "
                         "lower onto (1 = single-device)")
_COLLECTIVE_MS = obs.histogram("engine.step.collective_ms",
                               "per-step device sync incl. cross-shard "
                               "logits reassembly on a mesh engine",
                               buckets=(0.1, 0.5, 1.0, 5.0, 10.0, 50.0,
                                        100.0, 500.0))

#: consecutive non-finite-logits steps a request is held back before
#: the finite guard gives up and samples anyway — must exceed any
#: transient nan-injector window (random_gray_plan caps at 5 steps) so
#: gray storms keep token parity, while permanently NaN-corrupted KV
#: pages still terminate instead of wedging the step loop
_NONFINITE_SKIP_LIMIT = 8


class StepLimitExceededError(RuntimeError):
    """`run(max_steps=...)` hit its cap before the queue drained.

    Subclasses RuntimeError for compatibility with callers that caught
    the bare raise this replaces; typed so drivers can distinguish the
    diagnostic guard from a genuine engine failure (the ATP401
    error-taxonomy contract — see attention_tpu/analysis/errors.py)."""


@functools.partial(jax.jit, static_argnames=("model",))
def _paged_apply(model, params, tokens, caches):
    """One batched model step over paged caches.  Module-level with a
    static ``model`` (flax Modules hash by config, the `generate_paged`
    discipline) so every engine instance serving the same model at the
    same batch shapes shares ONE compiled executable per shape — two
    total: ``(max_decode_batch, 1)`` and ``(max_prefill_rows,
    prefill_chunk)``."""
    return model.apply({"params": params}, tokens, caches)


@functools.partial(jax.jit, static_argnames=("model",))
def _ragged_apply(model, params, tokens, caches):
    """One PACKED model step: the whole mixed decode/prefill batch as a
    single ``(1, width)`` token axis over per-layer `RaggedPagedStep`
    caches — exactly one attention launch per layer per engine step.
    Width and the caches' q_tile marker are pow2-bucketed by the
    caller, so distinct compiled signatures stay O(log max_tokens)."""
    return model.apply({"params": params}, tokens, caches)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Serving-engine knobs.  Defaults are sized for tiny CPU tests;
    production configs scale ``num_pages``/batch widths up."""

    num_pages: int = 64
    page_size: int = 128           # paged-kernel granule: 128-multiple
    max_seq_len: int = 1024        # per-request prompt + generated cap
    max_decode_batch: int = 8      # decode rows per step (fixed shape)
    max_prefill_rows: int = 2      # prefill rows per step (fixed shape)
    prefill_chunk: int = 64        # tokens per prefill slice (padded to)
    token_budget: int = 128        # real tokens scheduled per step
    watermark_pages: int = 1       # admission must leave this reserve
    cache_dtype: Any = None        # None -> model dtype
    # "ragged": ONE packed jitted launch per step (ops/ragged_paged);
    # "two_call": the legacy fixed-shape decode+prefill pair, kept as
    # the parity oracle
    step_mode: str = "ragged"
    # double-buffer: stage next step's page-table rows on host while
    # the current launch runs on device (ragged mode only)
    async_steps: bool = False
    # 0 = single-device (default).  N >= 1 serves every per-step jitted
    # launch — both step modes — through the KV-head-sharded kernels on
    # a 1D "tp" mesh of the first N devices: one pool slice per head
    # shard, page tables replicated, host-side packing unchanged.
    # Requires num_kv_heads % N == 0 and N available devices (typed
    # MeshConfigError otherwise, raised at engine construction).
    mesh_shards: int = 0

    def validate(self) -> None:
        if self.page_size % 128:
            raise ValueError(
                f"page_size {self.page_size} must be a 128-multiple "
                "(paged kernel granule)"
            )
        if self.step_mode not in ("ragged", "two_call"):
            raise ValueError(
                f"step_mode {self.step_mode!r} not in "
                "['ragged', 'two_call']"
            )
        if min(self.num_pages, self.max_seq_len, self.max_decode_batch,
               self.max_prefill_rows, self.prefill_chunk,
               self.token_budget) < 1:
            raise ValueError("engine config fields must all be >= 1")
        if not (0 <= self.watermark_pages < self.num_pages):
            raise ValueError(
                f"watermark_pages {self.watermark_pages} outside "
                f"[0, num_pages={self.num_pages})"
            )
        if self.mesh_shards < 0:
            raise ValueError(
                f"mesh_shards {self.mesh_shards} must be >= 0 "
                "(0 = single-device)"
            )

    @property
    def table_width(self) -> int:
        """Page-table row width: covers max_seq_len PLUS one padded
        prefill chunk, so pad rows of a final partial chunk always land
        on claimable pages instead of NaN-poisoning the row."""
        return -(-(self.max_seq_len + self.prefill_chunk)
                 // self.page_size)


class ServingEngine:
    """Deterministic continuous-batching engine over a TinyDecoder-
    family model (any ``impl='flash'`` model whose ``apply`` threads
    per-layer caches, the `generate_paged` contract)."""

    def __init__(self, model, params, config: EngineConfig, *,
                 on_token: Callable[[Request, int], None] | None = None,
                 on_finish: Callable[[Request], None] | None = None,
                 on_timeout: Callable[[Request], None] | None = None):
        config.validate()
        if model.impl != "flash":
            raise ValueError(
                f"ServingEngine requires impl='flash' (got {model.impl!r})"
            )
        self.model = model
        self.params = params
        self.config = config
        self.on_token = on_token
        self.on_finish = on_finish
        self.on_timeout = on_timeout

        # mesh mode: a 1D "tp" mesh of the first mesh_shards devices;
        # the step launches run the model's head-sharded cached paths
        # (a clone with tp_axis set — same params, same math per head)
        # over pools placed one KV-head slice per shard.  Host-side
        # state — allocator, watermarks, prefix cache, packing — never
        # shards: page ids are head-agnostic, so one logical pool and
        # one accounting source of truth serve every shard.
        if config.mesh_shards:
            from attention_tpu.parallel.serving import MeshConfigError

            devices = jax.devices()
            if config.mesh_shards > len(devices):
                raise MeshConfigError(
                    f"mesh_shards {config.mesh_shards} exceeds the "
                    f"{len(devices)} available device(s)"
                )
            if model.num_kv_heads % config.mesh_shards:
                raise MeshConfigError(
                    f"kv heads {model.num_kv_heads} not divisible by "
                    f"mesh_shards {config.mesh_shards}"
                )
            self.mesh = Mesh(
                np.asarray(devices[:config.mesh_shards]), ("tp",)
            )
            try:
                self._step_model = model.clone(tp_axis="tp",
                                               mesh=self.mesh)
            except TypeError as e:
                raise MeshConfigError(
                    f"model {type(model).__name__} lacks the "
                    f"tp_axis/mesh fields mesh serving clones "
                    f"(TinyDecoder-family contract): {e}"
                )
            self._pool_sharding = NamedSharding(
                self.mesh, PartitionSpec(None, "tp", None, None)
            )
        else:
            self.mesh = None
            self._step_model = model
            self._pool_sharding = None

        head_dim = model.dim // model.num_q_heads
        dtype = config.cache_dtype or model.dtype
        pool_shape = (config.num_pages, model.num_kv_heads,
                      config.page_size, head_dim)
        self._k_pools = [self._place_pool(jnp.zeros(pool_shape, dtype))
                         for _ in range(model.depth)]
        self._v_pools = [self._place_pool(jnp.zeros(pool_shape, dtype))
                         for _ in range(model.depth)]
        if obs.is_enabled():
            _MESH_SHARDS.set(float(config.mesh_shards or 1))

        self.pool = PagePool(config.num_pages)
        self.allocator = BlockAllocator(
            self.pool, config.page_size,
            watermark_pages=config.watermark_pages,
        )
        self.scheduler = Scheduler(
            self.allocator,
            max_decode_batch=config.max_decode_batch,
            max_prefill_rows=config.max_prefill_rows,
            prefill_chunk=config.prefill_chunk,
            token_budget=config.token_budget,
        )
        self.metrics = EngineMetrics()
        self._step = 0
        # plain int (not itertools.count) so snapshots can persist the
        # position: auto request-ids and FCFS tiebreaks survive restore
        self._next_seq = 0
        self._finished_in_step = 0
        self._rng_keys: dict[str, jax.Array] = {}
        self._wall: dict[str, dict[str, float]] = {}
        # health signals the replica supervisor reads (frontend/
        # supervisor.py).  ``last_step_virtual_cost`` is the seeded
        # virtual duration of the most recent step — 1.0 unless a
        # chaos slow-step injector inflates it — so slowness detection
        # stays deterministic where real wall time (StepMetrics.wall_s)
        # cannot.  ``nonfinite_events`` counts logits rows the finite
        # guard rejected before sampling.
        self.last_step_virtual_cost = 1.0
        # standing degradation knob: every step's virtual cost starts
        # from this multiplier (1.0 = healthy), so a bench or chaos
        # harness can pin a replica "slow" for a whole window instead
        # of re-injecting per step — the supervisor and the gray-
        # failure detector then see a persistent signal
        self.step_cost_multiplier = 1.0
        self.nonfinite_events = 0
        # consecutive finite-guard skips per request: a TRANSIENT
        # non-finite window (the chaos nan injector poisons returned
        # logits for a few steps) must never emit, but PERMANENTLY
        # poisoned logits (NaN-corrupted KV pages — the chaos
        # ``corrupt`` fault) would livelock the step loop if held back
        # forever; past the limit the request falls through to the
        # documented garbage-but-terminating contract (the checkers
        # exclude corrupted targets from parity)
        self._nonfinite_skips: dict[str, int] = {}
        # async double-buffer state: page-table rows pre-rendered for
        # next step while the current launch runs on device, keyed by
        # request id as (num_pages, row) — `pack` only consumes a row
        # whose page count is still current
        self._staged_rows: dict[str, tuple[int, np.ndarray]] = {}
        # seconds this step spent blocked in the logits device sync
        # (host overhead = step wall minus this)
        self._last_fetch_s = 0.0
        # write-ahead log between snapshots; attached by SnapshotManager
        # (engine/snapshot.py), None when durability is off
        self.journal: Any = None
        # fleet prefix store (attention_tpu/prefixstore); attached by
        # the owning ReplicaHandle (or a test) — None keeps every
        # intake/commit path byte-identical to the storeless engine
        self.prefix_store: Any = None
        # request-trace coordinates (obs/trace.py).  A fronting
        # ReplicaHandle stamps these so engine-side events carry
        # (tick, replica, incarnation); standalone engines default to
        # tick == step.  trace_owner says who records submit/terminal
        # events — the frontend's _finalize funnel takes that role for
        # replicas it owns, so a chain never gets two terminals.
        self.trace_replica: str | None = None
        self.trace_incarnation: int = 0
        self.trace_start_tick: int = 0
        self.trace_owner: str = "engine"

    # -- request tracing --------------------------------------------------

    def _trace_event(self, req: Request, event: str, **extra: Any) -> None:
        """Stamp one trace event with this engine's coordinates."""
        _trace.record(
            req.request_id, event,
            tick=self.trace_start_tick + self._step,
            replica=self.trace_replica,
            incarnation=self.trace_incarnation,
            step=self._step, **extra,
        )

    # -- request intake ---------------------------------------------------

    @property
    def current_step(self) -> int:
        return self._step

    def _validate_intake(self, prompt, sampling: SamplingParams,
                         deadline_step: int | None) -> tuple[int, ...]:
        """Shared admission validation for add_request/resume_request;
        returns the normalized prompt tuple."""
        sampling.validate(self.model.vocab)
        prompt = tuple(int(t) for t in prompt)
        if any(not (0 <= t < self.model.vocab) for t in prompt):
            raise ValueError(
                f"prompt tokens must be in the vocab [0, "
                f"{self.model.vocab})"
            )
        total = len(prompt) + sampling.max_tokens - 1
        if total > self.config.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_tokens "
                f"({sampling.max_tokens}) - 1 = {total} exceeds "
                f"max_seq_len {self.config.max_seq_len}"
            )
        # deadline enforcement AT ADMISSION: a request whose TTL has
        # already elapsed never enters the queue — the typed raise is
        # the front end's signal to mark it TIMED_OUT without burning
        # a queue slot on it
        if deadline_step is not None and deadline_step <= self._step:
            raise DeadlineExceededError(
                f"deadline step {deadline_step} is not after the "
                f"current step {self._step}: expired before admission"
            )
        return prompt

    def _import_prefix(self, prompt: tuple[int, ...]) -> int:
        """Fleet prefix-store import at intake: before admission runs
        its local `lookup_prefix`, splice any matching store chain
        into the allocator so the lookup then hits.  A no-op without
        an attached store; never raises (corruption is counted and
        the request simply cold-prefills)."""
        if self.prefix_store is None:
            return 0
        from attention_tpu.prefixstore.adapter import import_chain

        return import_chain(
            self, prompt, now=self.trace_start_tick + self._step
        )

    def add_request(self, prompt, sampling: SamplingParams | None = None,
                    *, request_id: str | None = None,
                    arrival: int | None = None,
                    deadline_step: int | None = None) -> Request:
        """Enqueue one request.  ``arrival`` (engine step) defaults to
        now; future arrivals let traces replay deterministically.
        ``deadline_step`` (engine step, exclusive) arms the per-step
        deadline sweep; an already-expired deadline raises the typed
        `DeadlineExceededError` here instead of enqueueing."""
        sampling = sampling or SamplingParams()
        prompt = self._validate_intake(prompt, sampling, deadline_step)
        seq = self._next_seq
        self._next_seq += 1
        req = Request(
            request_id=request_id or f"req-{seq}",
            prompt=prompt,
            sampling=sampling,
            arrival=self._step if arrival is None else arrival,
            seq=seq,
            deadline_step=deadline_step,
        )
        self._import_prefix(prompt)
        self._wall[req.request_id] = {"added": time.perf_counter()}
        self.scheduler.add(req)
        if _trace.active() and self.trace_owner == "engine":
            self._trace_event(req, "submitted")
            self._trace_event(req, "admitted")
        if self.journal is not None:
            self.journal.record_admit(req)
        return req

    def resume_request(self, prompt, sampling: SamplingParams, *,
                       request_id: str,
                       output_tokens: list[int] | None = None,
                       arrival: int | None = None,
                       deadline_step: int | None = None) -> Request:
        """Re-admit a partially generated request — the cross-replica
        half of preemption-by-recompute.  ``output_tokens`` are the
        tokens already streamed to the client (by this engine before a
        fault, or by ANOTHER replica that died); the request re-prefills
        prompt + fed generation and resumes decoding without resampling
        anything, exactly like a preempted request readmitting.

        The RNG chain is restored arithmetically: the engine's sampler
        performs one key split per sampled token, so splitting
        ``PRNGKey(seed)`` ``len(output_tokens)`` times reconstructs the
        live key a dead replica took with it — sampled continuations
        stay token-identical to an uninterrupted run."""
        out = [int(t) for t in (output_tokens or [])]
        prompt = self._validate_intake(prompt, sampling, deadline_step)
        if len(out) >= sampling.max_tokens:
            raise ValueError(
                f"request {request_id}: {len(out)} streamed tokens "
                f"leave nothing to resume (max_tokens "
                f"{sampling.max_tokens})"
            )
        seq = self._next_seq
        self._next_seq += 1
        req = Request(
            request_id=request_id,
            prompt=prompt,
            sampling=sampling,
            arrival=self._step if arrival is None else arrival,
            seq=seq,
            deadline_step=deadline_step,
        )
        if out:
            # between steps the invariant is: every emitted token has
            # been fed back EXCEPT the newest, which waits in
            # pending_token (mirrors `Request.emit`/`feed_pending`)
            req.tokens = list(prompt) + out[:-1]
            req.output_tokens = list(out)
            req.pending_token = out[-1]
            if sampling.temperature > 0.0:
                key = jax.random.PRNGKey(sampling.seed)
                for _ in range(len(out)):
                    key, _ = jax.random.split(key)
                self._rng_keys[request_id] = key
        self._import_prefix(prompt)
        self._wall[req.request_id] = {"added": time.perf_counter()}
        self.scheduler.add(req)
        if self.journal is not None:
            self.journal.record_admit(req)
        return req

    def cancel(self, request_id: str) -> bool:
        """Cancel a request anywhere in its lifecycle (client gone).

        Frees its pages (prefix-cache references, if any, survive — a
        cancelled prompt's committed pages stay reusable), drops its
        RNG chain, removes it from the queue or the running set, and
        transitions it to the terminal CANCELLED state.  Safe to call
        between steps only (the scheduler's contract); returns False
        when no live request has that id."""
        for queue in (self.scheduler.waiting, self.scheduler.running):
            for req in queue:
                if req.request_id != request_id:
                    continue
                queue.remove(req)
                _CANCELLED.inc()
                if _trace.active() and self.trace_owner == "engine":
                    self._trace_event(req, "cancelled")
                if req.pages:
                    self.allocator.free(req.pages)
                req.pages = []
                req.transition(RequestState.CANCELLED)
                self._rng_keys.pop(req.request_id, None)
                self._wall.pop(req.request_id, None)
                if self.journal is not None:
                    self.journal.record_cancel(request_id)
                return True
        return False

    # -- deadlines --------------------------------------------------------

    def _time_out(self, req: Request) -> None:
        """Expire one request: free pages (prefix-cache references
        survive, like cancel), terminal TIMED_OUT transition, notify."""
        for queue in (self.scheduler.waiting, self.scheduler.running):
            if req in queue:
                queue.remove(req)
        _TIMED_OUT.inc()
        if _trace.active() and self.trace_owner == "engine":
            self._trace_event(req, "timed_out")
        if req.pages:
            self.allocator.free(req.pages)
        req.pages = []
        req.transition(RequestState.TIMED_OUT)
        req.finish_step = self._step
        self._rng_keys.pop(req.request_id, None)
        self._wall.pop(req.request_id, None)
        if self.journal is not None:
            self.journal.record_timeout(req.request_id)
        if self.on_timeout is not None:
            self.on_timeout(req)

    def _expire_deadlines(self) -> int:
        """The per-step deadline sweep: every queued or running request
        whose ``deadline_step`` has arrived is timed out before the
        step schedules — a deadline can fire mid-prefill (chunks
        computed, no token ever emitted) exactly as it can mid-decode."""
        expired = [
            r for r in (*self.scheduler.waiting, *self.scheduler.running)
            if r.deadline_step is not None
            and r.deadline_step <= self._step
        ]
        for req in expired:
            self._time_out(req)
        return len(expired)

    # -- step loop --------------------------------------------------------

    def step(self) -> StepMetrics:
        """Run one scheduler iteration: compose a batch, lower it onto
        ONE ragged launch (or the legacy two-call pair), stream out
        sampled tokens."""
        t0 = time.perf_counter()
        self._finished_in_step = 0
        self.last_step_virtual_cost = self.step_cost_multiplier
        self._last_fetch_s = 0.0
        pad_tokens = 0
        occupancy = 0.0
        with obs.span("engine.step"):
            timed_out = self._expire_deadlines()
            sched = self.scheduler.schedule(self._step)
            if _trace.active():
                # preemptions free the pages the admissions claim, so
                # they precede admissions in the chain too
                for req in sched.preempted:
                    self._trace_event(req, "preempted")
                for req in sched.admitted:
                    ev = ("resumed"
                          if (req.preemptions or req.output_tokens)
                          else "prefill_start")
                    self._trace_event(req, ev)
            total = sched.num_decode_tokens + sched.num_prefill_tokens
            baseline_pad = self._baseline_pad(sched)
            if self.config.step_mode == "ragged":
                if not sched.is_empty:
                    with obs.span("engine.step.ragged"):
                        width = self._run_ragged(sched)
                    pad_tokens = width - total
                    occupancy = total / width
            else:
                if sched.decode:
                    with obs.span("engine.step.decode"):
                        self._run_decode(sched.decode)
                if sched.prefill:
                    with obs.span("engine.step.prefill"):
                        self._run_prefill(sched.prefill)
                pad_tokens = baseline_pad
                if total:
                    occupancy = total / (total + baseline_pad)
        if self.mesh is not None and obs.is_enabled():
            # the mesh engine's only cross-shard cost: the step's
            # single device sync, where the sharded launch's
            # replicated logits reassemble on host
            _COLLECTIVE_MS.observe(self._last_fetch_s * 1e3)
        wall_s = time.perf_counter() - t0
        m = StepMetrics(
            step=self._step,
            wall_s=wall_s,
            num_decode_reqs=len(sched.decode),
            num_prefill_reqs=len(sched.prefill),
            decode_tokens=sched.num_decode_tokens,
            prefill_tokens=sched.num_prefill_tokens,
            queue_depth=len(self.scheduler.waiting),
            running=len(self.scheduler.running),
            admitted=len(sched.admitted),
            preempted=len(sched.preempted),
            finished=self._finished_in_step,
            timed_out=timed_out,
            free_pages=self.pool.free_pages,
            used_pages=self.pool.used_pages,
            page_utilization=self.pool.used_pages / self.pool.num_pages,
            prefix_hit_tokens_total=self.allocator.prefix_hit_tokens,
            preemptions_total=self.scheduler.num_preemptions,
            pad_tokens=pad_tokens,
            baseline_pad_tokens=baseline_pad,
            ragged_occupancy=occupancy,
            host_overhead_s=max(0.0, wall_s - self._last_fetch_s),
        )
        self.metrics.record_step(m)
        self._step += 1
        return m

    def run(self, *, max_steps: int | None = None) -> dict[str, Any]:
        """Step until every request finishes; returns the metrics
        summary.  Detects a permanently unschedulable queue (a request
        that can never fit the pool) and raises instead of spinning."""
        stalls = 0
        while self.scheduler.has_work():
            if max_steps is not None and self._step >= max_steps:
                raise StepLimitExceededError(
                    f"engine exceeded max_steps={max_steps} with "
                    f"{len(self.scheduler.waiting)} waiting / "
                    f"{len(self.scheduler.running)} running"
                )
            m = self.step()
            due = (self.scheduler.waiting
                   and self.scheduler.waiting[0].arrival < self._step)
            idle = (m.decode_tokens == 0 and m.prefill_tokens == 0
                    and not self.scheduler.running)
            stalls = stalls + 1 if (idle and due) else 0
            if stalls > 2:
                head = self.scheduler.waiting[0]
                raise OutOfPagesError(
                    f"request {head.request_id} cannot be admitted "
                    "(needs more pages than the pool can ever free)"
                )
        return self.metrics.summary()

    # -- health / drain hooks (the multi-replica front end's probes) ------

    def health(self) -> dict[str, Any]:
        """Cheap host-side pressure snapshot — what a fronting router
        reads every tick to drive load scoring, shedding thresholds,
        and the degradation ladder.  Pure Python state, no device
        sync, safe to call between steps at any frequency."""
        return {
            "step": self._step,
            "waiting": len(self.scheduler.waiting),
            "running": len(self.scheduler.running),
            "free_pages": self.pool.free_pages,
            "used_pages": self.pool.used_pages,
            "page_utilization": self.pool.used_pages
            / self.pool.num_pages,
            "cached_pages": self.allocator.cached_pages,
            "preemptions": self.scheduler.num_preemptions,
            "nonfinite_events": self.nonfinite_events,
            "step_virtual_cost": self.last_step_virtual_cost,
        }

    def drain(self, *, max_steps: int | None = None) -> dict[str, Any]:
        """Graceful shutdown: serve the current queue dry and return
        the metrics summary.  New work only arrives through
        add_request/resume_request, so a caller that stops admitting
        and calls drain gets clean quiescence — every page back in the
        pool or held solely by the prefix cache."""
        return self.run(max_steps=max_steps)

    # -- batch lowering ---------------------------------------------------

    def _baseline_pad(self, sched: ScheduledStep) -> int:
        """Pad tokens the legacy two-call lowering dispatches for this
        step's composition — the yardstick ragged occupancy is measured
        against."""
        pad = 0
        if sched.decode:
            pad += self.config.max_decode_batch - len(sched.decode)
        if sched.prefill:
            pad += (self.config.max_prefill_rows
                    * self.config.prefill_chunk
                    - sched.num_prefill_tokens)
        return pad

    def _table_rows(self, reqs: list[Request]) -> np.ndarray:
        rows = np.full((len(reqs), self.config.table_width), -1, np.int64)
        for i, req in enumerate(reqs):
            rows[i, : len(req.pages)] = req.pages
        return rows

    def _place_pool(self, arr):
        """Device placement for one per-layer pool: one KV-head slice
        per shard on a mesh engine, plain single-device otherwise.
        Snapshot restore routes reconstructed pools through this too,
        so a restored mesh engine's pools land sharded again."""
        arr = jnp.asarray(arr)
        if self._pool_sharding is None:
            return arr
        return jax.device_put(arr, self._pool_sharding)

    def _fetch_logits(self, logits_dev) -> np.ndarray:
        """The step loop's ONLY device sync: materialize the launch's
        logits on host.  Isolated in one hook so (a) the async loop can
        finish its overlapped staging before the block, (b) per-step
        host overhead is measurable as wall minus time spent here, and
        (c) fault injectors have a single seam to poison."""
        t0 = time.perf_counter()
        out = np.asarray(logits_dev, np.float32)
        self._last_fetch_s += time.perf_counter() - t0
        return out

    def _apply(self, tokens: np.ndarray, tables: np.ndarray,
               lens: np.ndarray) -> np.ndarray:
        caches = tuple(
            PagedKV(self._k_pools[layer], self._v_pools[layer],
                    jnp.asarray(tables, jnp.int32),
                    jnp.asarray(lens, jnp.int32))
            for layer in range(self.model.depth)
        )
        if obs.is_enabled():
            _LAUNCHES.inc(mode="two_call")
        logits, new_caches = _paged_apply(
            self._step_model, self.params,
            jnp.asarray(tokens, jnp.int32), caches
        )
        for layer, c in enumerate(new_caches):
            self._k_pools[layer] = c.k_pool
            self._v_pools[layer] = c.v_pool
        return self._fetch_logits(logits)

    def _run_ragged(self, sched: ScheduledStep) -> int:
        """Lower the WHOLE step onto one jitted packed launch; returns
        the packed width dispatched.

        The per-request query tile covers the longest prefill chunk and
        the packed width covers every real token, both pow2-bucketed —
        occupancy stays high while compiled signatures stay
        O(log max_tokens).  With ``async_steps`` the host stages next
        step's page-table rows between dispatch and the logits sync."""
        cfg = self.config
        slots = cfg.max_decode_batch + cfg.max_prefill_rows
        group = self.model.num_q_heads // self.model.num_kv_heads
        head_dim = self.model.dim // self.model.num_q_heads
        max_q = max((n for _, n in sched.prefill), default=1)
        q_tile = recommended_q_tile(
            max_q, group, heads=self.model.num_q_heads,
            kv_heads=self.model.num_kv_heads, seq=cfg.max_seq_len,
            dim=head_dim, batch=slots,
            dtype=cfg.cache_dtype or self.model.dtype,
        )
        total = sched.num_decode_tokens + sched.num_prefill_tokens
        width = packed_bucket(max(total, q_tile))
        batch = sched.pack(width=width, slots=slots,
                           table_width=cfg.table_width,
                           staged_rows=self._staged_rows)
        self._staged_rows = {}
        tables = jnp.asarray(batch.tables, jnp.int32)
        kv_lens = jnp.asarray(batch.kv_lens, jnp.int32)
        cu = jnp.asarray(batch.cu_q_lens, jnp.int32)
        dist = jnp.asarray(batch.distribution, jnp.int32)
        pos = jnp.asarray(batch.token_pos, jnp.int32)
        slot = jnp.asarray(batch.token_slot, jnp.int32)
        q_span = np.zeros((q_tile,), np.int32)  # shape carries q_tile
        caches = tuple(
            RaggedPagedStep(self._k_pools[layer], self._v_pools[layer],
                            tables, kv_lens, cu, dist, pos, slot, q_span)
            for layer in range(self.model.depth)
        )
        if obs.is_enabled():
            _LAUNCHES.inc(mode="ragged")
        logits_dev, new_caches = _ragged_apply(
            self._step_model, self.params,
            jnp.asarray(batch.tokens, jnp.int32), caches,
        )
        for layer, c in enumerate(new_caches):
            self._k_pools[layer] = c.k_pool
            self._v_pools[layer] = c.v_pool
        if cfg.async_steps:
            # the double-buffer window: the launch is in flight, the
            # sync has not happened — overlap next step's host staging
            with obs.span("engine.step.overlap"):
                self._stage_next_step()
        logits = self._fetch_logits(logits_dev)
        cu_h = batch.cu_q_lens
        num_decode = len(sched.decode)
        for i, req in enumerate(sched.decode):
            self._post_decode(req, logits[0, cu_h[i]])
        for s, (req, real) in enumerate(sched.prefill):
            self._post_prefill(
                req, real, logits[0, cu_h[num_decode + s] + real - 1]
            )
        return width

    def _stage_next_step(self) -> None:
        """Host half of the double buffer: pre-render page-table rows
        for every request that will decode next step, while the device
        is still busy.  Pure staging — no page allocation, no pool
        mutation, no RNG consumption — so the async loop's tokens are
        identical to the sync loop's by construction; `pack` discards
        any staged row whose page count went stale."""
        staged: dict[str, tuple[int, np.ndarray]] = {}
        tw = self.config.table_width
        for req in self.scheduler.running:
            if req.state is RequestState.DECODING and req.pages:
                row = np.full((tw,), -1, np.int32)
                row[: len(req.pages)] = req.pages
                staged[req.request_id] = (len(req.pages), row)
        self._staged_rows = staged

    def quiesce(self) -> None:
        """Settle the staged/in-flight step: drop staged rows and block
        until the device pools are final.  Snapshot cuts run this first
        so a serialized image never captures a half-staged async step."""
        self._staged_rows = {}
        for a in (*self._k_pools, *self._v_pools):
            jax.block_until_ready(a)

    def _run_decode(self, reqs: list[Request]) -> None:
        d = self.config.max_decode_batch
        tokens = np.zeros((d, 1), np.int32)
        tables = np.full((d, self.config.table_width), -1, np.int64)
        lens = np.full((d,), -1, np.int32)  # -1 = inactive pad row
        for i, req in enumerate(reqs):
            lens[i] = req.computed_tokens
            tokens[i, 0] = req.feed_pending()
            tables[i, : len(req.pages)] = req.pages
        logits = self._apply(tokens, tables, lens)
        for i, req in enumerate(reqs):
            self._post_decode(req, logits[i, 0])

    def _post_decode(self, req: Request, logits_row: np.ndarray) -> None:
        """Consume one decode request's logits row — the mode-agnostic
        half of a decode step (both lowerings call this, which is what
        makes their token streams identical by construction)."""
        if not np.isfinite(logits_row).all():
            # poisoned logits must never reach sampling: a garbage
            # token would break parity with the fault-free run.
            # Un-feed the pending token (its KV slot is simply
            # overwritten on retry) so the request makes no
            # progress this step, and count the event — the
            # replica supervisor's NaN signal.  Bounded: see
            # _NONFINITE_SKIP_LIMIT.
            self.nonfinite_events += 1
            skips = self._nonfinite_skips.get(req.request_id, 0) + 1
            self._nonfinite_skips[req.request_id] = skips
            if skips <= _NONFINITE_SKIP_LIMIT:
                req.pending_token = req.tokens.pop()
                return
        else:
            self._nonfinite_skips.pop(req.request_id, None)
        req.computed_tokens = len(req.tokens)
        self._emit(req, self._sample(req, logits_row))

    def _run_prefill(self, items: list[tuple[Request, int]]) -> None:
        p = self.config.max_prefill_rows
        s = self.config.prefill_chunk
        tokens = np.zeros((p, s), np.int32)
        tables = np.full((p, self.config.table_width), -1, np.int64)
        lens = np.full((p,), -1, np.int32)
        for i, (req, real) in enumerate(items):
            c = req.computed_tokens
            tokens[i, :real] = req.tokens[c : c + real]
            tables[i, : len(req.pages)] = req.pages
            lens[i] = c
        logits = self._apply(tokens, tables, lens)
        for i, (req, real) in enumerate(items):
            self._post_prefill(req, real, logits[i, real - 1])

    def _post_prefill(self, req: Request, real: int,
                      last_row: np.ndarray) -> None:
        """Consume one prefill chunk's last logits row — the
        mode-agnostic half of a prefill step (both lowerings call
        this)."""
        if (req.computed_tokens + real >= len(req.tokens)
                and not req.output_tokens
                and not np.isfinite(last_row).all()):
            # the final chunk samples the first token; with
            # non-finite logits, skip the whole chunk (the KV it
            # wrote is recomputed in place next step) rather than
            # emit garbage.  Bounded: see _NONFINITE_SKIP_LIMIT.
            self.nonfinite_events += 1
            skips = self._nonfinite_skips.get(req.request_id, 0) + 1
            self._nonfinite_skips[req.request_id] = skips
            if skips <= _NONFINITE_SKIP_LIMIT:
                return
        req.computed_tokens += real
        if req.computed_tokens < len(req.tokens):
            return  # more chunks to go
        self._commit_prefix(req)
        req.transition(RequestState.DECODING)
        if req.output_tokens:
            # resumed after preemption: the recomputed KV now covers
            # every fed token; the pending token was already sampled
            # and streamed — never resample it
            return
        self._emit(req, self._sample(req, last_row))

    def _commit_prefix(self, req: Request) -> None:
        full = req.num_prompt_tokens // self.config.page_size
        if full:
            self.allocator.commit_prefix(
                req.prompt, req.pages[:full], now=self._step
            )
            if self.prefix_store is not None:
                # fleet export rides the local commit: the pages just
                # became shared-by-reference here, so publish them to
                # the store (waiters on this chain's single-flight
                # lease observe the chain and import next tick)
                from attention_tpu.prefixstore.adapter import export_chain

                export_chain(
                    self, req.prompt, req.pages[:full],
                    now=self.trace_start_tick + self._step,
                )

    # -- token emission ---------------------------------------------------

    def _sample(self, req: Request, logits_row: np.ndarray) -> int:
        if req.sampling.temperature == 0.0:
            return int(np.argmax(logits_row))
        from attention_tpu.models.decode import warp_logits

        key = self._rng_keys.get(req.request_id)
        if key is None:
            key = jax.random.PRNGKey(req.sampling.seed)
        key, sub = jax.random.split(key)
        self._rng_keys[req.request_id] = key
        warped = warp_logits(
            jnp.asarray(logits_row)[None],
            temperature=req.sampling.temperature,
            top_k=req.sampling.top_k,
            top_p=req.sampling.top_p,
        )
        return int(jax.random.categorical(sub, warped, axis=-1)[0])

    def _emit(self, req: Request, token: int) -> None:
        done = req.emit(token)
        if self.journal is not None:
            self.journal.record_token(req.request_id, token)
        if req.first_token_step < 0:
            req.first_token_step = self._step
            self._wall[req.request_id]["first_token"] = time.perf_counter()
            if _trace.active():
                self._trace_event(req, "first_token")
        if self.on_token is not None:
            self.on_token(req, token)
        if done:
            self._finish(req)

    def _finish(self, req: Request) -> None:
        req.transition(RequestState.FINISHED)
        req.finish_step = self._step
        if _trace.active() and self.trace_owner == "engine":
            self._trace_event(req, "finished")
        self._nonfinite_skips.pop(req.request_id, None)
        if self.journal is not None:
            self.journal.record_finish(req.request_id)
        if req.pages:
            self.allocator.free(req.pages)
        req.pages = []
        self.scheduler.remove_finished(req)
        self._rng_keys.pop(req.request_id, None)
        self._finished_in_step += 1
        wall = self._wall.pop(req.request_id, {})
        now = time.perf_counter()
        self.metrics.record_request(RequestMetrics(
            request_id=req.request_id,
            arrival_step=req.arrival,
            first_scheduled_step=req.first_scheduled_step,
            first_token_step=req.first_token_step,
            finish_step=req.finish_step,
            prompt_tokens=req.num_prompt_tokens,
            output_tokens=req.num_output_tokens,
            prefix_cached_tokens=req.prefix_cached_tokens,
            preemptions=req.preemptions,
            ttft_s=now - wall.get("added", now)
            if "first_token" not in wall
            else wall["first_token"] - wall["added"],
            finish_s=now - wall.get("added", now),
        ))
        if self.on_finish is not None:
            self.on_finish(req)


# re-exported for callers that only import the engine module
__all__ = [
    "EngineConfig",
    "ServingEngine",
    "Request",
    "RequestState",
    "SamplingParams",
    "ScheduledStep",
]
