"""Synthetic request traces + deterministic trace replay.

The serving engine's test/bench harness: a trace is a plain-JSON list
of requests (arrival step, prompt token ids, sampling params), so a
workload is a FILE — reproducible across runs, machines, and engine
versions.  `synthetic_trace` fabricates one (seeded, optionally with a
shared prompt prefix so the prefix cache has something to hit);
`replay` feeds a trace through an engine and collects every request's
output stream.  `cli serve-sim` and `scripts/engine_trace.py` are thin
shells over these helpers.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

from attention_tpu.engine.engine import ServingEngine
from attention_tpu.engine.request import SamplingParams

_SAMPLING_KEYS = ("max_tokens", "temperature", "top_k", "top_p", "seed",
                  "stop_token")


def synthetic_trace(
    num_requests: int,
    *,
    vocab: int,
    seed: int = 0,
    prompt_len_min: int = 4,
    prompt_len_max: int = 24,
    max_tokens: int = 8,
    arrival_every: int = 1,
    shared_prefix_len: int = 0,
    shared_count: int = 0,
    temperature: float = 0.0,
) -> list[dict[str, Any]]:
    """A seeded synthetic request trace.

    The first ``shared_count`` requests start with one common
    ``shared_prefix_len``-token prefix (generate-once-reuse-many: make
    it at least ``page_size + 1`` for the prefix cache to engage).
    Arrivals are staggered ``arrival_every`` steps apart (0 = all at
    step 0).  Token 0 is reserved as the engine's pad token and never
    generated into prompts.
    """
    if num_requests < 1:
        raise ValueError(f"num_requests must be >= 1, got {num_requests}")
    if not (1 <= prompt_len_min <= prompt_len_max):
        raise ValueError(
            f"bad prompt length range [{prompt_len_min}, {prompt_len_max}]"
        )
    rng = np.random.default_rng(seed)
    shared = rng.integers(1, vocab, shared_prefix_len).tolist() \
        if shared_prefix_len else []
    trace = []
    for i in range(num_requests):
        n = int(rng.integers(prompt_len_min, prompt_len_max + 1))
        body = rng.integers(1, vocab, n).tolist()
        prompt = (shared + body) if i < shared_count else body
        trace.append({
            "id": f"req-{i}",
            "arrival": i * arrival_every,
            "prompt": [int(t) for t in prompt],
            "max_tokens": int(max_tokens),
            "temperature": float(temperature),
            "seed": int(seed + i),
        })
    return trace


def bursty_trace(
    num_requests: int,
    *,
    vocab: int,
    seed: int = 0,
    tenants: int = 2,
    burst_every: int = 6,
    burst_size: int = 3,
    shared_prefix_len: int = 0,
    prompt_len_min: int = 4,
    prompt_len_max: int = 24,
    max_tokens: int = 8,
    temperature: float = 0.0,
    deadline_ticks: int | None = None,
    priorities: tuple[int, ...] = (0, 1, 1, 2),
) -> list[dict[str, Any]]:
    """A seeded multi-tenant bursty trace — the front end's workload.

    Requests arrive in BURSTS of ``burst_size`` every ``burst_every``
    ticks (the diurnal-spike shape that makes load shedding and the
    degradation ladder earn their keep), tagged with the resilience
    fields the plain engine ignores and `replay_frontend` consumes:

    * ``session``: ``tenant-<k>`` — requests of one tenant share a
      session (sticky routing) and, when ``shared_prefix_len`` > 0,
      a per-tenant common prompt prefix (make it >= page_size + 1 for
      the prefix cache to engage);
    * ``priority``: drawn from ``priorities`` (0 = highest; class 2 is
      the sheddable tail);
    * ``deadline_ticks``: per-request TTL relative to arrival
      (None = no deadline).

    Token 0 stays reserved as the engine's pad token.
    """
    if num_requests < 1:
        raise ValueError(f"num_requests must be >= 1, got {num_requests}")
    if tenants < 1 or burst_every < 1 or burst_size < 1:
        raise ValueError(
            "tenants, burst_every, and burst_size must all be >= 1"
        )
    if not (1 <= prompt_len_min <= prompt_len_max):
        raise ValueError(
            f"bad prompt length range [{prompt_len_min}, {prompt_len_max}]"
        )
    rng = np.random.default_rng(seed)
    prefixes = [
        rng.integers(1, vocab, shared_prefix_len).tolist()
        if shared_prefix_len else []
        for _ in range(tenants)
    ]
    trace = []
    for i in range(num_requests):
        burst = i // burst_size
        tenant = int(rng.integers(tenants))
        n = int(rng.integers(prompt_len_min, prompt_len_max + 1))
        body = rng.integers(1, vocab, n).tolist()
        entry = {
            "id": f"req-{i}",
            "arrival": burst * burst_every,
            "prompt": [int(t) for t in prefixes[tenant] + body],
            "max_tokens": int(max_tokens),
            "temperature": float(temperature),
            "seed": int(seed + i),
            "session": f"tenant-{tenant}",
            "priority": int(priorities[int(rng.integers(
                len(priorities)))]),
        }
        if deadline_ticks is not None:
            entry["deadline_ticks"] = int(deadline_ticks)
        trace.append(entry)
    return trace


def diurnal_trace(
    num_requests: int,
    *,
    vocab: int,
    seed: int = 0,
    period: int = 48,
    base_rate: float = 1.0,
    peak_rate: float = 4.0,
    tenants: int = 3,
    rag_every: int = 7,
    rag_prefill_len: int = 64,
    prompt_len_min: int = 4,
    prompt_len_max: int = 24,
    max_tokens: int = 8,
    temperature: float = 0.0,
    deadline_ticks: int | None = None,
    priorities: tuple[int, ...] = (0, 1, 1, 2),
) -> list[dict[str, Any]]:
    """A seeded diurnal trace — the seasonal forecaster's workload.

    Arrival rate follows one sinusoidal "day" of ``period`` ticks,
    swinging between ``base_rate`` (trough) and ``peak_rate`` (peak)
    requests/tick — the shape a production fleet sees from
    millions of users across time zones, scaled down to sim ticks.
    Arrivals are generated by deterministic rate integration (advance
    virtual time by ``1/rate(t)`` per request), so the SAME seed and
    knobs give the same arrival ticks on every platform.

    The tenant mix is the `bursty_trace` schema (``session``,
    ``priority``, optional ``deadline_ticks``); every ``rag_every``-th
    request is a long-prefill RAG burst — its tenant's shared
    ``rag_prefill_len``-token retrieval header (make it >= page_size +
    1 for the prefix cache to engage) glued before the body, the
    workload that makes prefill pressure seasonal too.  Token 0 stays
    reserved as the engine's pad token.
    """
    if num_requests < 1:
        raise ValueError(f"num_requests must be >= 1, got {num_requests}")
    if period < 2:
        raise ValueError(f"period must be >= 2 ticks, got {period}")
    if not (0.0 < base_rate <= peak_rate):
        raise ValueError(
            f"need 0 < base_rate <= peak_rate, got "
            f"{base_rate}/{peak_rate}"
        )
    if tenants < 1 or rag_every < 1:
        raise ValueError("tenants and rag_every must both be >= 1")
    if not (1 <= prompt_len_min <= prompt_len_max):
        raise ValueError(
            f"bad prompt length range [{prompt_len_min}, {prompt_len_max}]"
        )
    rng = np.random.default_rng(seed)
    rag_prefixes = [
        rng.integers(1, vocab, rag_prefill_len).tolist()
        if rag_prefill_len else []
        for _ in range(tenants)
    ]
    trace = []
    clock = 0.0
    mid = (peak_rate + base_rate) / 2.0
    amp = (peak_rate - base_rate) / 2.0
    for i in range(num_requests):
        # rate at the current virtual time; trough at t=0 so a run
        # starts quiet, peaks mid-period
        rate = mid - amp * float(np.cos(2.0 * np.pi * clock / period))
        clock += 1.0 / rate
        tenant = int(rng.integers(tenants))
        n = int(rng.integers(prompt_len_min, prompt_len_max + 1))
        body = rng.integers(1, vocab, n).tolist()
        is_rag = rag_prefill_len > 0 and (i + 1) % rag_every == 0
        prompt = (rag_prefixes[tenant] + body) if is_rag else body
        entry = {
            "id": f"req-{i}",
            "arrival": int(clock),
            "prompt": [int(t) for t in prompt],
            "max_tokens": int(max_tokens),
            "temperature": float(temperature),
            "seed": int(seed + i),
            "session": f"tenant-{tenant}",
            "priority": int(priorities[int(rng.integers(
                len(priorities)))]),
        }
        if deadline_ticks is not None:
            entry["deadline_ticks"] = int(deadline_ticks)
        trace.append(entry)
    return trace


def disagg_trace(
    num_requests: int,
    *,
    vocab: int,
    seed: int = 0,
    rate: float = 1.5,
    burst_every: int = 16,
    burst_size: int = 3,
    tenants: int = 3,
    rag_prefill_len: int = 96,
    prompt_len_min: int = 4,
    prompt_len_max: int = 12,
    max_tokens: int = 12,
    temperature: float = 0.0,
    deadline_ticks: int | None = None,
) -> list[dict[str, Any]]:
    """A seeded mixed prefill/decode workload — the disaggregated
    fleet's trace (`attention_tpu.fleet`).

    Two populations with opposite resource appetites: a steady stream
    of decode-heavy chat sessions (short prompts, ``max_tokens``-long
    generations — the decode pool's diet), interrupted every
    ``burst_every`` requests by a burst of ``burst_size`` long-prefill
    RAG requests (the tenant's shared ``rag_prefill_len``-token
    retrieval header glued before a short body, few output tokens —
    the prefill pool's diet).  The alternation is what gives the
    autoscaler a prefill:decode imbalance worth rebalancing.

    Arrivals use the `diurnal_trace` deterministic rate-integration
    scheme at a flat ``rate``; bursts land at the same virtual tick.
    Token 0 stays reserved as the engine's pad token.
    """
    if num_requests < 1:
        raise ValueError(f"num_requests must be >= 1, got {num_requests}")
    if rate <= 0.0:
        raise ValueError(f"rate must be > 0, got {rate}")
    if tenants < 1 or burst_every < 1 or burst_size < 1:
        raise ValueError(
            "tenants, burst_every, and burst_size must all be >= 1")
    if not (1 <= prompt_len_min <= prompt_len_max):
        raise ValueError(
            f"bad prompt length range [{prompt_len_min}, {prompt_len_max}]"
        )
    rng = np.random.default_rng(seed)
    rag_prefixes = [
        rng.integers(1, vocab, rag_prefill_len).tolist()
        if rag_prefill_len else []
        for _ in range(tenants)
    ]
    trace = []
    clock = 0.0
    i = 0
    burst_left = 0
    burst_tenant = 0
    while i < num_requests:
        if burst_left == 0 and i and i % burst_every == 0:
            # a RAG burst arrives together: same virtual tick, one
            # tenant's retrieval header shared across the burst
            burst_left = burst_size
            burst_tenant = int(rng.integers(tenants))
        if burst_left > 0:
            burst_left -= 1
            tenant = burst_tenant
            body = rng.integers(
                1, vocab,
                int(rng.integers(prompt_len_min,
                                 prompt_len_max + 1))).tolist()
            prompt = rag_prefixes[tenant] + body
            # floor of 2: one token commits the prompt, the next is
            # what the decode pool exists to serve — a 1-token RAG
            # request would finish before any handoff could happen
            out = max(2, max_tokens // 4)
        else:
            clock += 1.0 / rate
            tenant = int(rng.integers(tenants))
            prompt = rng.integers(
                1, vocab,
                int(rng.integers(prompt_len_min,
                                 prompt_len_max + 1))).tolist()
            out = max_tokens
        entry = {
            "id": f"req-{i}",
            "arrival": int(clock),
            "prompt": [int(t) for t in prompt],
            "max_tokens": int(out),
            "temperature": float(temperature),
            "seed": int(seed + i),
            "session": f"tenant-{tenant}",
            "priority": 1,
        }
        if deadline_ticks is not None:
            entry["deadline_ticks"] = int(deadline_ticks)
        trace.append(entry)
        i += 1
    return trace


def save_trace(path: str, trace: list[dict[str, Any]], *,
               gray_plan: dict[str, Any] | None = None) -> None:
    """Persist a trace; ``gray_plan`` (the `chaos.FaultPlan` JSON dict)
    rides along as a top-level annotation so a gray storm replays
    byte-identically from the trace file ALONE — no side-channel plan
    file to lose."""
    doc: dict[str, Any] = {"requests": trace}
    if gray_plan is not None:
        doc["gray_plan"] = gray_plan
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")


def load_trace(path: str) -> list[dict[str, Any]]:
    with open(path) as f:
        data = json.load(f)
    reqs = data["requests"] if isinstance(data, dict) else data
    if not isinstance(reqs, list) or not reqs:
        raise ValueError(f"{path}: trace holds no requests")
    for r in reqs:
        if "prompt" not in r or not r["prompt"]:
            raise ValueError(f"{path}: request {r.get('id')} has no prompt")
    return reqs


def load_gray_plan(path: str) -> dict[str, Any] | None:
    """The trace file's embedded gray-plan annotation (see
    `save_trace`), or None.  Returned as the raw JSON dict — the
    chaos layer (`chaos.FaultPlan.from_json`) owns the typed form, and
    this module must not import it."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        return None
    plan = data.get("gray_plan")
    if plan is not None and not isinstance(plan, dict):
        raise ValueError(f"{path}: gray_plan must be a JSON object")
    return plan


def sampling_of(entry: dict[str, Any]) -> SamplingParams:
    """`SamplingParams` from one trace entry (shared with the
    multi-replica front end's `replay_frontend`)."""
    kw = {k: entry[k] for k in _SAMPLING_KEYS if entry.get(k) is not None}
    return SamplingParams(**kw)


_sampling_of = sampling_of  # internal alias, kept for existing callers


def replay(engine: ServingEngine, trace: list[dict[str, Any]], *,
           max_steps: int | None = None):
    """Feed a trace through ``engine`` and run it dry.  Returns
    ``(summary, outputs)`` with ``outputs[request_id]`` the generated
    token list, in trace order."""
    outputs: dict[str, list[int]] = {}

    def _collect(req, token):
        outputs.setdefault(req.request_id, []).append(int(token))

    prev = engine.on_token

    def _chained(req, token):
        _collect(req, token)
        if prev is not None:
            prev(req, token)

    engine.on_token = _chained
    try:
        for entry in trace:
            engine.add_request(
                entry["prompt"], _sampling_of(entry),
                request_id=entry.get("id"),
                arrival=int(entry.get("arrival", 0)),
            )
        summary = engine.run(max_steps=max_steps)
    finally:
        engine.on_token = prev
    return summary, outputs
