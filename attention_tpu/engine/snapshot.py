"""Crash-consistent `ServingEngine` snapshots: save, verify, restore.

The training side has had this contract since PR 3
(`models/checkpoint.py` + `models/resilient.py`: checkpoint-every-N,
re-invoke, bit-identical resume); this module gives the *serving*
engine the same durability rung.  A snapshot is a consistent
between-steps cut of everything that determines future outputs:

========== ============================================================
section    contents
========== ============================================================
``meta``   format version, `EngineConfig` fields, model fingerprint
           (vocab/dim/depth/heads/dtype/impl), engine step, seq counter
``pools``  raw per-layer K/V page-pool payloads (``tobytes``; dtype and
           shape recorded in ``meta`` — bf16 round-trips via ml_dtypes).
           A mesh engine (``mesh_shards`` = N > 1) writes ``pools.0``
           .. ``pools.N-1`` instead — each shard's contiguous KV-head
           slice of every pool, independently CRC'd — and the manifest
           records ``shards: N``; restore reassembles along the head
           dim and re-places the pools on the reader's mesh
``state``  `PagePool` free list (exact order) + refcounts, prefix-cache
           index (keys, pages, parent/children links, LRU stamps),
           allocator counters, scheduler knobs
``requests`` waiting + running queues in order: full `Request` fields
           including streamed tokens and ``pending_token`` — the RNG
           chain is NOT serialized; it is reconstructed arithmetically
           (one split per sampled token) exactly like `resume_request`
========== ============================================================

On disk: one ASCII JSON manifest line (magic, version, per-section
byte counts and CRC32s) followed by the concatenated section payloads.
Serialization is deterministic (sorted keys, ordered queues), so
``sha256(serialize(engine))`` is a usable state fingerprint — the
chaos invariant ``restore(save(engine))`` compares exactly that.

Durability discipline (pinned by ATP701, `analysis/durability.py`):
the snapshot file appears atomically AND durably via
``tempfile.mkstemp`` in the target directory, ``os.fsync`` of the
temp fd, ``os.replace``, then an fsync of the directory — a reader
(or a recovery scan) never observes a torn snapshot, only the
previous one, and a landed file survives power loss.  Any validation
failure — bad magic, stale version, truncated or bit-flipped section,
model mismatch — raises the typed `SnapshotCorruptError`; recovery
code treats that as "this candidate does not count" and falls back,
never crashes.

Deliberately NOT serialized: wall-clock bookkeeping (``_wall`` is
re-seeded at restore; TTFT/latency percentiles are observability, not
contract) and `EngineMetrics` history.  Token streams, the scheduler
contract, and page accounting round-trip exactly.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import tempfile
import time
import zlib

import jax
import jax.numpy as jnp
import numpy as np

from attention_tpu import obs
from attention_tpu.obs import trace as _trace
from attention_tpu.engine.allocator import _PrefixEntry
from attention_tpu.engine.engine import EngineConfig, ServingEngine
from attention_tpu.engine.errors import SnapshotCorruptError, SnapshotError
from attention_tpu.engine.journal import (
    Journal,
    apply_journal,
    journal_path,
    list_journals,
)
from attention_tpu.engine.request import Request, RequestState, SamplingParams
from attention_tpu.parallel.serving import MeshConfigError

SNAPSHOT_MAGIC = "atp-snapshot"
SNAPSHOT_VERSION = 1
SNAPSHOT_SUFFIX = ".atpsnap"

#: manifest section order for a single-device snapshot.  A mesh
#: engine's snapshot (``EngineConfig.mesh_shards`` = N > 1) replaces
#: the one ``pools`` section with N ``pools.<s>`` sections — one
#: contiguous KV-head slice of every per-layer pool per shard, each
#: with its own CRC — and the manifest records ``shards``: N (absent
#: or 1 = the single-device layout).  Damage to ONE shard slice is
#: therefore detected per shard, and a migrating reader reassembles
#: the logical pools by concatenating the slices along the head dim.
SECTIONS = ("meta", "pools", "state", "requests")

_SNAP_RE = re.compile(r"^snap-(\d{8})\.atpsnap$")

_SAVES = obs.counter("engine.snapshot.saves",
                     "snapshot files written (atomic replace landed)")
_RESTORES = obs.counter("engine.snapshot.restores",
                        "engine restore attempts by outcome")
_CORRUPT = obs.counter("engine.snapshot.corrupt",
                       "snapshot validation failures (typed, recovered)")
_SAVE_MS = obs.histogram("engine.snapshot.save_ms",
                         "serialize + fsync-rename wall time per snapshot",
                         buckets=(1.0, 5.0, 10.0, 50.0, 100.0, 500.0,
                                  1000.0))
_BYTES = obs.histogram("engine.snapshot.bytes",
                       "snapshot file size",
                       buckets=(4096.0, 65536.0, 1048576.0, 16777216.0,
                                268435456.0))
_JOURNAL_LAG = obs.gauge("engine.snapshot.journal_lag",
                         "journal records accumulated since the last "
                         "snapshot (replay cost bound)")


def snapshot_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"snap-{step:08d}{SNAPSHOT_SUFFIX}")


def list_snapshots(directory: str) -> list[tuple[int, str]]:
    """``(step, path)`` pairs under ``directory``, ascending by step."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    out = []
    for name in names:
        m = _SNAP_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(out)


def _corrupt(path: str, why: str) -> SnapshotCorruptError:
    _CORRUPT.inc()
    return SnapshotCorruptError(f"{path}: {why}")


def _jbytes(o) -> bytes:
    return json.dumps(o, sort_keys=True, separators=(",", ":")).encode()


def _dtype_name(dtype) -> str:
    return np.dtype(dtype).name


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # extension dtypes (bfloat16 et al.) resolve through jnp
        return np.dtype(getattr(jnp, name))


def model_fingerprint(model) -> dict:
    """The architecture identity a snapshot is only valid against."""
    return {
        "vocab": int(model.vocab),
        "dim": int(model.dim),
        "depth": int(model.depth),
        "num_q_heads": int(model.num_q_heads),
        "num_kv_heads": int(model.num_kv_heads),
        "dtype": _dtype_name(model.dtype),
        "impl": str(model.impl),
    }


def _request_to_dict(req: Request, queue: str) -> dict:
    s = req.sampling
    return {
        "queue": queue,
        "request_id": req.request_id,
        "prompt": list(req.prompt),
        "sampling": {
            "max_tokens": s.max_tokens,
            "temperature": s.temperature,
            "top_k": s.top_k,
            "top_p": s.top_p,
            "seed": s.seed,
            "stop_token": s.stop_token,
        },
        "arrival": req.arrival,
        "seq": req.seq,
        "deadline_step": req.deadline_step,
        "state": req.state.value,
        "tokens": list(req.tokens),
        "output_tokens": list(req.output_tokens),
        "pending_token": req.pending_token,
        "computed_tokens": req.computed_tokens,
        "pages": list(req.pages),
        "prefix_cached_tokens": req.prefix_cached_tokens,
        "preemptions": req.preemptions,
        "first_scheduled_step": req.first_scheduled_step,
        "first_token_step": req.first_token_step,
        "finish_step": req.finish_step,
        # the request's trace tail rides the snapshot (obs/trace.py):
        # a warm restart or migration in a FRESH process reconstructs
        # the journey chain from this section alone.  Deterministic —
        # trace events carry only tick/step coordinates, never wall
        # time — so serialize() stays fingerprint-stable.
        "trace": _trace.events_of(req.request_id)
        if _trace.active() else [],
    }


def _request_from_dict(d: dict) -> Request:
    req = Request(
        request_id=d["request_id"],
        prompt=tuple(int(t) for t in d["prompt"]),
        sampling=SamplingParams(**d["sampling"]),
        arrival=d["arrival"],
        seq=d["seq"],
        deadline_step=d["deadline_step"],
    )
    # lifecycle position is restored, not re-derived: assign directly
    # (transition() validates client-visible edges, not resurrection)
    req.state = RequestState(d["state"])
    req.tokens = [int(t) for t in d["tokens"]]
    req.output_tokens = [int(t) for t in d["output_tokens"]]
    req.pending_token = d["pending_token"]
    req.computed_tokens = d["computed_tokens"]
    req.pages = [int(p) for p in d["pages"]]
    req.prefix_cached_tokens = d["prefix_cached_tokens"]
    req.preemptions = d["preemptions"]
    req.first_scheduled_step = d["first_scheduled_step"]
    req.first_token_step = d["first_token_step"]
    req.finish_step = d["finish_step"]
    return req


def _serialize_sections(engine: ServingEngine) -> list[tuple[str, bytes]]:
    # a snapshot cut must not capture a half-staged async step: settle
    # the double buffer (drop staged page-table rows, block until the
    # device pools are final) before reading any bytes out
    engine.quiesce()
    cfg = dataclasses.asdict(engine.config)
    if cfg["cache_dtype"] is not None:
        cfg["cache_dtype"] = _dtype_name(cfg["cache_dtype"])
    meta = {
        "config": cfg,
        "model": model_fingerprint(engine.model),
        "step": engine.current_step,
        "next_seq": engine._next_seq,
        "pool_dtype": _dtype_name(engine._k_pools[0].dtype),
        "pool_shape": list(engine._k_pools[0].shape),
    }
    shards = getattr(engine.config, "mesh_shards", 0) or 1
    hosted = [np.asarray(a) for a in (*engine._k_pools, *engine._v_pools)]
    if shards == 1:
        pool_sections = [("pools", b"".join(a.tobytes() for a in hosted))]
    else:
        # one section per head shard, each carrying that shard's
        # contiguous KV-head slice of every per-layer pool — exactly
        # the bytes the shard's device holds, CRC'd independently so
        # single-shard damage is a typed per-shard refusal
        hh = hosted[0].shape[1] // shards
        pool_sections = [
            (f"pools.{s}", b"".join(
                a[:, s * hh:(s + 1) * hh].tobytes() for a in hosted))
            for s in range(shards)
        ]
    alloc = engine.allocator
    sched = engine.scheduler
    state = {
        "free": [int(p) for p in engine.pool._free],
        "refs": [int(r) for r in engine.pool._refs],
        "watermark_pages": alloc.watermark_pages,
        "prefix": [
            {
                "key": list(e.key),
                "page": e.page,
                "parent": list(e.parent) if e.parent is not None else None,
                "children": sorted(list(c) for c in e.children),
                "last_use": e.last_use,
            }
            for _, e in sorted(alloc._prefix.items())
        ],
        "counters": {
            "prefix_hits": alloc.prefix_hits,
            "prefix_misses": alloc.prefix_misses,
            "prefix_hit_tokens": alloc.prefix_hit_tokens,
            "prefix_evictions": alloc.prefix_evictions,
        },
        "scheduler": {
            "token_budget": sched.token_budget,
            "prefix_admission": sched.prefix_admission,
            "num_preemptions": sched.num_preemptions,
        },
    }
    requests = (
        [_request_to_dict(r, "waiting") for r in sched.waiting]
        + [_request_to_dict(r, "running") for r in sched.running]
    )
    return [("meta", _jbytes(meta)), *pool_sections,
            ("state", _jbytes(state)), ("requests", _jbytes(requests))]


def _pool_section_names(shards: int) -> tuple[str, ...]:
    """The pool section names a ``shards``-way snapshot must carry."""
    if shards == 1:
        return ("pools",)
    return tuple(f"pools.{s}" for s in range(shards))


def serialize(engine: ServingEngine) -> bytes:
    """Deterministic snapshot bytes (manifest line + section payloads)."""
    sections = _serialize_sections(engine)
    shards = sum(1 for name, _ in sections
                 if name == "pools" or name.startswith("pools."))
    manifest = {
        "magic": SNAPSHOT_MAGIC,
        "version": SNAPSHOT_VERSION,
        "shards": shards,
        "sections": [
            {"name": name, "nbytes": len(payload),
             "crc32": zlib.crc32(payload)}
            for name, payload in sections
        ],
    }
    return (_jbytes(manifest) + b"\n"
            + b"".join(payload for _, payload in sections))


def state_fingerprint(engine: ServingEngine) -> str:
    """sha256 of the deterministic serialization — equal fingerprints
    mean byte-identical future outputs (wall-clock metrics excluded by
    construction)."""
    return hashlib.sha256(serialize(engine)).hexdigest()


def _fsync_dir(directory: str) -> None:
    """fsync a directory so a just-landed ``os.replace`` survives power
    loss (no-op on platforms without directory fds)."""
    try:
        dfd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def save(engine: ServingEngine, path: str) -> dict:
    """Write one snapshot durably and atomically (tmp in the target
    dir, fsync, ``os.replace``, fsync the directory); returns
    ``{path, nbytes, step}``."""
    t0 = time.perf_counter()
    blob = serialize(engine)
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
            f.flush()
            # a rename can land while the data blocks are still dirty:
            # without this fsync a power loss can leave the final path
            # holding an empty/partial file, and _prune may by then
            # have dropped the journals an older snapshot needs
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(directory)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _SAVES.inc()
    _SAVE_MS.observe((time.perf_counter() - t0) * 1e3)
    _BYTES.observe(float(len(blob)))
    return {"path": path, "nbytes": len(blob),
            "step": engine.current_step}


def _read_sections(path: str) -> tuple[dict, dict[str, bytes]]:
    """Parse + checksum every section; raises `SnapshotCorruptError`
    on any structural damage."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise _corrupt(path, f"unreadable: {e}")
    nl = blob.find(b"\n")
    if nl < 0:
        raise _corrupt(path, "no manifest line")
    try:
        manifest = json.loads(blob[:nl])
    except ValueError:
        raise _corrupt(path, "unparseable manifest")
    if not isinstance(manifest, dict) \
            or manifest.get("magic") != SNAPSHOT_MAGIC:
        raise _corrupt(path, "bad magic (not an engine snapshot)")
    if manifest.get("version") != SNAPSHOT_VERSION:
        raise _corrupt(
            path,
            f"unsupported snapshot version {manifest.get('version')!r} "
            f"(reader speaks {SNAPSHOT_VERSION})",
        )
    sections: dict[str, bytes] = {}
    offset = nl + 1
    try:
        entries = [(s["name"], int(s["nbytes"]), int(s["crc32"]))
                   for s in manifest["sections"]]
    except (KeyError, TypeError, ValueError):
        raise _corrupt(path, "malformed section table")
    for name, nbytes, crc in entries:
        payload = blob[offset:offset + nbytes]
        if len(payload) != nbytes:
            raise _corrupt(
                path,
                f"section {name!r} truncated "
                f"({len(payload)}/{nbytes} bytes)",
            )
        if zlib.crc32(payload) != crc:
            raise _corrupt(path, f"section {name!r} checksum mismatch")
        sections[name] = payload
        offset += nbytes
    if offset != len(blob):
        raise _corrupt(path, f"{len(blob) - offset} trailing bytes")
    shards = manifest.get("shards", 1)
    if not isinstance(shards, int) or isinstance(shards, bool) \
            or shards < 1:
        raise _corrupt(path, f"bad shards count {shards!r}")
    required = ("meta", *_pool_section_names(shards),
                "state", "requests")
    for name in required:
        if name not in sections:
            raise _corrupt(path, f"missing section {name!r}")
    return manifest, sections


def verify(path: str) -> list[str]:
    """Validation problems for one snapshot file ([] = valid).

    The CLI surface (`cli snapshot verify`); same checks as
    `restore` minus the model fingerprint (no model at hand)."""
    try:
        _, sections = _read_sections(path)
        for name in ("meta", "state", "requests"):
            json.loads(sections[name])
    except SnapshotError as e:
        return [str(e)]
    except ValueError as e:
        return [f"{path}: undecodable section payload: {e}"]
    return []


def inspect(path: str) -> dict:
    """Manifest + decoded summary for `cli snapshot inspect`."""
    problems = verify(path)
    out: dict = {"path": path, "valid": not problems,
                 "problems": problems}
    if problems:
        return out
    manifest, sections = _read_sections(path)
    meta = json.loads(sections["meta"])
    requests = json.loads(sections["requests"])
    out.update({
        "version": manifest["version"],
        "shards": manifest.get("shards", 1),
        "sections": manifest["sections"],
        "nbytes": os.path.getsize(path),
        "step": meta["step"],
        "model": meta["model"],
        "config": meta["config"],
        "requests": [
            {"request_id": r["request_id"], "queue": r["queue"],
             "state": r["state"],
             "output_tokens": len(r["output_tokens"]),
             # page count, not page ids: ids are engine-local and
             # meaningless to whoever reads the report (pre-PR-19
             # snapshots always carry the key, so no .get needed)
             "pages": len(r["pages"])}
            for r in requests
        ],
    })
    return out


def restore(path: str, model, params, *,
            on_token=None, on_finish=None,
            on_timeout=None) -> ServingEngine:
    """Reconstruct an engine whose subsequent outputs are byte-identical
    to the snapshotted one's.  Raises `SnapshotCorruptError` on any
    validation failure (the caller's cue to fall back cold)."""
    manifest, sections = _read_sections(path)
    try:
        meta = json.loads(sections["meta"])
        state = json.loads(sections["state"])
        requests = json.loads(sections["requests"])
    except ValueError as e:
        raise _corrupt(path, f"undecodable section payload: {e}")
    try:
        fp = model_fingerprint(model)
        if meta["model"] != fp:
            raise _corrupt(
                path,
                f"model fingerprint mismatch: snapshot "
                f"{meta['model']}, engine {fp}",
            )
        cfg = dict(meta["config"])
        if cfg.get("cache_dtype") is not None:
            cfg["cache_dtype"] = _np_dtype(cfg["cache_dtype"])
        config = EngineConfig(**cfg)
        try:
            engine = ServingEngine(model, params, config,
                                   on_token=on_token,
                                   on_finish=on_finish,
                                   on_timeout=on_timeout)
        except MeshConfigError as e:
            # the snapshot itself is fine — this HOST can't provide
            # the mesh geometry it was cut on.  Plain SnapshotError
            # (not ...Corrupt...) so recovery still falls back cold
            # without counting the file as damaged.
            raise SnapshotError(
                f"{path}: snapshot needs mesh geometry this host "
                f"cannot provide: {e}"
            )
        dtype = _np_dtype(meta["pool_dtype"])
        shape = tuple(meta["pool_shape"])
        n_arrays = 2 * model.depth
        nb = int(np.prod(shape)) * dtype.itemsize
        shards = manifest.get("shards", 1)
        if shape[1] % shards:
            raise _corrupt(
                path,
                f"pool head dim {shape[1]} not divisible by "
                f"{shards} shard section(s)",
            )
        # each pools.<s> section holds every per-layer array's slice
        # of 1/shards of the KV heads; reassembly concatenates the
        # slices back along the head dim (axis 1)
        slice_nb = nb // shards
        slice_shape = (shape[0], shape[1] // shards, *shape[2:])
        parts: list[list[np.ndarray]] = [[] for _ in range(n_arrays)]
        for name in _pool_section_names(shards):
            payload = sections[name]
            if len(payload) != n_arrays * slice_nb:
                raise _corrupt(
                    path,
                    f"section {name!r} holds {len(payload)} bytes, "
                    f"expected {n_arrays * slice_nb}",
                )
            for i in range(n_arrays):
                parts[i].append(np.frombuffer(
                    payload[i * slice_nb:(i + 1) * slice_nb],
                    dtype=dtype).reshape(slice_shape))
        arrays = [
            engine._place_pool(
                p[0] if shards == 1 else np.concatenate(p, axis=1))
            for p in parts
        ]
        engine._k_pools = arrays[:model.depth]
        engine._v_pools = arrays[model.depth:]

        engine.pool._free = [int(p) for p in state["free"]]
        engine.pool._refs = [int(r) for r in state["refs"]]
        alloc = engine.allocator
        alloc.watermark_pages = state["watermark_pages"]
        counters = state["counters"]
        alloc.prefix_hits = counters["prefix_hits"]
        alloc.prefix_misses = counters["prefix_misses"]
        alloc.prefix_hit_tokens = counters["prefix_hit_tokens"]
        alloc.prefix_evictions = counters["prefix_evictions"]
        alloc._prefix = {}
        for e in state["prefix"]:
            key = tuple(int(t) for t in e["key"])
            alloc._prefix[key] = _PrefixEntry(
                key=key,
                page=int(e["page"]),
                parent=(tuple(int(t) for t in e["parent"])
                        if e["parent"] is not None else None),
                children={tuple(int(t) for t in c)
                          for c in e["children"]},
                last_use=int(e["last_use"]),
            )
        sched_state = state["scheduler"]
        engine.scheduler.token_budget = sched_state["token_budget"]
        engine.scheduler.prefix_admission = \
            sched_state["prefix_admission"]
        engine.scheduler.num_preemptions = \
            sched_state["num_preemptions"]

        for d in requests:
            req = _request_from_dict(d)
            if d["queue"] == "waiting":
                engine.scheduler.waiting.append(req)
            else:
                engine.scheduler.running.append(req)
            # splice the snapshotted trace tail back into the live
            # store (idempotent: in-process restores already hold it)
            _trace.adopt(req.request_id, d.get("trace", []))
            # wall-clock bookkeeping restarts at restore (TTFT history
            # is observability, not contract)
            engine._wall[req.request_id] = {"added": time.perf_counter()}
            if req.sampling.temperature > 0.0 and req.output_tokens:
                # arithmetic RNG-chain reconstruction: one split per
                # sampled token, the resume_request contract
                key = jax.random.PRNGKey(req.sampling.seed)
                for _ in range(len(req.output_tokens)):
                    key, _ = jax.random.split(key)
                engine._rng_keys[req.request_id] = key
        engine._step = meta["step"]
        engine._next_seq = meta["next_seq"]
    except (KeyError, TypeError, ValueError) as e:
        # CRC-valid but structurally unusable (e.g. a snapshot written
        # by a buggy/foreign writer): still a typed refusal, not a crash
        raise _corrupt(path, f"malformed snapshot contents: {e!r}")
    _RESTORES.inc(outcome="ok")
    return engine


def recover_engine(model, params, directory: str, *,
                   on_token=None, on_finish=None,
                   on_timeout=None) -> tuple[ServingEngine, dict]:
    """Warm recovery: newest valid snapshot + journal replay.

    Scans ``directory`` newest-first, restores the first snapshot that
    validates, then chain-replays every journal at or after that step
    (rotation closes a journal only after the *next* snapshot lands,
    so the chain is complete even when the newest snapshot is the
    corrupt one).  Raises `SnapshotCorruptError` when nothing under
    ``directory`` validates — the caller's cue for the cold path."""
    snaps = list_snapshots(directory)
    skipped: list[dict] = []
    engine = None
    chosen = -1
    chosen_path = None
    for step, path in reversed(snaps):
        try:
            engine = restore(path, model, params, on_token=on_token,
                             on_finish=on_finish, on_timeout=on_timeout)
            chosen, chosen_path = step, path
            break
        except SnapshotError as e:
            skipped.append({"path": path, "error": str(e)})
    if engine is None:
        _RESTORES.inc(outcome="cold_fallback")
        raise SnapshotCorruptError(
            f"{directory}: no valid snapshot among {len(snaps)} "
            f"candidate(s): "
            + (skipped[-1]["error"] if skipped else "directory empty")
        )
    events: list[dict] = []
    for jstep, jpath in list_journals(directory):
        if jstep >= chosen:
            events.extend(Journal.read(jpath))
    replayed = apply_journal(engine, events)
    _RESTORES.inc(outcome="warm")
    return engine, {
        "snapshot_step": chosen,
        "snapshot_path": chosen_path,
        "journal_events": replayed,
        "skipped": skipped,
    }


class SnapshotManager:
    """Periodic snapshotting + journal rotation for one engine.

    Wraps ``engine.step`` by instance-attribute assignment (the same
    composition pattern as `chaos.FaultInjector`, so the two stack) to
    snapshot every ``every`` steps, attaches the write-ahead
    `Journal`, and writes a genesis snapshot at attach so recovery
    always has a base.  Keeps the ``keep`` newest snapshots plus every
    journal needed to chain-replay from the oldest kept one.

    Attach starts a new INCARNATION: every ``snap-*``/``journal-*``
    (and torn ``.tmp``) left by a previous manager of this directory
    is deleted before the genesis lands.  The genesis is a full state
    cut, so those files are pure supersession debris — and because
    their names are keyed by step, leaving them would poison recovery:
    a dead incarnation's journal replays records the genesis already
    contains (duplicated tokens), and after a cold restart its
    higher-step snapshots would outrank the genesis and resurrect
    pre-restart state.  Clearing first keeps every crash window of
    attach safe: a kill before the genesis lands degrades to a cold
    recovery, never to wrong tokens.

    ``crash_next`` is the chaos crash-point: when armed, the next save
    dies "mid-write" — a partial ``.tmp`` file is left behind and the
    final path is never touched, proving the atomic-replace discipline
    (recovery must not even notice).
    """

    def __init__(self, engine: ServingEngine, directory: str, *,
                 every: int = 16, keep: int = 3):
        if every < 1 or keep < 1:
            raise SnapshotError(
                f"SnapshotManager needs every>=1, keep>=1 "
                f"(got every={every}, keep={keep})"
            )
        os.makedirs(directory, exist_ok=True)
        self.engine = engine
        self.directory = directory
        self.every = every
        self.keep = keep
        self.crash_next = False
        self.saves = 0
        self.last_snapshot_step = -1
        self._inner_step = engine.step
        engine.step = self._step
        self._clear_stale()
        # the genesis snapshot() below owns journal creation (rotation
        # after the snapshot lands), so nothing is journaled — and the
        # lag gauge reads 0 — until recovery has a base to extend
        engine.journal = None
        self.snapshot()

    def _clear_stale(self) -> None:
        """Delete a dead incarnation's files (see class docstring)."""
        stale = [p for _, p in list_snapshots(self.directory)]
        stale += [p for _, p in list_journals(self.directory)]
        stale += [os.path.join(self.directory, name)
                  for name in os.listdir(self.directory)
                  if name.endswith(".tmp")]
        for path in stale:
            try:
                os.unlink(path)
            except OSError:
                pass

    def _step(self):
        metrics = self._inner_step()
        if self.engine.current_step % self.every == 0:
            self.snapshot()
        return metrics

    def snapshot(self) -> str | None:
        """Take one snapshot now; returns its path (None when the
        armed crash-point fired instead)."""
        engine = self.engine
        step = engine.current_step
        if obs.enabled():
            _JOURNAL_LAG.set(float(engine.journal.records_written)
                             if engine.journal is not None else 0.0)
        if self.crash_next:
            self.crash_next = False
            blob = serialize(engine)
            fd, tmp = tempfile.mkstemp(dir=self.directory,
                                       suffix=".tmp")
            # deliberately torn: simulates the process dying mid-write;
            # the final snapshot path is never touched
            with os.fdopen(fd, "wb") as f:  # atp: disable=ATP701
                f.write(blob[: max(1, len(blob) // 2)])
            return None
        path = snapshot_path(self.directory, step)
        save(engine, path)
        # rotate AFTER the snapshot lands (the genesis call creates the
        # incarnation's first journal): the outgoing journal file stays
        # complete on disk, so replay can chain from an older snapshot
        # if this one is later damaged.  Close the outgoing handle —
        # the file is immutable history from here on.
        if engine.journal is not None:
            engine.journal.close()
        engine.journal = Journal(journal_path(self.directory, step),
                                 snapshot_step=step)
        self.saves += 1
        self.last_snapshot_step = step
        self._prune()
        return path

    def _prune(self) -> None:
        snaps = list_snapshots(self.directory)
        drop = snaps[:-self.keep] if len(snaps) > self.keep else []
        for _, path in drop:
            try:
                os.unlink(path)
            except OSError:
                pass
        oldest_kept = snaps[-self.keep][0] if len(snaps) >= self.keep \
            else (snaps[0][0] if snaps else 0)
        for jstep, jpath in list_journals(self.directory):
            if jstep < oldest_kept:
                try:
                    os.unlink(jpath)
                except OSError:
                    pass

    def detach(self) -> None:
        """Unhook from the engine: step unwrapped, the journal's
        append handle closed and dropped.  `ReplicaHandle.kill` calls
        this so a kill/restart storm cannot leak file descriptors
        (pinned by the ResourceWarning test in tests/
        test_supervisor.py).  Idempotent."""
        self.engine.step = self._inner_step
        if self.engine.journal is not None:
            self.engine.journal.close()
        self.engine.journal = None
