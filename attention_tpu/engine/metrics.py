"""Serving metrics: per-step counters and per-request latency records.

The observability layer the ROADMAP's "serve heavy traffic" goal needs:
every engine step emits a `StepMetrics` row (batch composition, queue
depth, page utilization, cumulative prefix-cache and preemption
counters) and every finished request a `RequestMetrics` row (TTFT,
TPOT, prefix reuse, preemption count).  Both are plain dataclasses with
``to_dict``/JSON helpers; :meth:`EngineMetrics.to_run_record` folds the
aggregate into a `utils.profiling.RunRecord` so engine runs land in the
same JSONL streams (`profiling.append_jsonl`) as every kernel
benchmark.

These rows are also re-emitted through the unified telemetry registry
(`attention_tpu.obs`): every recorded step updates the ``engine.*``
counters/gauges/histograms and every RunRecord goes through
``obs.record_run`` — so ``cli obs report``/``obs.prom_text()`` show
engine state alongside op-dispatch and tuning counters.  Emission is
no-op while telemetry is disabled (the default); these dataclasses
stay the source of truth for the deterministic per-run JSON.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any

from attention_tpu import obs
from attention_tpu.obs.naming import (
    SERIES_ENGINE_TPOT_DIGEST,
    SERIES_ENGINE_TTFT_DIGEST,
)
from attention_tpu.obs.quantile import QuantileDigest
from attention_tpu.utils.profiling import RunRecord

_STEPS = obs.counter("engine.steps.total", "engine steps recorded")
_DECODE_TOKENS = obs.counter("engine.tokens.decode",
                             "decode tokens scheduled")
_PREFILL_TOKENS = obs.counter("engine.tokens.prefill",
                              "real prefill tokens scheduled")
_FINISHED = obs.counter("engine.requests.finished", "requests finished")
_QUEUE = obs.gauge("engine.queue.depth", "waiting requests after step")
_RUNNING = obs.gauge("engine.queue.running", "running requests after step")
_PAGES_USED = obs.gauge("engine.pages.used", "pool pages in use")
_PAGES_FREE = obs.gauge("engine.pages.free", "pool pages free")
_STEP_WALL = obs.histogram("engine.step.wall_ms", "engine step wall ms",
                           buckets=(0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25,
                                    50, 100, 250, 500, 1000))
_TTFT = obs.histogram("engine.request.ttft_steps",
                      "steps from arrival to first token",
                      buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
_TPOT = obs.histogram("engine.request.tpot_steps",
                      "mean steps per output token after the first",
                      buckets=(1, 1.5, 2, 3, 4, 8, 16, 32))
_PAD_TOKENS = obs.counter(
    "engine.step.pad_tokens",
    "pad tokens dispatched (packed/padded width minus real tokens)")
_RAGGED_OCC = obs.gauge(
    "engine.step.ragged_occupancy",
    "real-token fraction of the last non-empty step's launch width")
_TTFT_DIG = obs.digest(SERIES_ENGINE_TTFT_DIGEST,
                       "TTFT quantile digest (engine steps)")
_TPOT_DIG = obs.digest(SERIES_ENGINE_TPOT_DIGEST,
                       "TPOT quantile digest (steps/token)")


@dataclasses.dataclass
class StepMetrics:
    """One scheduler/engine step."""

    step: int
    wall_s: float = 0.0
    num_decode_reqs: int = 0
    num_prefill_reqs: int = 0
    decode_tokens: int = 0
    prefill_tokens: int = 0          # real prompt tokens (pads excluded)
    queue_depth: int = 0             # waiting (incl. preempted) after step
    running: int = 0
    admitted: int = 0
    preempted: int = 0
    finished: int = 0
    timed_out: int = 0               # deadline-sweep expiries this step
    free_pages: int = 0
    used_pages: int = 0
    page_utilization: float = 0.0
    prefix_hit_tokens_total: int = 0  # cumulative
    preemptions_total: int = 0        # cumulative
    pad_tokens: int = 0              # pads dispatched this step
    baseline_pad_tokens: int = 0     # what the two-call lowering pads
    ragged_occupancy: float = 0.0    # real / dispatched width
    host_overhead_s: float = 0.0     # wall minus the logits device sync

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict())


@dataclasses.dataclass
class RequestMetrics:
    """One finished request.  Step-denominated latencies are exact and
    deterministic (the unit of serving time is the engine step);
    wall-clock figures ride along for throughput reporting."""

    request_id: str
    arrival_step: int
    first_scheduled_step: int
    first_token_step: int
    finish_step: int
    prompt_tokens: int
    output_tokens: int
    prefix_cached_tokens: int
    preemptions: int
    ttft_s: float
    finish_s: float

    @property
    def ttft_steps(self) -> int:
        return self.first_token_step - self.arrival_step

    @property
    def tpot_steps(self) -> float:
        """Mean steps per output token after the first."""
        if self.output_tokens <= 1:
            return 0.0
        return (self.finish_step - self.first_token_step) \
            / (self.output_tokens - 1)

    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["ttft_steps"] = self.ttft_steps
        d["tpot_steps"] = round(self.tpot_steps, 3)
        return d


class EngineMetrics:
    """Collects step and request rows over an engine's lifetime."""

    def __init__(self):
        self.steps: list[StepMetrics] = []
        self.requests: list[RequestMetrics] = []
        self._t0 = time.perf_counter()

    def record_step(self, m: StepMetrics) -> None:
        self.steps.append(m)
        if obs.enabled():
            _STEPS.inc()
            if m.decode_tokens:
                _DECODE_TOKENS.inc(m.decode_tokens)
            if m.prefill_tokens:
                _PREFILL_TOKENS.inc(m.prefill_tokens)
            _QUEUE.set(m.queue_depth)
            _RUNNING.set(m.running)
            _PAGES_USED.set(m.used_pages)
            _PAGES_FREE.set(m.free_pages)
            _STEP_WALL.observe(m.wall_s * 1e3)
            if m.pad_tokens:
                _PAD_TOKENS.inc(m.pad_tokens)
            if m.decode_tokens or m.prefill_tokens:
                _RAGGED_OCC.set(m.ragged_occupancy)

    def record_request(self, m: RequestMetrics) -> None:
        self.requests.append(m)
        if obs.enabled():
            _FINISHED.inc()
            _TTFT.observe(m.ttft_steps)
            _TTFT_DIG.observe(m.ttft_steps)
            if m.output_tokens > 1:
                _TPOT.observe(m.tpot_steps)
                _TPOT_DIG.observe(m.tpot_steps)

    def latency_digests(self) -> tuple[QuantileDigest, QuantileDigest]:
        """(ttft, tpot) digests rebuilt from the deterministic request
        rows — works with telemetry disabled, so summaries never depend
        on the obs flag."""
        ttft, tpot = QuantileDigest(), QuantileDigest()
        for r in self.requests:
            ttft.add(max(r.ttft_steps, 0))
            if r.output_tokens > 1:
                tpot.add(r.tpot_steps)
        return ttft, tpot

    def summary(self) -> dict[str, Any]:
        wall = time.perf_counter() - self._t0
        out_tokens = sum(r.output_tokens for r in self.requests)
        prompt_tokens = sum(r.prompt_tokens for r in self.requests)
        cached = sum(r.prefix_cached_tokens for r in self.requests)
        ttfts = [r.ttft_steps for r in self.requests]
        tpots = [r.tpot_steps for r in self.requests if r.output_tokens > 1]
        busy = [s for s in self.steps if s.decode_tokens or s.prefill_tokens]
        mixed = [s for s in busy if s.decode_tokens and s.prefill_tokens]
        ttft_dig, tpot_dig = self.latency_digests()
        return {
            "num_requests": len(self.requests),
            "num_steps": len(self.steps),
            "wall_s": round(wall, 4),
            "prompt_tokens": prompt_tokens,
            "output_tokens": out_tokens,
            "tokens_per_s": round(out_tokens / wall, 2) if wall else 0.0,
            "prefix_cached_tokens": cached,
            "prefix_cache_hit_rate": round(
                cached / prompt_tokens, 4) if prompt_tokens else 0.0,
            "mean_ttft_steps": round(
                sum(ttfts) / len(ttfts), 2) if ttfts else 0.0,
            "max_ttft_steps": max(ttfts) if ttfts else 0,
            # digest-backed quantiles (bounded relative error, not the
            # fixed Prometheus buckets) — the SLO accounting surface
            "ttft_p50_steps": round(ttft_dig.quantile(0.5), 3),
            "ttft_p99_steps": round(ttft_dig.quantile(0.99), 3),
            "mean_tpot_steps": round(
                sum(tpots) / len(tpots), 3) if tpots else 0.0,
            "tpot_p50_steps": round(tpot_dig.quantile(0.5), 3),
            "tpot_p99_steps": round(tpot_dig.quantile(0.99), 3),
            "mixed_batch_steps": len(mixed),
            "mean_batched_tokens_per_step": round(
                sum(s.decode_tokens + s.prefill_tokens for s in busy)
                / len(busy), 2) if busy else 0.0,
            "peak_page_utilization": round(
                max((s.page_utilization for s in self.steps), default=0.0),
                4),
            "preemptions": self.steps[-1].preemptions_total
            if self.steps else 0,
            "pad_tokens_total": sum(s.pad_tokens for s in self.steps),
            "baseline_pad_tokens_total": sum(
                s.baseline_pad_tokens for s in self.steps),
            "mean_ragged_occupancy": round(
                sum(s.ragged_occupancy for s in busy) / len(busy), 4)
            if busy else 0.0,
            "mean_host_overhead_ms": round(
                sum(s.host_overhead_s for s in busy) * 1e3 / len(busy),
                3) if busy else 0.0,
        }

    def to_run_record(self, *, config: str = "engine-serve",
                      backend: str = "engine",
                      extra: dict[str, Any] | None = None) -> RunRecord:
        """The aggregate as a `RunRecord` (the repo's uniform benchmark
        row).  m/n carry prompt/output token totals; the serving-
        specific detail rides in ``extra``."""
        import jax

        s = self.summary()
        per_tok_us = (s["wall_s"] * 1e6 / s["output_tokens"]
                      if s["output_tokens"] else 0.0)
        try:
            dev = jax.devices()[0]
            device_kind, n_dev = dev.device_kind, jax.device_count()
        except Exception:  # noqa: BLE001 - metrics must not need a device
            device_kind, n_dev = "unknown", 0
        record = RunRecord(
            config=config,
            backend=backend,
            m=s["prompt_tokens"],
            n=s["output_tokens"],
            dk=0,
            dv=0,
            dtype="",
            best_us=round(per_tok_us, 2),
            median_us=round(per_tok_us, 2),
            gflops_per_chip=0.0,
            utilization=0.0,
            device_kind=device_kind,
            n_devices=n_dev,
            extra={**s, **(extra or {})},
        )
        obs.record_run(record)
        return record
