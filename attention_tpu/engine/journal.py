"""Write-ahead journal: the delta between snapshots.

A snapshot (`engine/snapshot.py`) is a consistent cut of the full
engine state; the journal records everything that *changes* the
client-visible contract after that cut — admissions, cancellations,
deadline expiries, and every emitted token — so recovery is

    latest valid snapshot  +  journal replay  =  the crashed engine,

with recovery cost bounded by snapshot lag instead of total live
context (the cold path re-prefills every in-flight prompt from
scratch; see `frontend.ReplicaHandle.restart`).

Format: append-only JSONL, one record per line, each carrying a
``crc`` of its own canonical serialization.  Append-only is what makes
the *record* path crash-safe without the tmp+``os.replace`` idiom the
snapshot needs (ATP701 in `analysis/durability.py` enforces exactly
this split): a crash can tear at most the final line, and
:meth:`Journal.read` stops at the first record that fails to parse or
checksum — the valid prefix is used, a torn tail is silently dropped,
never an exception.  The file itself, though, is created FRESH and
atomically (tmp + ``os.replace`` of the ``begin`` record): a journal
extends exactly the snapshot it is named for, so a same-named file
left by a dead incarnation holds records already baked into that
snapshot — appending across incarnations would replay them twice.
Files are named ``journal-<step:08d>.wal`` after the snapshot step
they extend and are rotated by `SnapshotManager` *after* the next
snapshot lands, so a corrupt newest snapshot can still chain-replay
from an older one through the complete journals in between.

Replay (`apply_journal`) applies the *net effect* per request rather
than re-executing events: requests that reached a terminal state after
the snapshot are dropped; snapshot-live requests that emitted tokens
are rewound onto the resume invariant (all emitted tokens fed back
except the newest, which waits in ``pending_token``) with
``computed_tokens`` held at the snapshot value — the KV appended after
the cut died with the process, so the chunked prefill-continuation
path recomputes it; post-snapshot admissions re-enter through
``add_request``/``resume_request``.  RNG chains are rebuilt
arithmetically from the token count, so sampled continuations stay
token-identical to an uninterrupted run.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import zlib

import jax

from attention_tpu.engine.errors import (
    DeadlineExceededError,
    SnapshotError,
)
from attention_tpu.engine.request import RequestState, SamplingParams

JOURNAL_SUFFIX = ".wal"

_JOURNAL_RE = re.compile(r"^journal-(\d{8})\.wal$")

#: record kinds replay understands; anything else is skipped (forward
#: compatibility: an old reader ignores kinds a newer writer adds)
RECORD_KINDS = ("begin", "admit", "token", "cancel", "finish", "timeout")


def journal_path(directory: str, step: int) -> str:
    """The journal file extending the snapshot taken at ``step``."""
    return os.path.join(directory, f"journal-{step:08d}{JOURNAL_SUFFIX}")


def list_journals(directory: str) -> list[tuple[int, str]]:
    """``(snapshot_step, path)`` pairs, ascending by step."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    out = []
    for name in names:
        m = _JOURNAL_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(out)


def _canonical(rec: dict) -> str:
    return json.dumps(rec, sort_keys=True, separators=(",", ":"))


def _record_line(rec: dict) -> bytes:
    crc = zlib.crc32(_canonical(rec).encode())
    return (_canonical({**rec, "crc": crc}) + "\n").encode()


class Journal:
    """Append-only record stream attached to one `ServingEngine`.

    The engine calls the ``record_*`` hooks (guarded on
    ``engine.journal is not None``, so the no-durability path costs one
    attribute check per event).  Appends go through ONE long-lived
    ``"ab"`` handle, flushed per record so readers of the path always
    see every completed line; the only torn state a crash can leave is
    the final line.  The handle's lifetime is explicit: ``close()`` is
    called by `SnapshotManager` on journal rotation and on ``detach``
    (which `ReplicaHandle.kill` invokes), so a kill/restart storm
    leaks neither file descriptors nor ResourceWarnings.
    """

    def __init__(self, path: str, *, snapshot_step: int):
        self.path = path
        self.snapshot_step = snapshot_step
        self.records_written = 0
        # The journal extends the snapshot just taken at
        # ``snapshot_step``: a same-named file on disk belongs to a
        # dead incarnation and its records are already baked into that
        # snapshot, so the file is created fresh — atomically, via a
        # sibling temp + os.replace, never truncate-in-place — and a
        # crash here leaves either no journal (reads as empty) or a
        # complete begin record, never a stale or torn head.
        directory = os.path.dirname(os.path.abspath(path))
        fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(_record_line({"kind": "begin",
                                      "snapshot_step": snapshot_step}))
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self.records_written = 1
        # O_APPEND handle: a concurrent truncate (the chaos
        # journal_tear point) cannot strand the write position
        self._file = open(path, "ab")

    @property
    def closed(self) -> bool:
        return self._file is None

    def close(self) -> None:
        """Release the append handle.  Idempotent; appending to a
        closed journal is a typed error (the engine's ``journal``
        reference must be dropped alongside)."""
        if self._file is not None:
            self._file.close()
            self._file = None

    def _append(self, rec: dict) -> None:
        if self._file is None:
            raise SnapshotError(
                f"journal {self.path} is closed (detached manager or "
                "rotated-out file); records must not land here"
            )
        self._file.write(_record_line(rec))
        self._file.flush()
        self.records_written += 1

    def record_admit(self, req) -> None:
        s = req.sampling
        self._append({
            "kind": "admit",
            "id": req.request_id,
            "prompt": list(req.prompt),
            "sampling": {
                "max_tokens": s.max_tokens,
                "temperature": s.temperature,
                "top_k": s.top_k,
                "top_p": s.top_p,
                "seed": s.seed,
                "stop_token": s.stop_token,
            },
            "arrival": req.arrival,
            "deadline_step": req.deadline_step,
            # non-empty for resume_request: the already-streamed tokens
            # the re-prefill feeds back
            "outputs": list(req.output_tokens),
        })

    def record_token(self, request_id: str, token: int) -> None:
        self._append({"kind": "token", "id": request_id,
                      "token": int(token)})

    def record_cancel(self, request_id: str) -> None:
        self._append({"kind": "cancel", "id": request_id})

    def record_finish(self, request_id: str) -> None:
        self._append({"kind": "finish", "id": request_id})

    def record_timeout(self, request_id: str) -> None:
        self._append({"kind": "timeout", "id": request_id})

    @staticmethod
    def read(path: str) -> list[dict]:
        """Every valid record from the head of ``path``.

        Missing file reads as empty; reading stops at the first line
        that fails to parse or checksum (append-only means only the
        tail can tear, so everything after a bad line is the same
        crash's debris).
        """
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return []
        records: list[dict] = []
        for line in raw.split(b"\n"):
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                break
            if not isinstance(rec, dict) or "crc" not in rec:
                break
            crc = rec.pop("crc")
            if zlib.crc32(_canonical(rec).encode()) != crc:
                break
            records.append(rec)
        return records


def _stream_done(outputs: list[int], sampling: SamplingParams) -> bool:
    """Mirror of `Request.emit`'s stop conditions on a raw token list."""
    if not outputs:
        return False
    return (len(outputs) >= sampling.max_tokens
            or (sampling.stop_token is not None
                and outputs[-1] == sampling.stop_token))


def apply_journal(engine, events: list[dict]) -> int:
    """Replay journal ``events`` onto a freshly restored engine.

    Net-effect replay in three deterministic passes (each in first-
    appearance order): terminal requests are dropped, snapshot-live
    requests are rewound onto the resume invariant, post-snapshot
    admissions re-enter through the normal intake paths.  No client
    callbacks fire — every journaled token was already streamed before
    the crash.  Returns the number of events applied.
    """
    sched = engine.scheduler
    live = {r.request_id: r for r in (*sched.waiting, *sched.running)}
    admits: dict[str, dict] = {}
    post: dict[str, list[int]] = {}
    ended: set[str] = set()
    order: list[str] = []
    applied = 0
    for ev in events:
        kind = ev.get("kind")
        rid = ev.get("id")
        if kind == "begin" or rid is None:
            continue
        applied += 1
        if rid not in order:
            order.append(rid)
        if kind == "admit":
            admits[rid] = ev
            post[rid] = []
            ended.discard(rid)
        elif kind == "token":
            post.setdefault(rid, []).append(int(ev["token"]))
        elif kind in ("cancel", "finish", "timeout"):
            ended.add(rid)

    # pass 1: drop every request that reached a terminal state after
    # the snapshot — its stream was fully delivered (or deliberately
    # ended) before the crash, so the snapshot copy is stale
    for rid in order:
        if rid in ended and rid in live:
            engine.cancel(rid)
            live.pop(rid)

    # pass 2: rewind snapshot-live requests that emitted tokens after
    # the cut
    for rid in order:
        if rid in ended or rid in admits:
            continue
        req = live.get(rid)
        toks = post.get(rid)
        if req is None or not toks:
            continue
        outs = list(req.output_tokens) + toks
        if _stream_done(outs, req.sampling):
            # finished before the crash; only the finish record tore off
            engine.cancel(rid)
            continue
        req.tokens = list(req.prompt) + outs[:-1]
        req.output_tokens = outs
        req.pending_token = outs[-1]
        # the KV behind the journaled tail died with the process: hold
        # computed_tokens at the snapshot value and fall back to
        # chunked prefill continuation to recompute it
        req.computed_tokens = min(req.computed_tokens, len(req.tokens))
        if (req.computed_tokens < len(req.tokens)
                and req.state is RequestState.DECODING):
            # recovery-time surgery, not a client-visible lifecycle
            # edge — assign directly instead of transition()
            req.state = RequestState.PREFILLING
        if req.sampling.temperature > 0.0:
            key = jax.random.PRNGKey(req.sampling.seed)
            for _ in range(len(outs)):
                key, _ = jax.random.split(key)
            engine._rng_keys[rid] = key

    # pass 3: re-admit post-snapshot arrivals still live at the crash
    for rid in order:
        if rid not in admits or rid in ended:
            continue
        ev = admits[rid]
        if rid in live:
            # the id was re-admitted after its snapshot-live copy ended
            # without a journaled terminal record (torn tail): the
            # admit record is the fresher truth
            engine.cancel(rid)
        sampling = SamplingParams(**(ev.get("sampling") or {}))
        outs = list(ev.get("outputs") or []) + post.get(rid, [])
        if _stream_done(outs, sampling):
            continue
        try:
            if outs:
                engine.resume_request(
                    ev["prompt"], sampling, request_id=rid,
                    output_tokens=outs, arrival=ev.get("arrival"),
                    deadline_step=ev.get("deadline_step"),
                )
            else:
                engine.add_request(
                    ev["prompt"], sampling, request_id=rid,
                    arrival=ev.get("arrival"),
                    deadline_step=ev.get("deadline_step"),
                )
        except DeadlineExceededError:
            # expired relative to the restored step; the owner's own
            # deadline/retry machinery already saw the original expiry
            pass
    return applied
