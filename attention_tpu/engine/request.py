"""Request objects and lifecycle for the continuous-batching engine.

A request is the unit of serving work: a prompt, per-request sampling
parameters, and the bookkeeping the scheduler/allocator need (owned
pages, how many tokens have committed KV, the not-yet-fed pending
token).  The lifecycle is a small explicit state machine —

    WAITING -> PREFILLING -> DECODING -> FINISHED
       ^           |            |
       |        PREEMPTED <-----+
       +-----------+   (requeued; recompute on readmission)

plus three terminal exits reachable from every non-terminal state:
CANCELLED (client gone), TIMED_OUT (deadline expired — the resilient
front end's TTL enforcement, checked at every engine step), and the
front-end-only SHED (admission control refused the request before it
ever touched an engine).

— and every transition goes through :meth:`Request.transition`, which
rejects illegal edges loudly (a request decoding before its prefill
finished is exactly the kind of bug that otherwise surfaces three
layers down as a poisoned page append).
"""

from __future__ import annotations

import dataclasses
import enum


class RequestState(enum.Enum):
    WAITING = "waiting"
    PREFILLING = "prefilling"
    DECODING = "decoding"
    FINISHED = "finished"
    PREEMPTED = "preempted"
    CANCELLED = "cancelled"
    TIMED_OUT = "timed_out"
    SHED = "shed"


#: the states a request can never leave — exactly the set the
#: resilience invariant pins: every admitted request ends in ONE of
#: FINISHED / CANCELLED / TIMED_OUT / SHED
TERMINAL_STATES = frozenset({
    RequestState.FINISHED, RequestState.CANCELLED,
    RequestState.TIMED_OUT, RequestState.SHED,
})

# legal lifecycle edges; PREFILLING -> FINISHED covers max_tokens == 1
# (the first token is sampled at prefill completion and already ends
# the request).  CANCELLED is reachable from every non-terminal state
# (`ServingEngine.cancel` — a client abandoning the request), and
# TIMED_OUT likewise (the engine's per-step deadline sweep); both are
# terminal like FINISHED.  SHED is the front end's admission refusal,
# so it is only reachable from WAITING — a request that has touched an
# engine is past the shedding gate.
_TRANSITIONS: dict[RequestState, frozenset[RequestState]] = {
    RequestState.WAITING: frozenset(
        {RequestState.PREFILLING, RequestState.CANCELLED,
         RequestState.TIMED_OUT, RequestState.SHED}
    ),
    RequestState.PREFILLING: frozenset(
        {RequestState.DECODING, RequestState.FINISHED,
         RequestState.PREEMPTED, RequestState.CANCELLED,
         RequestState.TIMED_OUT}
    ),
    RequestState.DECODING: frozenset(
        {RequestState.FINISHED, RequestState.PREEMPTED,
         RequestState.CANCELLED, RequestState.TIMED_OUT}
    ),
    RequestState.PREEMPTED: frozenset(
        {RequestState.PREFILLING, RequestState.CANCELLED,
         RequestState.TIMED_OUT}
    ),
    RequestState.FINISHED: frozenset(),
    RequestState.CANCELLED: frozenset(),
    RequestState.TIMED_OUT: frozenset(),
    RequestState.SHED: frozenset(),
}


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs — the same contract as
    `models.decode.generate` (temperature 0 = greedy argmax; top-k /
    top-p require temperature > 0), plus the serving-side stop
    conditions (``max_tokens``, optional ``stop_token``).  ``seed``
    keys the request's own PRNG chain, so a request's sampled stream
    is reproducible regardless of what else is in the batch."""

    max_tokens: int = 16
    temperature: float = 0.0
    top_k: int | None = None
    top_p: float | None = None
    seed: int = 0
    stop_token: int | None = None

    def validate(self, vocab: int) -> None:
        if self.max_tokens < 1:
            raise ValueError(
                f"max_tokens must be >= 1, got {self.max_tokens}"
            )
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}"
            )
        if self.temperature == 0.0 and (
            self.top_k is not None or self.top_p is not None
        ):
            raise ValueError(
                "top_k/top_p require temperature > 0 (temperature == 0 "
                "is greedy argmax)"
            )
        if self.top_p is not None and not (0.0 < self.top_p <= 1.0):
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.top_k is not None and not (1 <= self.top_k <= vocab):
            raise ValueError(
                f"top_k must be in [1, vocab={vocab}], got {self.top_k}"
            )


@dataclasses.dataclass
class Request:
    """One serving request plus its engine-side bookkeeping.

    ``tokens`` is the KV-bearing token sequence: the prompt, extended by
    each generated token *as it is fed back* into the model.  The last
    emitted token waits in ``pending_token`` until its decode step feeds
    it (and is never fed at all if it ends the request) — mirroring
    `generate_paged`, which emits ``steps`` tokens but appends only
    ``steps - 1`` of them.  ``computed_tokens`` counts how many of
    ``tokens`` have KV committed to pages; preemption-by-recompute
    resets it to 0 while keeping ``tokens``/``pending_token``, so the
    resumed request re-prefills its whole sequence and continues
    WITHOUT resampling anything already streamed out.
    """

    request_id: str
    prompt: tuple[int, ...]
    sampling: SamplingParams
    arrival: int = 0  # engine step at which the request becomes visible
    seq: int = 0      # admission tiebreak: FCFS is (arrival, seq)
    # engine step at which the request expires (None = no deadline):
    # the deadline sweep at the top of every `ServingEngine.step` times
    # out any request whose deadline_step <= the current step
    deadline_step: int | None = None

    state: RequestState = RequestState.WAITING
    tokens: list[int] = dataclasses.field(default_factory=list)
    output_tokens: list[int] = dataclasses.field(default_factory=list)
    pending_token: int | None = None
    computed_tokens: int = 0
    pages: list[int] = dataclasses.field(default_factory=list)
    prefix_cached_tokens: int = 0
    preemptions: int = 0

    # metrics timestamps (engine steps; -1 = not yet)
    first_scheduled_step: int = -1
    first_token_step: int = -1
    finish_step: int = -1

    def __post_init__(self):
        if not self.prompt:
            raise ValueError(f"request {self.request_id}: empty prompt")
        if not self.tokens:
            self.tokens = list(self.prompt)

    @property
    def num_prompt_tokens(self) -> int:
        return len(self.prompt)

    @property
    def num_output_tokens(self) -> int:
        return len(self.output_tokens)

    @property
    def is_finished(self) -> bool:
        return self.state is RequestState.FINISHED

    @property
    def is_terminal(self) -> bool:
        return self.state in TERMINAL_STATES

    def transition(self, new: RequestState) -> None:
        if new not in _TRANSITIONS[self.state]:
            raise ValueError(
                f"request {self.request_id}: illegal lifecycle "
                f"transition {self.state.name} -> {new.name}"
            )
        self.state = new

    def emit(self, token: int) -> bool:
        """Record one generated token; returns True if it ends the
        request (stop token or max_tokens reached).  A finishing token
        is never fed back, so it leaves ``tokens`` untouched."""
        self.output_tokens.append(int(token))
        done = (
            len(self.output_tokens) >= self.sampling.max_tokens
            or (self.sampling.stop_token is not None
                and int(token) == self.sampling.stop_token)
        )
        self.pending_token = None if done else int(token)
        return done

    def feed_pending(self) -> int:
        """Move the pending token into the KV-bearing sequence (the
        decode step is about to append its KV row)."""
        if self.pending_token is None:
            raise ValueError(
                f"request {self.request_id}: no pending token to feed"
            )
        tok = self.pending_token
        self.tokens.append(tok)
        self.pending_token = None
        return tok
