"""Typed serving errors: the resilience half of the error taxonomy.

PR 2 introduced the capacity/accounting pair
(`attention_tpu.ops.paged.OutOfPagesError` / `PageAccountingError`);
the multi-replica front end (`attention_tpu.frontend`) adds the three
failure modes a *resilient* serving layer must distinguish:

* :class:`DeadlineExceededError` — a request's TTL expired.  Raised at
  admission when the deadline is already in the past; requests that
  expire mid-flight are not raised but transitioned to the terminal
  ``TIMED_OUT`` state (the step loop must keep serving everyone else).
* :class:`ReplicaDeadError` — an operation touched a replica that has
  been killed.  The front end's retry machinery catches it and
  requeues the victim's requests elsewhere; reaching a caller means
  the retry budget could not absorb the failure.
* :class:`RequestShedError` — admission control rejected the request
  (load shedding, degradation-ladder policy, or an exhausted retry
  budget).  Stored on the shed request so callers see a typed cause,
  never a bare RuntimeError.

The snapshot/journal subsystem (PR 9) adds the durability half:

* :class:`SnapshotError` — base for anything wrong with persisted
  serving state.  Callers that want "warm if possible, cold
  otherwise" catch this one class.
* :class:`SnapshotCorruptError` — a snapshot or journal file failed
  validation (bad magic, stale version, truncated section, checksum
  mismatch).  Recovery code treats it as "this file does not count",
  never as a crash: `ReplicaHandle.restart(warm_from=...)` falls back
  to the cold `resume_request` path.
* :class:`ReplicaStateError` — a lifecycle operation was applied to a
  replica in the wrong state (e.g. `restart` on a live replica).
  Distinct from :class:`ReplicaDeadError`, which covers work routed
  *at* a dead replica.

The gray-failure work (ISSUE 10) adds the transient half:

* :class:`StepInterruptedError` — one engine step aborted before any
  state mutation (an intermittent, non-fail-stop fault).  The front
  end records it on the replica's error streak and retries next tick;
  the `ReplicaSupervisor` escalates only when the streak persists.

The global prefix tier (`attention_tpu.prefixstore`, ISSUE 17) adds
the fleet-reuse half:

* :class:`PrefixStoreCorruptError` — a content-addressed prefix
  record failed validation (bad magic, CRC mismatch, truncated
  payload).  The import path treats it exactly like
  :class:`SnapshotCorruptError` treats a bad snapshot: drop the
  entry, count it, fall back to cold prefill — wrong tokens are
  never acceptable, a re-prefill always is.
* :class:`PrefixLeaseError` — single-flight lease misuse (releasing
  a lease another request holds, acquiring over a live foreign
  lease).  Lease *expiry* is not an error — it is the deterministic
  tick-driven escape hatch when a lease holder dies mid-prefill.

All subclass RuntimeError, the `OutOfPagesError` lineage — the
ATP401 contract (attention_tpu/analysis/errors.py) extends over
``frontend/`` and ``prefixstore/`` so generic raises cannot creep
back in.
"""

from __future__ import annotations


class DeadlineExceededError(RuntimeError):
    """A request's deadline/TTL expired.

    Surfaced by `ServingEngine.add_request`/`resume_request` when the
    deadline predates the admission step; mid-flight expiry instead
    transitions the request to the terminal TIMED_OUT state."""


class ReplicaDeadError(RuntimeError):
    """An operation was routed at a killed replica.

    `ReplicaHandle.step` (and every other engine accessor on a dead
    handle) raises this; the front end's retry-with-backoff path
    catches it and requeues the in-flight requests elsewhere."""


class RequestShedError(RuntimeError):
    """Admission control rejected the request.

    Load shedding under watermark/queue pressure, the degradation
    ladder's lowest-priority cut, or a retry budget that ran dry —
    always deliberate policy, recorded on the request's ``error``
    field so clients can distinguish "shed, retry later" from a
    serving bug."""


class StepInterruptedError(RuntimeError):
    """An engine step aborted before mutating any request state.

    The gray-failure chaos injector raises this from a wrapped
    ``engine.step`` BEFORE the inner step runs, modelling a transient
    host-side fault (driver hiccup, runtime retry) that costs a
    scheduler round but corrupts nothing.  The front end notes it on
    the replica's error streak — the `ReplicaSupervisor`'s
    consecutive-typed-step-errors signal — and simply tries again next
    tick; it is never a reason to cancel or requeue work."""


class SnapshotError(RuntimeError):
    """Base class for serving-state durability failures.

    `recover_engine` and `ReplicaHandle.restart(warm_from=...)` catch
    this class: any subclass means "warm recovery unavailable, take
    the cold path", never a crash."""


class SnapshotCorruptError(SnapshotError):
    """A snapshot or journal file failed validation.

    Bad magic, unsupported version, truncated section, per-section
    CRC mismatch, or a model fingerprint that does not match the
    engine being restored.  Raised by `engine.snapshot.restore` (and
    by `recover_engine` when *no* candidate validates); a torn journal
    *tail* is tolerated silently instead — the valid prefix is used."""


class ReplicaStateError(RuntimeError):
    """A replica lifecycle operation was applied in the wrong state.

    E.g. `ReplicaHandle.restart` on a replica that is still alive:
    the caller must `kill()` first.  Kept distinct from
    :class:`ReplicaDeadError` (work routed at a *dead* replica) so
    chaos invariants can tell misuse from expected fail-stop."""


class PrefixStoreCorruptError(RuntimeError):
    """A fleet prefix-store record or store file failed validation.

    Bad magic, unsupported version, truncated section, per-section
    CRC mismatch, byte-accounting drift, or record metadata that does
    not describe its own payload.  Raised by
    `prefixstore.records.decode_record` / `prefixstore.store.load_store`;
    the engine import path catches it, bumps ``prefixstore.corrupt``,
    discards the poisoned entry, and falls back to cold prefill — a
    corrupt record may cost a re-prefill, never a wrong token."""


class PrefixLeaseError(RuntimeError):
    """Single-flight prefix lease misuse.

    Releasing a lease owned by a different request, or acquiring over
    a live lease held by another owner, is a caller bug and raises
    this.  Tick-driven lease *expiry* (the holder died mid-prefill)
    is deliberately not an error: waiters observe the expired lease,
    the next one in deterministic arrival order takes over, and the
    storm still prefills at most once per lease generation."""


class HandoffCorruptError(PrefixStoreCorruptError):
    """A prefill→decode KV-handoff payload failed validation.

    The disaggregated fleet ships a request's committed prefix pages
    from the prefill pool to its decode destination in the prefix-
    record section format (`attention_tpu.fleet.handoff`); bad magic,
    a truncated or CRC-mismatched ``pools.<s>`` section, or metadata
    that does not describe its payload raises this.  Subclasses
    :class:`PrefixStoreCorruptError` so every existing typed-error
    gate (chaos ``TYPED_ERRORS``, the import-path catch discipline)
    covers it unchanged.  The handoff path catches it, counts a
    ``handoff_fallback``, and re-admits the request WITHOUT the pages
    — the destination re-prefills, token parity holds, and the
    corruption costs compute, never a wrong token."""
