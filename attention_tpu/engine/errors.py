"""Typed serving errors: the resilience half of the error taxonomy.

PR 2 introduced the capacity/accounting pair
(`attention_tpu.ops.paged.OutOfPagesError` / `PageAccountingError`);
the multi-replica front end (`attention_tpu.frontend`) adds the three
failure modes a *resilient* serving layer must distinguish:

* :class:`DeadlineExceededError` — a request's TTL expired.  Raised at
  admission when the deadline is already in the past; requests that
  expire mid-flight are not raised but transitioned to the terminal
  ``TIMED_OUT`` state (the step loop must keep serving everyone else).
* :class:`ReplicaDeadError` — an operation touched a replica that has
  been killed.  The front end's retry machinery catches it and
  requeues the victim's requests elsewhere; reaching a caller means
  the retry budget could not absorb the failure.
* :class:`RequestShedError` — admission control rejected the request
  (load shedding, degradation-ladder policy, or an exhausted retry
  budget).  Stored on the shed request so callers see a typed cause,
  never a bare RuntimeError.

All three subclass RuntimeError, the `OutOfPagesError` lineage — the
ATP401 contract (attention_tpu/analysis/errors.py) extends over
``frontend/`` so generic raises cannot creep back in.
"""

from __future__ import annotations


class DeadlineExceededError(RuntimeError):
    """A request's deadline/TTL expired.

    Surfaced by `ServingEngine.add_request`/`resume_request` when the
    deadline predates the admission step; mid-flight expiry instead
    transitions the request to the terminal TIMED_OUT state."""


class ReplicaDeadError(RuntimeError):
    """An operation was routed at a killed replica.

    `ReplicaHandle.step` (and every other engine accessor on a dead
    handle) raises this; the front end's retry-with-backoff path
    catches it and requeues the in-flight requests elsewhere."""


class RequestShedError(RuntimeError):
    """Admission control rejected the request.

    Load shedding under watermark/queue pressure, the degradation
    ladder's lowest-priority cut, or a retry budget that ran dry —
    always deliberate policy, recorded on the request's ``error``
    field so clients can distinguish "shed, retry later" from a
    serving bug."""
