"""Iteration-level (continuous-batching) scheduler.

Each engine step the scheduler composes ONE batch out of whatever work
exists right now — decode tokens for running requests interleaved with
chunked-prefill slices of admitted requests (Orca-style iteration-level
scheduling: requests join and leave the batch between *tokens*, never
waiting for a whole batch to drain).  Policy, deterministically:

  * FCFS by ``(arrival, seq)`` everywhere: decode order, prefill
    continuation, admission, and the requeue point after preemption.
  * Token budget: a step schedules at most ``token_budget`` real
    tokens (decode = 1 each, prefill = chunk length), so one giant
    prompt cannot starve decode latency.
  * Decode first, then prefill: decode rows are cheap and latency-
    critical; leftover budget admits/advances prefills.
  * Page pressure: decode appends that cannot get a page trigger
    preemption-by-recompute of the YOUNGEST running request (its pages
    are freed, its computed-token count resets, it re-queues at its
    original FCFS position and re-prefills on readmission — generated
    tokens are kept and never resampled).  Admissions that would
    breach the allocator watermark simply wait.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from attention_tpu import obs
from attention_tpu.engine.allocator import BlockAllocator, pages_for_tokens
from attention_tpu.engine.request import Request, RequestState
from attention_tpu.ops.paged import OutOfPagesError

_ADMITTED = obs.counter("engine.scheduler.admissions",
                        "requests admitted into the running set")
_PREEMPTED = obs.counter("engine.scheduler.preemptions",
                         "preemption-by-recompute events")
_ADMIT_WAITS = obs.counter(
    "engine.scheduler.admit_waits",
    "admissions deferred by the allocator watermark")


@dataclasses.dataclass
class PackedBatch:
    """One step's work flattened onto a single padded token axis — the
    host-side image of `ops.ragged_paged.RaggedPagedStep`.

    ``tokens`` (1, width) int32 feeds the model in one launch;
    ``token_slot``/``token_pos`` (width,) map each packed token to its
    owning request slot (-1 = pad) and absolute cache position;
    ``kv_lens`` (slots,) / ``cu_q_lens`` (slots+1,) / ``tables``
    (slots, table_width) / ``distribution`` (2,) are the kernel's
    scalar-prefetch operands.  Decode slots come first (the
    ``distribution`` contract); ``num_real`` real tokens occupy the
    packed prefix, the remaining ``width - num_real`` are pad."""

    tokens: np.ndarray
    token_slot: np.ndarray
    token_pos: np.ndarray
    kv_lens: np.ndarray
    cu_q_lens: np.ndarray
    tables: np.ndarray
    distribution: np.ndarray
    width: int
    num_real: int


@dataclasses.dataclass
class ScheduledStep:
    """One step's batch composition (what the engine will lower onto
    kernel calls) plus the events the metrics layer records."""

    step: int
    decode: list[Request] = dataclasses.field(default_factory=list)
    # (request, real tokens of this chunk) — the two-call engine pads
    # every chunk to the configured prefill_chunk for shape stability;
    # the ragged engine packs the real tokens via `pack`
    prefill: list[tuple[Request, int]] = dataclasses.field(
        default_factory=list
    )
    preempted: list[Request] = dataclasses.field(default_factory=list)
    admitted: list[Request] = dataclasses.field(default_factory=list)

    @property
    def num_decode_tokens(self) -> int:
        return len(self.decode)

    @property
    def num_prefill_tokens(self) -> int:
        return sum(n for _, n in self.prefill)

    @property
    def is_empty(self) -> bool:
        return not self.decode and not self.prefill

    def pack(self, *, width: int, slots: int, table_width: int,
             staged_rows: dict | None = None) -> PackedBatch:
        """Flatten this step onto one padded token axis, decode slots
        first then prefill chunks, each request's tokens contiguous.

        CONSUMES pending decode tokens (`Request.feed_pending`) — call
        at most once per step, from the engine's dispatch path.

        ``staged_rows`` (optional ``{request_id: (num_pages, row)}``)
        reuses page-table rows staged by the async loop while the
        previous step ran on device; a row is taken only when the
        request's page count is unchanged, so the packed operands are
        bit-identical to a cold rebuild."""
        items = [(r, 1) for r in self.decode] + list(self.prefill)
        total = self.num_decode_tokens + self.num_prefill_tokens
        if len(items) > slots:
            raise ValueError(
                f"step has {len(items)} requests but only {slots} slots"
            )
        if total > width:
            raise ValueError(
                f"step has {total} tokens but packed width is {width}"
            )
        tokens = np.zeros((1, width), np.int32)
        token_slot = np.full((width,), -1, np.int32)
        token_pos = np.zeros((width,), np.int32)
        kv_lens = np.zeros((slots,), np.int32)
        cu = np.zeros((slots + 1,), np.int32)
        tables = np.full((slots, table_width), -1, np.int32)
        num_decode = len(self.decode)
        off = 0
        for s, (req, n) in enumerate(items):
            c = req.computed_tokens
            if s < num_decode:
                tokens[0, off] = req.feed_pending()
            else:
                tokens[0, off:off + n] = req.tokens[c:c + n]
            token_slot[off:off + n] = s
            token_pos[off:off + n] = np.arange(c, c + n)
            kv_lens[s] = c
            staged = (staged_rows or {}).get(req.request_id)
            if staged is not None and staged[0] == len(req.pages):
                tables[s] = staged[1]
            else:
                tables[s, :len(req.pages)] = req.pages
            off += n
            cu[s + 1] = off
        cu[len(items) + 1:] = off
        return PackedBatch(
            tokens=tokens, token_slot=token_slot, token_pos=token_pos,
            kv_lens=kv_lens, cu_q_lens=cu, tables=tables,
            distribution=np.asarray([num_decode, len(items)], np.int32),
            width=width, num_real=total,
        )


class Scheduler:
    def __init__(self, allocator: BlockAllocator, *,
                 max_decode_batch: int, max_prefill_rows: int,
                 prefill_chunk: int, token_budget: int):
        if min(max_decode_batch, max_prefill_rows, prefill_chunk,
               token_budget) < 1:
            raise ValueError("scheduler limits must all be >= 1")
        self.allocator = allocator
        self.max_decode_batch = max_decode_batch
        self.max_prefill_rows = max_prefill_rows
        self.prefill_chunk = prefill_chunk
        self.token_budget = token_budget
        self.waiting: list[Request] = []   # kept FCFS-sorted
        self.running: list[Request] = []   # admission order (== FCFS)
        self.num_preemptions = 0
        # degradation-ladder hook: False turns admission-path prefix-
        # cache lookups off (committed pages stay resident for later
        # recovery, but new admissions recompute instead of increffing
        # shared pages — cheaper page churn under sustained pressure)
        self.prefix_admission = True

    # -- queue plumbing ---------------------------------------------------

    def _fcfs(self, req: Request):
        return (req.arrival, req.seq)

    def add(self, req: Request) -> None:
        self.waiting.append(req)
        self.waiting.sort(key=self._fcfs)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def remove_finished(self, req: Request) -> None:
        self.running.remove(req)

    def _preempt(self, victim: Request, sched: ScheduledStep) -> None:
        """Preemption-by-recompute: release every page, forget computed
        KV, requeue at the victim's original FCFS position.  Emitted
        tokens and the pending token survive — readmission re-prefills
        ``victim.tokens`` and resumes decoding without resampling."""
        self.running.remove(victim)
        if victim in sched.decode:
            sched.decode.remove(victim)
        sched.prefill = [(r, n) for r, n in sched.prefill if r is not victim]
        if victim.pages:
            self.allocator.free(victim.pages)
        victim.pages = []
        victim.computed_tokens = 0
        victim.prefix_cached_tokens = 0
        victim.preemptions += 1
        victim.transition(RequestState.PREEMPTED)
        self.num_preemptions += 1
        _PREEMPTED.inc()
        sched.preempted.append(victim)
        self.waiting.append(victim)
        self.waiting.sort(key=self._fcfs)

    def _preempt_for(self, req: Request, sched: ScheduledStep) -> bool:
        """Free pages for ``req``'s decode append by preempting the
        youngest running request.  Returns True if ``req`` itself was
        the victim (caller skips it this step)."""
        victim = max(self.running, key=self._fcfs)
        if victim is req and len(self.running) == 1:
            # preempting the sole running request to serve itself can
            # never converge — the pool is simply too small for it
            raise OutOfPagesError(
                f"request {req.request_id} needs a page but is the only "
                "running request and nothing is evictable: the pool "
                "cannot hold it"
            )
        self._preempt(victim, sched)
        return victim is req

    # -- step composition -------------------------------------------------

    def _ensure_pages(self, req: Request, cover_tokens: int, *,
                      for_decode: bool) -> None:
        need = pages_for_tokens(cover_tokens, self.allocator.page_size) \
            - len(req.pages)
        if need > 0:
            req.pages.extend(
                self.allocator.allocate(need, for_decode=for_decode)
            )

    def schedule(self, step: int) -> ScheduledStep:
        sched = ScheduledStep(step=step)
        budget = self.token_budget

        # 1) decode: every DECODING request in FCFS order, up to the
        # batch width; each needs page coverage for one appended row
        for req in sorted(
            [r for r in self.running
             if r.state is RequestState.DECODING], key=self._fcfs
        ):
            if len(sched.decode) >= self.max_decode_batch or budget < 1:
                break
            if req.state is not RequestState.DECODING:
                continue  # preempted by an earlier candidate this step
            while True:
                try:
                    self._ensure_pages(req, len(req.tokens) + 1,
                                       for_decode=True)
                    break
                except OutOfPagesError:
                    if self._preempt_for(req, sched):
                        break  # req preempted itself; skip this step
            if req.state is not RequestState.DECODING:
                continue
            sched.decode.append(req)
            budget -= 1

        # 2) prefill continuation: requests already mid-prompt advance
        # before anyone new is admitted (FCFS).  A running request's
        # chunk may drain the watermark reserve and, failing that,
        # preempt the youngest runner — it already holds pages and
        # queue position; stalling it wastes both.
        for req in sorted(
            [r for r in self.running
             if r.state is RequestState.PREFILLING], key=self._fcfs
        ):
            if len(sched.prefill) >= self.max_prefill_rows or budget < 1:
                break
            if req.state is not RequestState.PREFILLING:
                continue  # preempted by an earlier candidate this step
            padded_end = req.computed_tokens + self.prefill_chunk
            while True:
                try:
                    self._ensure_pages(req, padded_end, for_decode=True)
                    break
                except OutOfPagesError:
                    if self._preempt_for(req, sched):
                        break
            if req.state is not RequestState.PREFILLING:
                continue
            self._schedule_chunk(req, sched, budget)
            if sched.prefill and sched.prefill[-1][0] is req:
                budget -= sched.prefill[-1][1]

        # 3) admission: FCFS over due arrivals, watermark-guarded
        while (self.waiting
               and self.waiting[0].arrival <= step
               and len(sched.prefill) < self.max_prefill_rows
               and budget >= 1):
            with obs.span("scheduler.admit"):
                req = self.waiting[0]
                if req.pages:  # defensive: queued requests hold nothing
                    self.allocator.free(req.pages)
                    req.pages = []
                pages = (self.allocator.lookup_prefix(req.tokens,
                                                      now=step)
                         if self.prefix_admission else [])
                try:
                    req.pages = pages
                    req.computed_tokens = (
                        len(pages) * self.allocator.page_size)
                    req.prefix_cached_tokens = req.computed_tokens
                    before = len(sched.prefill)
                    self._schedule_chunk(req, sched, budget)
                    if len(sched.prefill) == before:
                        raise OutOfPagesError(
                            "admission chunk not scheduled")
                except OutOfPagesError:
                    # watermark refusal: return the prefix references
                    # and wait — running requests drain the queue
                    # eventually
                    if pages:
                        self.allocator.free(pages)
                        self.allocator.prefix_hits -= 1
                        self.allocator.prefix_hit_tokens -= (
                            len(pages) * self.allocator.page_size
                        )
                    req.pages = []
                    req.computed_tokens = 0
                    req.prefix_cached_tokens = 0
                    _ADMIT_WAITS.inc()
                    break
                self.waiting.pop(0)
                self.running.append(req)
                req.transition(RequestState.PREFILLING)
                if req.first_scheduled_step < 0:
                    req.first_scheduled_step = step
                sched.admitted.append(req)
                _ADMITTED.inc()
                budget -= sched.prefill[-1][1]

        return sched

    def _schedule_chunk(self, req: Request, sched: ScheduledStep,
                        budget: int) -> None:
        """Add one prefill chunk for ``req`` if pages allow.  The chunk
        is padded to ``prefill_chunk`` rows in the kernel call, so page
        coverage must span the padded end (a pad row crossing into an
        unclaimed page would NaN-poison the whole row, real tokens
        included)."""
        remaining = len(req.tokens) - req.computed_tokens
        real = min(self.prefill_chunk, remaining, budget)
        if real < 1:
            return
        padded_end = req.computed_tokens + self.prefill_chunk
        self._ensure_pages(req, padded_end, for_decode=False)
        sched.prefill.append((req, real))
