"""Binary testcase format: reader, writer, generator, verifier.

The on-disk contract is byte-compatible with the reference's frozen harness
(`attention.c:84-162`, `attention-mpi.c:417-495`):

  * header: 4 little-endian int32 — m, n, dk, dv
  * m*dk float64 — Q
  * n*dk float64 — K
  * n*dv float64 — V
  * m*dv float64 — expected output (appended after V; the verifier seeks
    past the inputs to reach it, `attention.c:139-140`)

Verification is elementwise ``|result - expected| <= 0.02``
(`attention.c:143`).  The reference's NaN check has a known bug — it tests
``result[base + 1]`` for every column instead of ``result[base + j]``
(`attention.c:150`) — which we fix here: every element is NaN-checked.

The reference ships no generator (testcase files come from the course
grader); ``generate_testcase`` fills that gap, producing files any
implementation — including the reference C binaries — can consume.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Sequence

import numpy as np

from attention_tpu.core.oracle import attention_oracle

HEADER_DTYPE = np.dtype("<i4")
DATA_DTYPE = np.dtype("<f8")
VERIFY_THRESHOLD = 0.02  # attention.c:143


@dataclasses.dataclass
class TestCase:
    q: np.ndarray  # (m, dk) float64
    k: np.ndarray  # (n, dk) float64
    v: np.ndarray  # (n, dv) float64
    expected: np.ndarray | None = None  # (m, dv) float64

    @property
    def dims(self) -> tuple[int, int, int, int]:
        m, dk = self.q.shape
        n, dv = self.v.shape
        return m, n, dk, dv

    def nbytes(self) -> int:
        total = 4 * HEADER_DTYPE.itemsize
        for arr in (self.q, self.k, self.v, self.expected):
            if arr is not None:
                total += arr.size * DATA_DTYPE.itemsize
        return total


def write_testcase(path: str | os.PathLike, case: TestCase) -> None:
    """Serialize a testcase in the reference's binary layout."""
    m, n, dk, dv = case.dims
    if case.k.shape != (n, dk):
        raise ValueError(f"K shape {case.k.shape} != ({n}, {dk})")
    if case.expected is not None and case.expected.shape != (m, dv):
        raise ValueError(f"expected shape {case.expected.shape} != ({m}, {dv})")
    with open(path, "wb") as f:
        np.array([m, n, dk, dv], dtype=HEADER_DTYPE).tofile(f)
        case.q.astype(DATA_DTYPE).tofile(f)
        case.k.astype(DATA_DTYPE).tofile(f)
        case.v.astype(DATA_DTYPE).tofile(f)
        if case.expected is not None:
            case.expected.astype(DATA_DTYPE).tofile(f)


def read_testcase(path: str | os.PathLike, *, with_expected: bool = True) -> TestCase:
    """Load a testcase; mirrors `read_matrices` (`attention.c:100-121`)."""
    with open(path, "rb") as f:
        header = np.fromfile(f, dtype=HEADER_DTYPE, count=4)
        if header.size != 4:
            raise ValueError(f"invalid testcase header in {path}")
        m, n, dk, dv = (int(x) for x in header)
        if min(m, n, dk, dv) <= 0:
            raise ValueError(f"invalid dims {m, n, dk, dv} in {path}")
        q = np.fromfile(f, dtype=DATA_DTYPE, count=m * dk)
        k = np.fromfile(f, dtype=DATA_DTYPE, count=n * dk)
        v = np.fromfile(f, dtype=DATA_DTYPE, count=n * dv)
        if q.size != m * dk or k.size != n * dk or v.size != n * dv:
            raise ValueError(f"truncated testcase data in {path}")
        expected = None
        if with_expected:
            exp = np.fromfile(f, dtype=DATA_DTYPE, count=m * dv)
            if exp.size == m * dv:
                expected = exp.reshape(m, dv)
    return TestCase(
        q=q.reshape(m, dk), k=k.reshape(n, dk), v=v.reshape(n, dv), expected=expected
    )


@dataclasses.dataclass(frozen=True)
class VerifyScan:
    """Full-scan verification statistics (the opt-in mode the chaos
    fuzzer's tolerance ledger consumes, also surfaced by
    ``cli run --stats``): not just the first mismatch, but how wrong
    and how widespread."""

    ok: bool
    threshold: float
    max_abs_err: float   # over finite elements (0.0 if none compared)
    mismatches: int      # elements over threshold OR non-finite
    nonfinite: int       # NaN/Inf result elements
    total: int
    message: str         # the classic first-mismatch diagnostic

    def stats_line(self) -> str:
        return (f"stats: max_abs_err={self.max_abs_err:.6g} "
                f"mismatches={self.mismatches}/{self.total} "
                f"nonfinite={self.nonfinite} "
                f"threshold={self.threshold:g}")


def verify_scan(
    expected: np.ndarray,
    result: np.ndarray,
    *,
    threshold: float = VERIFY_THRESHOLD,
) -> VerifyScan:
    """Full-scan variant of :func:`verify`: same pass/fail semantics,
    plus max-abs-error and mismatch/non-finite counts over EVERY
    element.  A shape mismatch reports every element as mismatched."""
    expected = np.asarray(expected, dtype=np.float64)
    result = np.asarray(result, dtype=np.float64)
    if expected.shape != result.shape:
        return VerifyScan(
            ok=False, threshold=threshold, max_abs_err=float("inf"),
            mismatches=max(expected.size, result.size), nonfinite=0,
            total=max(expected.size, result.size),
            message=(f"shape mismatch: expected {expected.shape}, "
                     f"got {result.shape}"),
        )
    finite = np.isfinite(result)
    err = np.abs(result - expected)
    bad = ~finite | (err > threshold)
    max_err = float(err[finite].max()) if finite.any() else 0.0
    if not bad.any():
        return VerifyScan(ok=True, threshold=threshold,
                          max_abs_err=max_err, mismatches=0,
                          nonfinite=0, total=result.size,
                          message="Correct!")
    idx = np.unravel_index(np.argmax(bad), bad.shape)
    loc = "][".join(str(i) for i in idx)
    return VerifyScan(
        ok=False, threshold=threshold, max_abs_err=max_err,
        mismatches=int(bad.sum()), nonfinite=int((~finite).sum()),
        total=result.size,
        message=(f"Expect result[{loc}] to be {expected[idx]:f}, "
                 f"but it is {result[idx]:f}"),
    )


def verify(
    expected: np.ndarray,
    result: np.ndarray,
    *,
    threshold: float = VERIFY_THRESHOLD,
    full_scan: bool = False,
) -> tuple[bool, str]:
    """Elementwise tolerance check, mirroring `verify` (`attention.c:123-162`).

    Returns (ok, message).  On failure the message pinpoints the first bad
    element with expected/actual values, matching the reference's diagnostic
    print (`attention.c:151`).  Unlike the reference, every element is
    NaN-checked (the reference only checks column 1 of each row,
    `attention.c:150` — a known quirk we fix).

    ``full_scan=True`` appends max-abs-error / mismatch-count statistics
    to the failure message (see :func:`verify_scan` for the structured
    form); the default message stays byte-identical to the reference's.
    """
    if full_scan:
        scan = verify_scan(expected, result, threshold=threshold)
        msg = scan.message if scan.ok \
            else f"{scan.message} [{scan.stats_line()}]"
        return scan.ok, msg
    expected = np.asarray(expected, dtype=np.float64)
    result = np.asarray(result, dtype=np.float64)
    if expected.shape != result.shape:
        return False, f"shape mismatch: expected {expected.shape}, got {result.shape}"
    bad = ~np.isfinite(result) | (np.abs(result - expected) > threshold)
    if not bad.any():
        return True, "Correct!"
    idx = np.unravel_index(np.argmax(bad), bad.shape)
    loc = "][".join(str(i) for i in idx)
    return (
        False,
        f"Expect result[{loc}] to be {expected[idx]:f}, but it is {result[idx]:f}",
    )


def verify_file(
    path: str | os.PathLike,
    result: np.ndarray,
    *,
    threshold: float = VERIFY_THRESHOLD,
) -> tuple[bool, str]:
    """Verify a result against the expected output stored in a testcase file."""
    case = read_testcase(path, with_expected=True)
    if case.expected is None:
        return False, f"no expected output appended to {path}"
    return verify(case.expected, result, threshold=threshold)


def generate_testcase(
    m: int,
    n: int,
    dk: int,
    dv: int,
    *,
    seed: int = 0,
    q_scale: float = 1.0,
    compute_expected: bool = True,
) -> TestCase:
    """Generate a random testcase with the oracle's expected output.

    Inputs are standard normal scaled by ``q_scale`` — with the 1/sqrt(dk)
    score scaling this yields well-conditioned softmax distributions at any
    of the reference's scales (README.md:95-102 `simple`..`scale5`).
    """
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((m, dk)) * q_scale
    k = rng.standard_normal((n, dk)) * q_scale
    v = rng.standard_normal((n, dv))
    expected = attention_oracle(q, k, v) if compute_expected else None
    return TestCase(q=q, k=k, v=v, expected=expected)


# Named suite mirroring the reference's testcase ladder (README.md:95-102).
# The reference's actual file sizes are unpublished; these are chosen so
# `simple` is instant and `scale5` stresses a single chip, with the same
# monotone growth in m/n.
SUITE: dict[str, tuple[int, int, int, int]] = {
    "simple": (128, 128, 32, 32),
    "scale1": (1024, 1024, 64, 64),
    "scale2": (2048, 2048, 64, 64),
    "scale3": (4096, 4096, 128, 128),
    "scale4": (8192, 8192, 128, 128),
    "scale5": (16384, 16384, 128, 128),
}


def generate_suite(
    out_dir: str | os.PathLike,
    names: Sequence[str] | None = None,
    *,
    seed: int = 0,
) -> list[str]:
    """Write the named testcase suite to ``out_dir``; returns file paths."""
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for name in names or SUITE:
        m, n, dk, dv = SUITE[name]
        case = generate_testcase(m, n, dk, dv, seed=seed)
        path = os.path.join(out_dir, f"{name}.bin")
        write_testcase(path, case)
        paths.append(path)
    return paths
