from attention_tpu.core.oracle import attention_oracle  # noqa: F401
from attention_tpu.core.testcase import (  # noqa: F401
    TestCase,
    generate_testcase,
    read_testcase,
    verify,
    write_testcase,
)
