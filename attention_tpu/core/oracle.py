"""Serial fp64 correctness oracle.

Mirrors the semantics of the reference's serial implementation
(`attention.c:20-75`): per query row, (1) scores = Q[i]·K^T * 1/sqrt(dk),
(2) numerically-stable 3-pass softmax (max-subtract, exp-sum, normalize),
(3) result[i] = scores · V.  All math in float64.

This is the ground truth every other backend is verified against, exactly
as `attention.c` is the oracle for `attention-mpi.c` (reference
`README.md:78`).  The implementation here is vectorized NumPy rather than
scalar loops — same math, fp64 throughout, so any elementwise difference
from the C version is far below the ±0.02 verification tolerance
(`attention.c:143`).
"""

from __future__ import annotations

import numpy as np


def attention_oracle(
    q: np.ndarray,
    k: np.ndarray,
    v: np.ndarray,
    *,
    scale: float | None = None,
    row_block: int = 1024,
) -> np.ndarray:
    """Compute softmax(Q K^T / sqrt(dk)) V in float64.

    Args:
      q: (m, dk) queries.
      k: (n, dk) keys.
      v: (n, dv) values.
      scale: score scale; defaults to 1/sqrt(dk) (`attention.c:23`).
      row_block: queries processed per block to bound the (block, n)
        score scratch, the analog of the reference's per-row O(n)
        scratch buffer (`attention.c:26`).

    Returns:
      (m, dv) float64 attention output.
    """
    q = np.asarray(q, dtype=np.float64)
    k = np.asarray(k, dtype=np.float64)
    v = np.asarray(v, dtype=np.float64)
    m, dk = q.shape
    n, dk2 = k.shape
    n2, dv = v.shape
    if dk != dk2 or n != n2:
        raise ValueError(f"shape mismatch: Q{q.shape} K{k.shape} V{v.shape}")
    if scale is None:
        scale = 1.0 / np.sqrt(float(dk))

    out = np.empty((m, dv), dtype=np.float64)
    for start in range(0, m, row_block):
        stop = min(start + row_block, m)
        scores = (q[start:stop] @ k.T) * scale
        scores -= scores.max(axis=-1, keepdims=True)
        np.exp(scores, out=scores)
        scores /= scores.sum(axis=-1, keepdims=True)
        out[start:stop] = scores @ v
    return out


def attention_oracle_mha(
    q: np.ndarray,
    k: np.ndarray,
    v: np.ndarray,
    *,
    scale: float | None = None,
) -> np.ndarray:
    """Multi-head / grouped-query oracle.

    q: (..., hq, m, d), k/v: (..., hkv, n, d) with hq a multiple of hkv
    (GQA: each group of hq/hkv query heads attends to one shared KV head).
    The reference is single-head (`attention.c` has no head dimension);
    this extends the same fp64 math to the multi-head configs in
    BASELINE.json (config 5).
    """
    q = np.asarray(q, dtype=np.float64)
    k = np.asarray(k, dtype=np.float64)
    v = np.asarray(v, dtype=np.float64)
    hq, m, d = q.shape[-3:]
    hkv, n, _ = k.shape[-3:]
    if hq % hkv != 0:
        raise ValueError(f"hq={hq} not a multiple of hkv={hkv}")
    group = hq // hkv
    if scale is None:
        scale = 1.0 / np.sqrt(float(d))
    kx = np.repeat(k, group, axis=-3)
    vx = np.repeat(v, group, axis=-3)
    scores = np.einsum("...md,...nd->...mn", q, kx) * scale
    scores -= scores.max(axis=-1, keepdims=True)
    np.exp(scores, out=scores)
    scores /= scores.sum(axis=-1, keepdims=True)
    return np.einsum("...mn,...nd->...md", scores, vx)
