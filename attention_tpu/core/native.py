"""ctypes bridge to the native C oracle and testcase I/O (csrc/).

The reference keeps a compiled serial C implementation as its bit-level
oracle and CPU baseline (`attention.c`); this module provides the same
natively-compiled role for this framework.  The library is built on first
use with the system C compiler and cached next to the sources; every
entry point falls back to the NumPy implementations in
:mod:`attention_tpu.core` if no compiler is available, so the Python
framework never hard-depends on the native path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")
_LIB_NAME = "libattn_serial.so"
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_build_error: str | None = None


def _compile(srcs: list[str], out_path: str, *, shared: bool) -> bool:
    """Try cc/gcc/clang in order; build to a pid-private temp and
    atomically rename.  Returns False (and cleans the temp) when no
    compiler works, a compiler hangs, or it errors."""
    tmp_path = f"{out_path}.{os.getpid()}.tmp"
    flags = ["-O3", "-march=native"]
    if shared:
        flags += ["-shared", "-fPIC"]
    for cc in ("cc", "gcc", "clang"):
        try:
            subprocess.run(
                [cc, *flags, *srcs, "-o", tmp_path, "-lm"],
                check=True, capture_output=True, timeout=120,
            )
            os.replace(tmp_path, out_path)
            return True
        except (FileNotFoundError, subprocess.CalledProcessError,
                subprocess.TimeoutExpired):
            if os.path.exists(tmp_path):
                try:
                    os.remove(tmp_path)
                except OSError:
                    pass
            continue
    return False


def _build_and_load() -> ctypes.CDLL | None:
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        csrc = os.path.abspath(_CSRC)
        src = os.path.join(csrc, "attention_serial.c")
        lib_path = os.path.join(csrc, _LIB_NAME)
        try:
            if not os.path.exists(lib_path) or os.path.getmtime(
                lib_path
            ) < os.path.getmtime(src):
                if not _compile([src], lib_path, shared=True):
                    _build_error = "no working C compiler found"
                    return None
            lib = ctypes.CDLL(lib_path)
        except OSError as e:  # load failure / missing sources
            _build_error = str(e)
            return None

        i64 = ctypes.c_int64
        dptr = np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS")
        lib.attn_serial.argtypes = [
            dptr, dptr, dptr, dptr, i64, i64, i64, i64, ctypes.c_double,
        ]
        lib.attn_serial.restype = None
        lib.attn_verify.argtypes = [dptr, dptr, i64, ctypes.c_double]
        lib.attn_verify.restype = i64
        lib.attn_read_testcase.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.attn_read_testcase.restype = ctypes.c_int
        _lib = lib
        return _lib


def native_available() -> bool:
    return _build_and_load() is not None


def attention_native(
    q: np.ndarray, k: np.ndarray, v: np.ndarray, *, scale: float | None = None
) -> np.ndarray:
    """fp64 serial attention through the compiled C oracle.

    Falls back to the NumPy oracle when the native library is unavailable.
    """
    lib = _build_and_load()
    q = np.ascontiguousarray(q, dtype=np.float64)
    k = np.ascontiguousarray(k, dtype=np.float64)
    v = np.ascontiguousarray(v, dtype=np.float64)
    if lib is None:
        from attention_tpu.core.oracle import attention_oracle

        return attention_oracle(q, k, v, scale=scale)
    m, dk = q.shape
    n, dv = v.shape
    if k.shape != (n, dk):
        raise ValueError(f"shape mismatch: Q{q.shape} K{k.shape} V{v.shape}")
    out = np.empty((m, dv), dtype=np.float64)
    lib.attn_serial(q, k, v, out, m, n, dk, dv, -1.0 if scale is None else scale)
    return out


def verify_native(
    result: np.ndarray, expected: np.ndarray, *, threshold: float = 0.02
) -> int:
    """First failing flat index, or -1 if within tolerance everywhere."""
    lib = _build_and_load()
    result = np.ascontiguousarray(result, dtype=np.float64)
    expected = np.ascontiguousarray(expected, dtype=np.float64)
    if result.shape != expected.shape:
        raise ValueError(f"shape mismatch {result.shape} vs {expected.shape}")
    if lib is None:
        bad = ~np.isfinite(result) | (np.abs(result - expected) > threshold)
        flat = np.flatnonzero(bad)
        return int(flat[0]) if flat.size else -1
    return int(lib.attn_verify(result.ravel(), expected.ravel(),
                               result.size, threshold))


def read_testcase_native(path: str):
    """Bulk-load a testcase through the native reader.

    Returns an ``attention_tpu.core.testcase.TestCase``; falls back to the
    NumPy reader without a native library.
    """
    from attention_tpu.core.testcase import TestCase, read_testcase

    lib = _build_and_load()
    if lib is None:
        return read_testcase(path)
    dims = np.zeros(4, dtype=np.int32)
    # first pass: header only, to size the buffers
    rc = lib.attn_read_testcase(path.encode(), dims, None, None, None, None)
    if rc == -1:
        raise FileNotFoundError(path)
    if rc in (-2, -3):
        raise ValueError(f"invalid testcase data in {path} (rc={rc})")
    m, n, dk, dv = (int(x) for x in dims)
    q = np.empty((m, dk))
    k = np.empty((n, dk))
    v = np.empty((n, dv))
    expected = np.empty((m, dv))
    rc = lib.attn_read_testcase(
        path.encode(), dims,
        q.ctypes.data_as(ctypes.c_void_p),
        k.ctypes.data_as(ctypes.c_void_p),
        v.ctypes.data_as(ctypes.c_void_p),
        expected.ctypes.data_as(ctypes.c_void_p),
    )
    if rc == -4:
        return TestCase(q=q, k=k, v=v, expected=None)
    if rc != 0:
        raise ValueError(f"invalid testcase data in {path} (rc={rc})")
    return TestCase(q=q, k=k, v=v, expected=expected)


_CLI_NAME = "attention_serial_cli"


def native_cli_path() -> str | None:
    """Build (if needed) and return the standalone native harness binary
    (`csrc/attention_main.c` — the reference's `./attention <case.bin>`
    CLI contract).  None when sources or a working C compiler are
    unavailable."""
    csrc = os.path.abspath(_CSRC)
    src_main = os.path.join(csrc, "attention_main.c")
    src_lib = os.path.join(csrc, "attention_serial.c")
    out = os.path.join(csrc, _CLI_NAME)
    try:
        newest = max(os.path.getmtime(src_main), os.path.getmtime(src_lib))
    except OSError:
        return None
    if os.path.exists(out) and os.path.getmtime(out) >= newest:
        return out
    return out if _compile([src_main, src_lib], out, shared=False) else None
